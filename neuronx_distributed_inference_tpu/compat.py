"""Guarded compatibility shims for older jax releases.

The library targets current jax (``jax.shard_map``, ``jax.sharding.set_mesh``
/ ``get_abstract_mesh``, ``jax_num_cpu_devices``); some deployment images pin
an older jax where those live elsewhere or do not exist. Every shim is
hasattr/except-guarded — on a current jax this module is a no-op — and
:func:`ensure_jax_compat` runs once at package import so CLI subprocesses
(``inference_demo``, ``bench.py``) get the same surface the test conftest
provides.
"""

from __future__ import annotations

import os

import jax

__all__ = ["ensure_jax_compat", "force_cpu_devices"]


def ensure_jax_compat() -> None:
    """Alias new-jax entry points onto an older jax. Idempotent."""
    if not hasattr(jax.sharding, "set_mesh"):
        # older jax: Mesh is itself a context manager that activates the
        # mesh for bare-PartitionSpec sharding constraints
        jax.sharding.set_mesh = lambda mesh: mesh
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        from jax._src import mesh as _mesh_lib

        def _get_abstract_mesh():
            m = _mesh_lib.thread_resources.env.physical_mesh
            return None if m.empty else m

        jax.sharding.get_abstract_mesh = _get_abstract_mesh
    if not hasattr(jax, "shard_map"):
        # older jax: shard_map lives in jax.experimental and spells the
        # replication-check kwarg check_rep rather than check_vma
        from jax.experimental.shard_map import shard_map as _shard_map

        def _shard_map_compat(f, **kw):
            if "check_vma" in kw:
                kw["check_rep"] = kw.pop("check_vma")
            return _shard_map(f, **kw)

        jax.shard_map = _shard_map_compat


def force_cpu_devices(n: int = 8) -> None:
    """Point jax at ``n`` virtual CPU devices (call before backend init)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # older jax: the XLA_FLAGS fallback above provides the devices
        pass
    except RuntimeError:
        pass  # backend already initialized; nothing more we can do
