"""Closed-loop degradation controller: ACT on SLO burn, reversibly.

PR 13's SLO plane (telemetry/slo.py) computes per-tenant multiwindow
burn rates and a hint (``shed_speculation`` / ``tighten_admission``) —
but nothing consumed it. :class:`DegradationController` is the actuator:
attached via ``ServingEngine(degradation=...)`` (which requires an
``slo=`` tracker), it is consulted once per scheduling pass and drives
three reversible actions off :meth:`~...telemetry.slo.SLOTracker.
burn_index`:

  * ``shed_speculation`` — a decode-side signal (ttft/tpot) burns in
    BOTH windows: the adapter's draft windows clamp to width 1
    (``PagedEngineAdapter.set_speculation_shed`` — no draft dispatches,
    per-sequence proposer state dropped through the ``_active_proposer``
    release path). Greedy token streams are bit-identical to an
    undegraded run; only the dispatch count changes (pinned).
  * ``tighten_admission`` — queue wait burns: the tenant's EFFECTIVE
    WFQ weight is scaled down (``MultiTenantQueue.set_weight_scale``)
    so new admissions defer to tenants still inside their target; the
    starvation bound keeps the tenant alive.
  * ``drop_ragged`` (opt-in, ``drop_ragged=True``) — decode-side burn
    additionally drops the ragged unified dispatch back to the
    two-phase path (``set_ragged_shed``), trading dispatch fusion for
    the smaller, older graphs.
  * ``shed_adapters`` (opt-in, ``shed_adapters=True``) — decode-side
    burn additionally admits NEW LoRA-tagged requests as base-model
    rows (``set_adapter_shed`` — no pool acquires, zero swap H2D
    traffic while burning; running rows finish under their pinned
    adapter, shed streams' meta annotated ``lora_shed=True``).

Every action is **hysteresis-guarded**: it enters when the tenant's
multiwindow burn (min of short/long — both must burn) crosses
``enter_burn``, and exits only once the burn falls below ``exit_burn``
AND the action has been held for ``min_hold_s`` — so a burn rate
oscillating around one threshold cannot flap the actuator. Transitions
land on the flight recorder (``degrade.enter`` / ``degrade.exit``) and
the ``nxdi_degraded{tenant,action}`` gauge (1 while active).

The controller never touches device state directly and never reorders
or changes tokens — every action only changes dispatch shape or
admission ORDER, so shedding and restoring mid-serve keeps every greedy
stream bit-identical (tests/test_resilience_control.py pins this).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

from .errors import ConfigurationError

__all__ = ["DEGRADE_ACTIONS", "DegradationController"]

#: Stable action names (label values of ``nxdi_degraded`` and the
#: ``degrade.*`` events).
DEGRADE_ACTIONS = ("shed_speculation", "tighten_admission", "drop_ragged",
                   "shed_adapters")

#: SLO signals that implicate the DECODE path (shed speculation /
#: ragged) vs the admission path (tighten the tenant's weight).
_DECODE_SIGNALS = ("ttft", "tpot")


class DegradationController:
    """Hysteresis-guarded actuator over one engine's SLO burn rates.

    ``enter_burn`` defaults to the SLO policy's ``burn_threshold`` at
    first use; ``exit_burn`` must be strictly below it. ``min_hold_s``
    is the minimum time an entered action is held before it may exit
    (flap damping). ``admission_scale`` is the effective-weight factor
    applied to a tenant while ``tighten_admission`` is active.
    ``drop_ragged=True`` additionally drops a ragged adapter to the
    two-phase path while decode-side burn is active.
    ``shed_adapters=True`` additionally admits new LoRA-tagged requests
    as base-model rows while decode-side burn is active (same hysteresis
    band; best-effort tenants trade adapter output for headroom)."""

    def __init__(self, *, enter_burn: Optional[float] = None,
                 exit_burn: float = 1.0, min_hold_s: float = 1.0,
                 admission_scale: float = 0.25,
                 drop_ragged: bool = False,
                 shed_adapters: bool = False,
                 min_interval_s: float = 0.0):
        if enter_burn is not None and enter_burn <= 0:
            raise ConfigurationError("enter_burn must be > 0")
        if exit_burn <= 0:
            raise ConfigurationError("exit_burn must be > 0")
        if enter_burn is not None and exit_burn >= enter_burn:
            raise ConfigurationError(
                f"exit_burn ({exit_burn}) must be below enter_burn "
                f"({enter_burn}) — equal thresholds would flap")
        if min_hold_s < 0:
            raise ConfigurationError("min_hold_s must be >= 0")
        if min_interval_s < 0:
            raise ConfigurationError("min_interval_s must be >= 0")
        if not 0 < admission_scale <= 1:
            raise ConfigurationError(
                "admission_scale must be in (0, 1] — it scales the "
                "tenant's effective weight DOWN")
        self.enter_burn = enter_burn
        self.exit_burn = exit_burn
        self.min_hold_s = min_hold_s
        self.admission_scale = admission_scale
        self.drop_ragged = drop_ragged
        self.shed_adapters = shed_adapters
        # evaluation throttle: burn_index rescans the rolling windows
        # (bounded, but per pass adds up in a tight serving loop) — a
        # production deployment sets e.g. short_window_s / 10; 0 (the
        # default) evaluates every pass, which tests rely on
        self.min_interval_s = min_interval_s
        self._next_eval = 0.0
        # (action, tenant) -> entered_at (host clock)
        self._active: Dict[Tuple[str, str], float] = {}
        # tenants whose weight scale THIS controller installed — the
        # reconcile must never touch an operator-set scale
        self._scaled: set = set()
        self.stats: Dict[str, int] = {"enters": 0, "exits": 0}

    def check_policy(self, policy) -> None:
        """Validate the hysteresis band against the SLO policy the
        controller will act on: with ``enter_burn`` defaulted, the
        EFFECTIVE enter threshold is ``policy.burn_threshold`` — and
        ``exit_burn`` at or above it would flap exactly like the
        explicit case the constructor rejects. ``ServingEngine`` calls
        this at construction so the misconfiguration is loud, not a
        per-pass enter/exit churn."""
        enter = (self.enter_burn if self.enter_burn is not None
                 else policy.burn_threshold)
        if self.exit_burn >= enter:
            raise ConfigurationError(
                f"exit_burn ({self.exit_burn}) must be below the "
                f"effective enter threshold ({enter} — the SLO policy's "
                "burn_threshold when enter_burn is not set); equal or "
                "inverted thresholds would flap")

    # -- read surface ------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return bool(self._active)

    def is_active(self, action: str, tenant: Optional[str] = None) -> bool:
        if tenant is not None:
            return (action, tenant) in self._active
        return any(a == action for a, _ in self._active)

    def state(self, now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-able snapshot — the ``degradation`` section of
        ``debug_state()``."""
        if now is None:
            now = time.perf_counter()
        return {
            "degraded": self.degraded,
            "active": [
                {"action": a, "tenant": t,
                 "held_s": round(now - since, 4)}
                for (a, t), since in sorted(self._active.items())],
            "stats": dict(self.stats),
        }

    # -- the per-pass evaluation -------------------------------------------
    def update(self, engine, now: Optional[float] = None) -> None:
        """One control-loop evaluation: read the engine's SLO burn
        index, reconcile the hysteresis state machines, and apply the
        actuator side effects. Host-side only; called by
        ``ServingEngine.run_pass`` (cheap — bounded windows, no device
        work)."""
        tracker = engine.slo
        if tracker is None:
            return
        if now is None:
            now = time.perf_counter()
        if now < self._next_eval:
            return                     # throttled (actions unchanged)
        self._next_eval = now + self.min_interval_s
        enter = (self.enter_burn if self.enter_burn is not None
                 else tracker.policy.burn_threshold)
        burns = tracker.burn_index(now)
        desired: Dict[Tuple[str, str], float] = {}
        for (tenant, signal), burn in burns.items():
            if signal in _DECODE_SIGNALS:
                desired[("shed_speculation", tenant)] = max(
                    burn, desired.get(("shed_speculation", tenant), 0.0))
                if self.drop_ragged:
                    desired[("drop_ragged", tenant)] = max(
                        burn, desired.get(("drop_ragged", tenant), 0.0))
                if self.shed_adapters:
                    desired[("shed_adapters", tenant)] = max(
                        burn, desired.get(("shed_adapters", tenant), 0.0))
            else:
                desired[("tighten_admission", tenant)] = burn
        # enter: both windows burn past the enter threshold
        for key, burn in desired.items():
            if key not in self._active and burn >= enter:
                self._active[key] = now
                self.stats["enters"] += 1
                self._transition("degrade.enter", key, burn, engine)
        # exit: burn back under the exit threshold AND the hold elapsed
        for key in list(self._active):
            burn = desired.get(key, 0.0)
            if (burn < self.exit_burn
                    and now - self._active[key] >= self.min_hold_s):
                del self._active[key]
                self.stats["exits"] += 1
                self._transition("degrade.exit", key, burn, engine)
        self._apply(engine)

    # -- side effects ------------------------------------------------------
    def _apply(self, engine) -> None:
        """Reconcile the actuators with the active set (idempotent)."""
        adapter = engine.adapter
        if hasattr(adapter, "set_speculation_shed"):
            adapter.set_speculation_shed(self.is_active("shed_speculation"))
        if hasattr(adapter, "set_ragged_shed"):
            adapter.set_ragged_shed(self.is_active("drop_ragged"))
        if hasattr(adapter, "set_adapter_shed"):
            adapter.set_adapter_shed(self.is_active("shed_adapters"))
        queue = engine.queue
        tightened = {t for a, t in self._active if a == "tighten_admission"}
        # re-assert the scale for every ACTIVE tenant (idempotent, like
        # the shed flags — an external reset mid-hold must not leave the
        # gauge claiming an actuator that is silently off) and restore
        # only tenants THIS controller scaled: an operator's own
        # set_weight_scale on other tenants survives untouched
        for t in tightened:
            queue.set_weight_scale(t, self.admission_scale)
        for t in self._scaled - tightened:
            queue.set_weight_scale(t, 1.0)
        self._scaled = tightened

    def _transition(self, event: str, key: Tuple[str, str], burn: float,
                    engine) -> None:
        # imports deferred so resilience/ stays importable before
        # telemetry wires up in exotic embeddings (and to avoid a module
        # cycle: telemetry never imports resilience)
        from ..telemetry import get_registry
        from ..telemetry import metrics as tmetrics
        from ..telemetry.trace import get_recorder
        action, tenant = key
        rec = get_recorder()
        if rec.enabled:
            rec.instant(event, cat="engine", action=action, tenant=tenant,
                        burn=round(burn, 4),
                        active=len(self._active))
        reg = get_registry()
        if reg.enabled:
            tmetrics.degraded_gauge(reg).set(
                1.0 if event == "degrade.enter" else 0.0,
                tenant=tenant, action=action)
