"""Recompute preemption under KV pressure (vLLM-style RECOMPUTE mode).

When the block pool cannot satisfy an allocation (admission or decode
growth), the paged adapter evicts the lowest-priority running sequence,
reclaims its blocks, and hands the engine a :class:`Preempted` record. The
record's ``tokens`` (prompt + everything generated so far, including the
not-yet-cached last sample) is re-queued verbatim as a new prompt: under
greedy sampling the recomputed continuation is bit-identical to an
uninterrupted run (pinned by ``tests/test_resilience.py``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Optional, Tuple

__all__ = ["Preempted", "PREEMPTION_POLICIES", "pick_victim"]

#: Victim-selection policies:
#:   ``lifo``             — evict the most recently admitted sequence (its
#:                          recompute cost is lowest; vLLM's default)
#:   ``fewest_generated`` — evict the sequence with the fewest generated
#:                          tokens (least decode work thrown away), ties
#:                          broken LIFO
PREEMPTION_POLICIES = ("lifo", "fewest_generated")


@dataclass(frozen=True)
class Preempted:
    """One evicted sequence, ready for the engine to re-queue.

    ``tokens`` is the full recompute prompt: original prompt + generated
    tokens (the last of which had been sampled but not yet written to KV —
    re-prefilling writes it and samples its successor, exactly as the
    interrupted decode would have).

    The record carries everything a requeue needs: ``deadline`` is the
    victim's ABSOLUTE ``perf_counter()`` deadline (None = unbounded) and
    ``meta`` is the adapter's opaque per-request passthrough (the serving
    engine parks tenant/priority/request identity there), so a scheduler
    never reconstructs admission arguments by hand —
    :meth:`admission_kwargs` splats straight into ``add_requests``.
    Sampling state needs no field: the adapters decode greedily, so the
    recompute prompt IS the complete sampling state and the replayed
    continuation is bit-identical (pinned from the adapter path by
    tests/test_resilience.py and from the engine path by
    tests/test_serving_engine.py)."""

    seq_id: int
    tokens: Tuple[int, ...]
    prompt_len: int
    n_generated: int
    reason: str                    # "grow" | "admission" | "scheduler"
    deadline: Optional[float] = None   # absolute perf_counter() deadline
    meta: Any = None                   # engine passthrough (tenant, ...)
    trace_id: Optional[str] = None     # flight-recorder "preempt" event id

    def admission_kwargs(self, seq_id: Optional[int] = None,
                         now: Optional[float] = None) -> Dict[str, Any]:
        """Keyword arguments that re-admit this record through
        ``PagedEngineAdapter.add_requests(**kwargs)``: the recompute
        prompt, the REMAINING relative deadline budget (the victim's
        clock keeps running while it waits), and the meta passthrough.
        ``seq_id`` defaults to the evicted id — pass a fresh one when the
        old id may have been reused."""
        if now is None:
            now = time.perf_counter()
        return {
            "seq_ids": [self.seq_id if seq_id is None else seq_id],
            "prompts": [list(self.tokens)],
            "deadline_s": [None if self.deadline is None
                           else max(self.deadline - now, 0.0)],
            "meta": [self.meta],
        }

    def to_json(self, now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-safe serialization, so a requeue/handoff record can cross
        a process boundary (the fleet handoff contract rides this —
        serving/fleet/handoff.py). The absolute ``deadline`` is a
        ``perf_counter()`` value with no meaning in another process, so
        it is serialized as the REMAINING relative budget (the victim's
        clock keeps running while the record is in flight) and re-anchored
        by :meth:`from_json`. ``meta`` must itself be JSON-safe — the
        serving engine's meta (request_id/tenant/priority dict) is."""
        if now is None:
            now = time.perf_counter()
        return {
            "schema": "nxdi-preempted-v1",
            "seq_id": int(self.seq_id),
            "tokens": [int(t) for t in self.tokens],
            "prompt_len": int(self.prompt_len),
            "n_generated": int(self.n_generated),
            "reason": self.reason,
            "deadline_remaining_s": (None if self.deadline is None
                                     else max(self.deadline - now, 0.0)),
            "meta": self.meta,
            "trace_id": self.trace_id,
        }

    @classmethod
    def from_json(cls, data: Dict[str, Any],
                  now: Optional[float] = None) -> "Preempted":
        """Inverse of :meth:`to_json`: re-anchors the remaining deadline
        budget to THIS process's ``perf_counter()`` clock. Raises
        ``KeyError`` on a wrong-schema payload (callers that accept
        records over the wire wrap it typed — see
        serving/fleet/handoff.py)."""
        if data.get("schema") != "nxdi-preempted-v1":
            raise KeyError(f"not an nxdi-preempted-v1 record: "
                           f"schema={data.get('schema')!r}")
        if now is None:
            now = time.perf_counter()
        rem = data["deadline_remaining_s"]
        return cls(
            seq_id=int(data["seq_id"]),
            tokens=tuple(int(t) for t in data["tokens"]),
            prompt_len=int(data["prompt_len"]),
            n_generated=int(data["n_generated"]),
            reason=str(data["reason"]),
            deadline=None if rem is None else now + float(rem),
            meta=data.get("meta"),
            trace_id=data.get("trace_id"),
        )


def pick_victim(policy: str,
                candidates: Iterable[Tuple[int, int, int]]) -> Optional[int]:
    """Choose the victim seq_id from ``(seq_id, admit_idx, n_generated)``
    tuples; ``None`` when there are no candidates. ``admit_idx`` is the
    adapter's monotonic admission counter."""
    cands = list(candidates)
    if not cands:
        return None
    if policy == "lifo":
        return max(cands, key=lambda c: c[1])[0]
    if policy == "fewest_generated":
        # ties (same generated count) fall back to LIFO
        return min(cands, key=lambda c: (c[2], -c[1]))[0]
    raise ValueError(f"unknown preemption policy {policy!r}; expected one "
                     f"of {PREEMPTION_POLICIES}")
