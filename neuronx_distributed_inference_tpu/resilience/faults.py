"""Deterministic fault-injection harness for the serving surface.

Named fault points are compiled into the adapters and the paged cache
manager; arming one makes the Nth traversal of that point fail (or stall)
deterministically, so every recovery path — admission rollback, preemption,
deadline expiry, step retry — is exercised by fast CPU tests.

Usage::

    from neuronx_distributed_inference_tpu.resilience import FAULTS

    with FAULTS.inject("paged_alloc", nth=2) as fp:
        adapter.add_requests([0, 1], [p0, p1])   # 2nd block alloc fails
    assert fp.trips == 1

Fault points (a STABLE contract, like the telemetry metric names):

  ``paged_alloc``    block allocation in ``BlockKVCacheManager``
                     (``begin_sequence`` / ``grow``) — default raises
                     :class:`~.errors.CapacityError`, indistinguishable
                     from a genuinely exhausted pool
  ``prefill_step``   the device prefill call inside ``add_requests``
  ``prefill_chunk``  one packed chunk dispatch of the paged adapter's
                     chunked prefill path — fires BEFORE the dispatch, so
                     rollback of partially-prefilled sequences (progress
                     made by earlier chunks) is exercised deterministically
  ``decode_step``    the device decode call inside ``step()`` — fires
                     AFTER host-side KV growth, so it proves rollback
  ``slow_step``      start of ``step()`` — sleeps ``delay_s`` instead of
                     raising (drives deadline expiry deterministically)
  ``pipeline_flush`` the deferred token fetch of the pipelined decode path
                     (``pipeline_depth >= 1``) — fires where a genuine
                     asynchronous device failure from the PREVIOUS dispatch
                     would surface, so lookahead rollback is testable
                     deterministically
  ``spec_draft``     the draft pass of a speculative serving step
                     (serving/speculation/) — fires AFTER per-row KV
                     growth, so draft-failure rollback (blocks shrunk,
                     positions untouched) is provable
  ``spec_verify``    the batched k+1-token verify dispatch of a
                     speculative step — fires after the draft pass wrote
                     its KV, so mid-verify failure must roll EVERY packed
                     row back to its last accepted token (no
                     half-accepted cache poisoning)
  ``ragged_step``    THE unified mixed dispatch of a ragged engine step
                     (serving/ragged/) — fires AFTER per-row KV growth
                     and the draft pass, so a failure must roll EVERY
                     packed row back to its last accepted/delivered
                     token: live rows' growth shrunk with positions
                     untouched, prefill rows aborted exactly like a
                     failed chunk dispatch
  ``kv_spill``       a block payload spill into the host-RAM KV tier
                     (serving/fleet/kv_tier.py) — spills are best-effort:
                     a trip is swallowed by the adapter's spill hook and
                     counted (``tier.stats["spill_errors"]``), never
                     failing the allocation that evicted the block
  ``kv_restore``     the device write that re-admits spilled block
                     payloads inside ``add_requests`` — fires BEFORE the
                     write, so the transactional admission rollback
                     (nothing admitted, free pool restored exactly) is
                     provable; retry heals
  ``handoff``        a prefill→decode handoff (serving/fleet/handoff.py),
                     fired on BOTH capture and admit — either side fails
                     typed (:class:`~.errors.HandoffError`) with its
                     engine state unchanged
  ``migrate_capture`` the source-side capture of a live decode→decode
                     migration (serving/fleet/handoff.py ``migrate``) —
                     fires BEFORE any source state changes, so a trip
                     leaves BOTH engines untouched and the un-migrated
                     stream keeps serving on the source
  ``migrate_admit``  the destination-side admission of a migration —
                     fires BEFORE the tier seed and the transactional
                     re-admission, so a trip leaves the destination's
                     free pool exact and the source still serving
                     (typed :class:`~.errors.HandoffError` either way)
  ``autoscale``      one FleetAutoscaler evaluation
                     (serving/fleet/autoscaler.py) — a trip aborts that
                     evaluation (no spawn, no retire) with the fleet
                     unchanged; serving is never disturbed
  ``adapter_swap``   the device write of a LoRA adapter swap
                     (serving/lora_pool.py) — fires AFTER the pre-swap
                     snapshot and BEFORE the stacked-slot write, so the
                     transactional rollback (every touched stacked leaf
                     restored, slot returned to the free list, no
                     resident slot corrupted) is provable; surfaces as a
                     retry-safe typed :class:`~.errors.StepFailure`
                     (``phase="adapter_swap"``), so retry heals
  ``adapter_spill``  the device→host copy of an evicted adapter slot's
                     (A,B) factors into the pool's bounded host cache —
                     spills are best-effort: a trip is swallowed and
                     counted (``pool.stats["spill_errors"]``), never
                     failing the acquisition whose eviction triggered it
                     (the re-acquire just pays a cold checkpoint load)

Hot-path cost while nothing is armed: a single attribute check
(``FAULTS.active``) — no call, no allocation (pinned by
``tests/test_resilience.py``).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from .errors import CapacityError

__all__ = ["FAULT_POINTS", "FAULTS", "FaultInjector", "InjectedFault"]

FAULT_POINTS = ("paged_alloc", "prefill_step", "prefill_chunk",
                "decode_step", "slow_step", "pipeline_flush",
                "spec_draft", "spec_verify", "ragged_step",
                "kv_spill", "kv_restore", "handoff",
                "migrate_capture", "migrate_admit", "autoscale",
                "adapter_swap", "adapter_spill")


class InjectedFault(RuntimeError):
    """Default exception raised by an armed step fault point. Deliberately
    NOT a :class:`~.errors.ServingError`: it models an unexpected low-level
    failure, which the adapters must wrap into a typed
    :class:`~.errors.StepFailure` at the boundary."""


def _default_exc(point: str) -> Exception:
    if point == "paged_alloc":
        # must look exactly like a real pool-dry failure so the recovery
        # path under test is the production one
        return CapacityError("out of KV cache blocks (injected fault)")
    return InjectedFault(f"injected fault at point {point!r}")


class FaultPoint:
    """One arming of one fault point. Context manager: armed on
    ``__enter__``, disarmed on ``__exit__``. Exposes :attr:`calls` (times
    the point was traversed while armed) and :attr:`trips` (times the
    fault actually fired) for test assertions."""

    def __init__(self, injector: "FaultInjector", point: str, nth: int,
                 times: int, delay_s: Optional[float],
                 exc_factory: Optional[Callable[[], Exception]]):
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}; known points: "
                             f"{FAULT_POINTS}")
        if nth < 1 or times < 1:
            raise ValueError("nth and times must be >= 1")
        self.injector = injector
        self.point = point
        self.nth = nth
        self.times = times
        self.delay_s = delay_s
        self.exc_factory = exc_factory
        self.calls = 0
        self.trips = 0

    def __enter__(self) -> "FaultPoint":
        self.injector._arm(self)
        return self

    def __exit__(self, *exc_info) -> bool:
        self.injector._disarm(self)
        return False

    def _hit(self):
        """Called by the injector on each traversal of the armed point."""
        self.calls += 1
        if not (self.nth <= self.calls < self.nth + self.times):
            return
        self.trips += 1
        if self.delay_s is not None:
            time.sleep(self.delay_s)
            return
        raise (self.exc_factory() if self.exc_factory is not None
               else _default_exc(self.point))


class FaultInjector:
    """Registry of armed fault points. The module-level singleton
    :data:`FAULTS` is the one the library's call sites consult; tests arm
    it via :meth:`inject`. At most one arming per point at a time."""

    def __init__(self):
        self.active = False            # the ONLY thing hot paths read
        self._armed: Dict[str, FaultPoint] = {}

    def inject(self, point: str, *, nth: int = 1, times: int = 1,
               delay_s: Optional[float] = None,
               exc_factory: Optional[Callable[[], Exception]] = None
               ) -> FaultPoint:
        """Build a :class:`FaultPoint` arming ``point`` to fire on calls
        ``nth .. nth+times-1`` (1-based). ``delay_s`` makes it sleep
        instead of raise; ``exc_factory`` overrides the default exception.
        Use as a context manager."""
        return FaultPoint(self, point, nth, times, delay_s, exc_factory)

    def _arm(self, fp: FaultPoint):
        if fp.point in self._armed:
            raise RuntimeError(f"fault point {fp.point!r} is already armed")
        self._armed[fp.point] = fp
        self.active = True

    def _disarm(self, fp: FaultPoint):
        if self._armed.get(fp.point) is fp:
            del self._armed[fp.point]
        self.active = bool(self._armed)

    def fire(self, point: str):
        """Traverse ``point``: no-op unless that point is armed. Call
        sites guard with ``if FAULTS.active:`` so this is never entered
        in an unarmed process."""
        fp = self._armed.get(point)
        if fp is not None:
            fp._hit()


FAULTS = FaultInjector()
