"""Chaos campaign: every fault point, one mixed fleet workload, global
invariants asserted after heal.

The fault harness (:mod:`.faults`) gave every recovery path a
deterministic trigger, and PRs 2-13 pinned each one in isolation — but
no test ever drove a REALISTIC mixed fleet workload (chunked prefill +
decode + speculative verify + ragged unified dispatch + KV spill tier +
disaggregated handoff + replica failover, staggered) through a
randomized fault schedule. :class:`ChaosCampaign` is that driver:

  * one **golden** fault-free run of the workload records every
    stream's greedy tokens;
  * one **cell** per (fault point x schedule) re-runs the same seeded
    workload with that point armed — ``single`` (first traversal) and
    ``repeat`` (Nth traversal, multiple times) schedules sweep the
    "fails immediately" and "fails mid-flight, twice" shapes;
  * after the cell heals (engine retries, replica quarantine/probation,
    fleet requeue, preemption replay — whatever the armed point
    provokes), the **global invariants** are asserted:

      1. every stream is bit-identical to the golden (requeued /
         replayed streams included — the Preempted recompute contract
         makes greedy failover lossless),
      2. no stream is lost (same key set, every one finished),
      3. the block free pool is EXACT (each app back to its baseline
         count, no leaked tables),
      4. zero ``_unwritten`` leaks on surviving adapters,
      5. the armed point actually fired (an unreachable point is a red
         cell, not silent vacuous green).

The campaign is fully seeded (prompts AND the router's backoff jitter),
so a red cell reproduces. ``bench.py --chaos-report`` sweeps the full
matrix and commits ``artifacts/bench_chaos_r15.json``;
tests/test_resilience_control.py runs a seeded random subset tier-1 and
red-verifies the harness on a doctored invariant (a deliberately leaked
block must fail the campaign).

This module imports the serving stack lazily (inside the workload), so
``resilience/`` stays importable without jax.
"""

from __future__ import annotations

import json
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

from .errors import CapacityError, HandoffError, ServingError, StepFailure
from .faults import FAULT_POINTS, FAULTS

__all__ = ["CHAOS_SCHEMA", "ChaosCell", "ChaosCampaign", "default_cells"]

CHAOS_SCHEMA = "nxdi-chaos-v1"

#: ``slow_step`` must be armed with a delay — armed bare it raises an
#: untyped InjectedFault BEFORE the adapters' typed-wrapping try blocks
#: (its documented use is driving deadline expiry, not failure).
_SLOW_STEP_DELAY_S = 0.002


@dataclass(frozen=True)
class ChaosCell:
    """One campaign cell: ``point`` armed to trip on traversals
    ``nth .. nth+times-1`` while the whole workload runs."""
    point: str
    schedule: str                  # "single" | "repeat"
    nth: int
    times: int
    delay_s: Optional[float] = None


def default_cells(points: Optional[Sequence[str]] = None
                  ) -> List[ChaosCell]:
    """The full sweep matrix: every registered fault point, single-shot
    (first traversal) and repeated-Nth (second + third traversals)."""
    cells: List[ChaosCell] = []
    for point in (points if points is not None else FAULT_POINTS):
        delay = _SLOW_STEP_DELAY_S if point == "slow_step" else None
        cells.append(ChaosCell(point, "single", nth=1, times=1,
                               delay_s=delay))
        cells.append(ChaosCell(point, "repeat", nth=2, times=2,
                               delay_s=delay))
    return cells


def _retrying(fn: Callable[[], Any], attempts: int = 6):
    """Drive one workload operation through the documented heal paths:
    typed retry-safe failures (rolled-back admissions/steps, handoff
    sides with state unchanged, injected pool-dry CapacityErrors) are
    simply retried — exactly what a production caller does. Non-retry-
    safe failures and every other error propagate."""
    last: Optional[BaseException] = None
    for _ in range(attempts):
        try:
            return fn()
        except StepFailure as e:
            if not e.retry_safe:
                raise
            last = e
        except (HandoffError, CapacityError) as e:
            last = e
    raise last


class ChaosCampaign:
    """Seeded chaos driver over three same-weights paged applications.

    ``apps`` is a sequence of THREE ``PagedCausalLMApplication``s built
    from identical weights (replicas of one model — the fleet premise):
    the workload puts a ragged+speculative engine on the first (plus
    the KV spill tier and the handoff decode role), a pipelined engine
    on the second (plus the handoff prefill role) and a standalone
    speculative engine on the third, so every registered fault point is
    traversed by construction. ``cell_hook`` (test-only) runs after a
    cell's workload heals and before its invariants are checked — the
    red-verification seam (a hook that leaks a block must turn the
    campaign red)."""

    def __init__(self, apps, *, seed: int = 0, max_new: int = 4,
                 max_passes: int = 3000,
                 cell_hook: Optional[Callable[["ChaosCampaign", str],
                                              None]] = None):
        apps = list(apps)
        if len(apps) != 3:
            from .errors import ConfigurationError
            raise ConfigurationError(
                "ChaosCampaign needs exactly 3 same-weights paged apps "
                f"(got {len(apps)}) — ragged+spec, pipelined, spec roles")
        self.apps = apps
        self.seed = seed
        self.max_new = max_new
        self.max_passes = max_passes
        self.cell_hook = cell_hook
        self._golden: Optional[Dict[str, Any]] = None
        self._baseline: List[int] = []

    # -- public surface ----------------------------------------------------
    def sample_cells(self, k: int) -> List[ChaosCell]:
        """A seeded random subset of the full matrix — the tier-1 smoke
        shape (one seed, a few cells, <20s) vs the bench's full sweep."""
        rng = random.Random(self.seed)
        return rng.sample(default_cells(), k)

    def run(self, cells: Optional[Sequence[ChaosCell]] = None
            ) -> Dict[str, Any]:
        """Golden run + every cell; returns the ``nxdi-chaos-v1``
        report (``report["ok"]`` is the campaign verdict — the caller
        asserts it, the harness never raises on a red cell)."""
        cells = list(cells) if cells is not None else default_cells()
        self._baseline = [app.kv_mgr.allocator.num_free
                          for app in self.apps]
        t0 = time.perf_counter()
        golden = self._run_workload()
        self._golden = golden
        self._check_clean("golden")
        bad_golden = [k for k, v in golden.items()
                      if v["reason"] != "length"]
        rows = [self._run_cell(cell) for cell in cells]
        ok = not bad_golden and all(r["ok"] for r in rows)
        return {
            "schema": CHAOS_SCHEMA,
            "ok": ok,
            "seed": self.seed,
            "points": sorted({c.point for c in cells}),
            "golden": {
                "streams": len(golden),
                "tokens": sum(len(v["tokens"]) for v in golden.values()),
                "bad": bad_golden,
            },
            "cells": rows,
            "wall_s": round(time.perf_counter() - t0, 2),
        }

    # -- one cell ----------------------------------------------------------
    def _run_cell(self, cell: ChaosCell) -> Dict[str, Any]:
        row: Dict[str, Any] = {
            "point": cell.point, "schedule": cell.schedule,
            "nth": cell.nth, "times": cell.times,
        }
        error = None
        result: Dict[str, Any] = {}
        stats: Dict[str, Any] = {}
        try:
            with FAULTS.inject(cell.point, nth=cell.nth, times=cell.times,
                               delay_s=cell.delay_s) as fp:
                result = self._run_workload(stats)
            row["trips"] = fp.trips
            row["calls"] = fp.calls
        except Exception as e:          # a cell must never kill the sweep
            error = f"{type(e).__name__}: {e}"
            row["trips"] = row["calls"] = -1
        if self.cell_hook is not None:
            self.cell_hook(self, cell.point)
        golden = self._golden or {}
        missing = sorted(set(golden) - set(result))
        mismatched = sorted(
            k for k in golden if k in result
            and (result[k]["tokens"] != golden[k]["tokens"]
                 or result[k]["reason"] != golden[k]["reason"]))
        pool = [(app.kv_mgr.allocator.num_free, len(app.kv_mgr.tables))
                for app in self.apps]
        checks = {
            "fired": error is None and row["trips"] >= 1,
            "streams_bit_identical": error is None and not mismatched,
            "no_stream_lost": error is None and not missing,
            "free_pool_exact": all(
                free == base and tables == 0
                for (free, tables), base in zip(pool, self._baseline)),
            "no_unwritten_leak": stats.get("unwritten_leaked", -1) == 0,
        }
        row.update(
            ok=error is None and all(checks.values()),
            checks=checks,
            requeues=stats.get("requeues", 0),
            quarantines=stats.get("quarantines", 0),
            replica_failures=stats.get("replica_failures", 0),
            migrations=stats.get("migrations", 0),
            error=error,
            mismatched=mismatched, missing=missing,
        )
        return row

    def _check_clean(self, label: str) -> None:
        for app, base in zip(self.apps, self._baseline):
            if app.kv_mgr.tables or app.kv_mgr.allocator.num_free != base:
                raise ServingError(
                    f"chaos {label} run left device state behind "
                    f"(tables={sorted(app.kv_mgr.tables)}, "
                    f"free={app.kv_mgr.allocator.num_free}/{base}) — the "
                    "workload itself is broken; fix it before sweeping")

    # -- the mixed workload ------------------------------------------------
    def _prompt(self, rng: random.Random, n: int,
                lo: int = 1, hi: int = 500) -> List[int]:
        return [rng.randrange(lo, hi) for _ in range(n)]

    def _run_workload(self, stats: Optional[Dict[str, Any]] = None
                      ) -> Dict[str, Any]:
        """One seeded mixed run over the three apps. Returns
        ``{stream key: {"tokens", "reason"}}``; ``stats`` (optional
        out-param) collects heal/leak accounting for the cell row."""
        from ..serving import PagedEngineAdapter
        from ..serving.engine import ServingEngine
        from ..serving.fleet import (EngineRouter, FleetAutoscaler,
                                     HostKVSpillTier, admit_handoff,
                                     capture_handoff, handoff_from_json,
                                     handoff_to_json, migrate)
        if stats is None:
            stats = {}
        rng = random.Random(self.seed)
        app_a, app_b, app_c = self.apps
        bs = app_a.kv_mgr.spec.block_size
        max_new = self.max_new
        tier = HostKVSpillTier(max_blocks=64)
        results: Dict[str, Any] = {}

        def detach_hooks():
            for app in self.apps:
                alloc = app.kv_mgr.allocator
                if getattr(alloc, "on_evict", None) is not None:
                    alloc.on_evict = None

        # ---- phase 1: disaggregated prefill -> decode handoff ----------
        # (raw adapters, the process-boundary JSON wire form; every side
        # heals by plain retry — state unchanged on a typed failure)
        p_handoff = self._prompt(rng, 2 * bs + 1)
        prefill_ad = PagedEngineAdapter(app_b)
        first = _retrying(
            lambda: prefill_ad.add_requests([800], [p_handoff]))
        toks_h = [first[800]]
        record = _retrying(lambda: capture_handoff(prefill_ad, 800))
        wire = json.loads(json.dumps(handoff_to_json(record)))
        decode_ad = PagedEngineAdapter(app_a, kv_spill_tier=tier)
        try:
            admitted = _retrying(
                lambda: admit_handoff(decode_ad, handoff_from_json(wire),
                                      801))
            toks_h.append(admitted[801])
            for _ in range(max_new - 2):
                toks_h.append(
                    _retrying(lambda: decode_ad.step([801])[801]))
            decode_ad.release([801])
            results["handoff"] = {"tokens": toks_h, "reason": "length"}
            # ---- phase 1.5: force LRU eviction so the spill tier (and
            # the kv_spill point) actually fires; the cold admission is
            # aborted, so its never-written hashes are purged
            usable = app_a.kv_mgr.spec.num_blocks - 1
            cold = self._prompt(rng, usable * bs, lo=600, hi=5000)

            def evict():
                app_a.kv_mgr.begin_sequence(999, cold)
                app_a.kv_mgr.abort_sequence(999)

            _retrying(evict)
        finally:
            detach_hooks()

        # ---- phase 2: the staggered mixed fleet ------------------------
        # A: ragged unified dispatch + speculation + spill tier (verify +
        #    prefill rows in ONE dispatch, restores from the tier);
        # B: pipelined decode + chunked prefill (the only non-retry-safe
        #    fault point, pipeline_flush, lives here) — fed exclusively
        #    through the ROUTER so a replica death fails over instead of
        #    losing streams;
        # C: standalone speculative path (spec_verify dispatches).
        adapter_a = PagedEngineAdapter(app_a, ragged=True, speculation=2,
                                       kv_spill_tier=tier)
        adapter_b = PagedEngineAdapter(app_b, pipeline_depth=1,
                                       kv_spill_tier=HostKVSpillTier(
                                           max_blocks=64))
        adapter_c = PagedEngineAdapter(app_c, speculation=2)
        eng_a = ServingEngine(adapter_a, starvation_bound_s=1e9)
        eng_b = ServingEngine(adapter_b, starvation_bound_s=1e9)
        eng_c = ServingEngine(adapter_c, starvation_bound_s=1e9)
        # a pinned-size autoscaler (min == max == 3, so it can never
        # act): every fleet pass still runs one closed-loop EVALUATION,
        # which is exactly the "autoscale" fault point — an injected
        # trip aborts the evaluation with the fleet unchanged, the
        # documented trivial heal
        autoscaler = FleetAutoscaler(lambda: None, min_replicas=3,
                                     max_replicas=3)
        router = EngineRouter(
            {"A": eng_a, "B": eng_b, "C": eng_c},
            backoff_base_s=0.005, backoff_max_s=0.05,
            quarantine_after=2, max_replica_failures=8, seed=self.seed,
            autoscaler=autoscaler)
        streams: Dict[str, Any] = {}
        try:
            prefix_b = self._prompt(rng, 2 * bs)
            # first wave: direct work on A (long prompt -> chunked rows
            # in the ragged grid) and C; the FIRST routed request lands
            # on idle B (least load) before any pass runs
            streams["a0"] = eng_a.submit(self._prompt(rng, 2 * bs + 1),
                                         max_new, tenant="tA")
            streams["c0"] = eng_c.submit(self._prompt(rng, bs + 1),
                                         max_new, tenant="tC")
            streams["r0"] = router.submit(
                prefix_b + self._prompt(rng, 2), max_new)
            self._drive(router, streams, passes=2)
            # staggered second wave: prefill chunks now share dispatches
            # with live decode/verify rows; r1 re-presents B's prefix so
            # warm-affinity routing keeps B loaded with pipelined decode
            streams["a1"] = eng_a.submit(self._prompt(rng, 2 * bs + 1),
                                         max_new, tenant="tA")
            streams["c1"] = eng_c.submit(self._prompt(rng, bs + 1),
                                         max_new, tenant="tC")
            streams["r1"] = router.submit(
                prefix_b + self._prompt(rng, 2), max_new)
            # ---- phase 2.5: live decode→decode migration of r1 -------
            # move the routed pipelined-decode stream B→A mid-decode and
            # then back A→B (two capture + two admit traversals, so the
            # repeated-Nth schedules of migrate_capture / migrate_admit
            # have a second call to trip on); each leg heals by plain
            # retry — an injected failure leaves BOTH engines unchanged
            rid_r1 = streams["r1"].request_id

            def migrate_r1(dst: str):
                req = router._requests.get(rid_r1)
                if (req is None or streams["r1"].finished
                        or req.replica == dst
                        or router.replicas[req.replica].state == "dead"
                        or router.replicas[dst].state != "healthy"):
                    return             # already failed over / finished:
                    # the stream is bit-identical either way, which is
                    # the invariant the cell checks
                migrate(router, rid_r1, dst=dst)

            for _ in range(self.max_passes):
                if streams["r1"].n_tokens >= 1 or streams["r1"].finished:
                    break
                self._drive(router, streams, passes=1)
            _retrying(lambda: migrate_r1("A"))
            self._drive(router, streams, passes=1)
            _retrying(lambda: migrate_r1("B"))
            self._drive(router, streams)
            stats["migrations"] = router.stats["migrations"]
            stats["unwritten_leaked"] = sum(
                len(ad._unwritten)
                for ad, eng in ((adapter_a, eng_a), (adapter_b, eng_b),
                                (adapter_c, eng_c))
                if not eng.closed)
            stats["requeues"] = router.stats["requeues"]
            stats["quarantines"] = router.stats["quarantines"]
            stats["replica_failures"] = router.stats["replica_failures"]
            for key, s in streams.items():
                results[key] = {"tokens": list(s.tokens),
                                "reason": s.finish_reason}
        finally:
            for eng in (eng_a, eng_b, eng_c):
                if not eng.closed:
                    eng.close()
            # recover dead replicas: a fatal teardown keeps its device
            # tables (the cache is donated away) — the operator rebuild
            # path reclaims them before the pool invariant is read
            for app in self.apps:
                for sid in list(app.kv_mgr.tables):
                    app.kv_mgr.end_sequence(sid)
            detach_hooks()

        # ---- phase 3: multi-LoRA adapter churn (app_a's pool) ----------
        # A bounded pool over MORE registered adapters than device slots:
        # one adapter-tagged ragged stream (the adapter_swap point fires
        # inside the transactional swap of its admission; a trip rolls
        # the admission back and plain retry heals it) followed by an
        # acquire/release churn that forces >= 3 evictions, so the
        # best-effort adapter_spill point is traversed repeatedly (a
        # trip is swallowed — the later re-acquire cold-loads instead of
        # restoring, bit-identical either way).
        if getattr(app_a.spec, "lora", None) is not None:
            import numpy as np

            from ..serving import LoraAdapterPool
            pool = LoraAdapterPool(app_a, host_cache_adapters=2)
            lw = app_a.params["layers"]
            nprng = np.random.default_rng(self.seed + 31)

            def adapter_arrays():
                arrs = {}
                for mod in app_a.spec.lora.target_modules:
                    sa = lw[f"lora_A_{mod}"].shape   # (L, slots, in, r)
                    sb = lw[f"lora_B_{mod}"].shape   # (L, slots, r, out)
                    arrs[mod] = (
                        (nprng.standard_normal((sa[0], sa[2], sa[3]))
                         * 0.05).astype(np.float32),
                        (nprng.standard_normal((sb[0], sb[2], sb[3]))
                         * 0.05).astype(np.float32))
                return arrs

            for i in range(pool.n_slots + 2):
                pool.register_arrays(f"l{i}", adapter_arrays())
            lora_ad = PagedEngineAdapter(app_a, ragged=True,
                                         lora_pool=pool)
            p_lora = self._prompt(rng, bs + 1)
            try:
                _retrying(lambda: lora_ad.add_requests(
                    [900], [p_lora], meta=[{"adapter": "l0"}]))
                toks_l: List[int] = []
                for _ in range(self.max_passes):
                    if len(toks_l) >= max_new:
                        break
                    out = _retrying(lambda: lora_ad.step([900]))
                    toks_l.extend(out.get(900, ()))
                lora_ad.release([900])
                results["lora"] = {"tokens": toks_l, "reason": "length"}
                names = [f"l{1 + i % (pool.n_slots + 1)}"
                         for i in range(2 * (pool.n_slots + 1))]
                for nm in names:
                    _retrying(lambda nm=nm: pool.acquire(nm))
                    pool.release(nm)
                stats["lora_swaps"] = pool.stats["swaps"]
                stats["lora_spills"] = pool.stats["spills"]
            finally:
                if 900 in app_a.kv_mgr.tables:
                    app_a.kv_mgr.end_sequence(900)
        return results

    def _drive(self, router, streams: Dict[str, Any],
               passes: Optional[int] = None) -> None:
        """Drive fleet passes until every stream finished (or ``passes``
        elapsed for the staggering pause), sleeping out replica backoff
        (``EngineRouter.backoff_wait_s``) when a pass makes no
        progress."""
        done = 0
        while passes is None or done < passes:
            if passes is None and all(s.finished
                                      for s in streams.values()) \
                    and not router.has_work:
                return
            delivered = router.run_pass()
            done += 1
            if passes is None and done >= self.max_passes:
                raise ServingError(
                    f"chaos workload wedged after {done} passes "
                    "(streams unfinished) — recovery did not converge")
            if not delivered:
                wait = router.backoff_wait_s()
                if wait:
                    time.sleep(wait)
