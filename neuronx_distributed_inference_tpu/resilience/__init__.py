"""Serving resilience layer: typed failure taxonomy, deterministic
fault injection, and recompute-preemption policies.

The serving adapters (``serving.py``) and the paged cache manager
(``modules/block_kv_cache.py``) raise ONLY exceptions from this taxonomy at
their public boundaries (enforced by the ``error-paths`` pass of
``scripts/nxdi_lint.py``, a
tier-1 lint). Every recovery path — transactional admission rollback,
preemption under KV pressure, deadline budgets — is exercised on CPU by
arming the fault points in :mod:`.faults`; no TPU, no flakiness.
"""

from .controller import DEGRADE_ACTIONS, DegradationController
from .errors import (AdmissionError, Cancelled, CapacityError,
                     ConfigurationError, DeadlineExceeded, HandoffError,
                     KVCacheStateError, QueueOverflow, ReplicaUnavailable,
                     SequenceStateError, ServingError, StepFailure)
from .faults import FAULT_POINTS, FAULTS, FaultInjector, InjectedFault
from .preemption import PREEMPTION_POLICIES, Preempted, pick_victim

__all__ = [
    "ServingError", "AdmissionError", "CapacityError", "ConfigurationError",
    "DeadlineExceeded", "KVCacheStateError", "SequenceStateError",
    "StepFailure", "QueueOverflow", "Cancelled",
    "ReplicaUnavailable", "HandoffError",
    "FAULTS", "FAULT_POINTS", "FaultInjector", "InjectedFault",
    "Preempted", "PREEMPTION_POLICIES", "pick_victim",
    "DEGRADE_ACTIONS", "DegradationController",
]
