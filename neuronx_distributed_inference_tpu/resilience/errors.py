"""Typed failure taxonomy for the serving surface.

Everything the engine adapters and the paged KV cache manager raise at
their public boundaries derives from :class:`ServingError`, so an engine
can catch the whole family with one clause and branch on type to pick a
recovery: re-queue (:class:`CapacityError`), reject the request
(:class:`AdmissionError`), drop it (:class:`DeadlineExceeded`), or retry
the step (:class:`StepFailure` — host state is rolled back before it
propagates).

Each class also subclasses the builtin it replaced (``ValueError`` /
``RuntimeError`` / ``TimeoutError``) so pre-taxonomy callers written
against the old ad-hoc raises keep working unchanged.
"""

from __future__ import annotations

from typing import Sequence, Tuple

__all__ = [
    "ServingError", "AdmissionError", "SequenceStateError",
    "ConfigurationError", "CapacityError", "KVCacheStateError",
    "DeadlineExceeded", "StepFailure", "QueueOverflow", "Cancelled",
    "ReplicaUnavailable", "HandoffError",
]


class ServingError(Exception):
    """Base of the serving failure taxonomy. :attr:`seq_ids` carries the
    affected sequence ids when the failure is attributable to specific
    rows (empty otherwise), so engines never have to parse messages.

    :attr:`trace_id` is the flight-recorder event id of the matching
    ``error.*`` timeline event when the recorder was enabled at raise time
    (``telemetry.trace``), ``None`` otherwise — a post-mortem dump can
    jump from the caught exception straight to its place in the trace."""

    trace_id = None                    # set by FlightRecorder.error()

    def __init__(self, msg: str, seq_ids: Sequence[int] = ()):
        super().__init__(msg)
        self.seq_ids: Tuple[int, ...] = tuple(seq_ids)


class AdmissionError(ServingError, ValueError):
    """``add_requests`` arguments are invalid: empty/duplicate seq_ids,
    zero-length or over-long prompts, seq_id already running or out of
    range. Nothing was admitted; no device or cache state changed."""


class SequenceStateError(ServingError, ValueError):
    """An operation addressed a seq_id in the wrong lifecycle state
    (e.g. ``step()`` on a released or never-added id)."""


class ConfigurationError(ServingError, ValueError):
    """The adapter was built over an incompatibly-configured application."""


class CapacityError(ServingError, RuntimeError):
    """A bounded resource ran out: KV cache blocks, batch slots, or the
    compiled ``seq_len``. The failed call was rolled back (or, with a
    preemption policy armed, lower-priority sequences were evicted first —
    a ``CapacityError`` then means eviction could not free enough)."""


class KVCacheStateError(ServingError, RuntimeError):
    """KV-cache bookkeeping invariant violated (double free, shrink below
    zero). Indicates a caller bug, not load — never retry."""


class DeadlineExceeded(ServingError, TimeoutError):
    """One or more sequences blew their per-request wall-clock budget.

    Raised by ``step()`` BEFORE any device work: the engine should
    ``release(exc.seq_ids)`` (or re-queue with a fresh deadline) and step
    again. Carries the offending ids in :attr:`seq_ids`."""


class QueueOverflow(CapacityError):
    """The serving engine's request queue is at ``max_queue_depth``:
    admission control rejected the submit before it consumed any engine
    or device state. A load balancer should shed or retry elsewhere.
    Subclasses :class:`CapacityError` so capacity-aware callers handle
    both with one clause."""


class ReplicaUnavailable(CapacityError):
    """The fleet router has no replica able to take the request: every
    replica is draining or dead (or the one a caller targeted is). A load
    balancer should shed or retry elsewhere. Subclasses
    :class:`CapacityError` — like :class:`QueueOverflow` it is a
    load-shedding signal, not a caller bug."""


class HandoffError(ServingError, RuntimeError):
    """A disaggregated prefill→decode handoff failed: malformed or
    wrong-schema record, capture of a sequence in the wrong lifecycle
    state, or a decode-side admission that could not consume the record.
    The failing side's engine state is unchanged (capture reads before it
    releases; admission is transactional)."""


class Cancelled(ServingError):
    """The request was cancelled (explicit ``cancel()`` call or the
    streaming client went away). Queued entries are dropped without any
    device work; running sequences are released and their KV blocks
    reclaimed. Delivered tokens remain valid."""


class StepFailure(ServingError, RuntimeError):
    """A device step (prefill or decode) raised. Host-side adapter and
    cache-manager bookkeeping was rolled back to the pre-call state before
    this propagates. The original exception rides along as ``__cause__``;
    :attr:`phase` is ``"prefill"`` or ``"decode"``; :attr:`seq_ids` names
    the rows in the failed call.

    :attr:`retry_safe` is True when the failure happened before the
    device computation consumed (donated) the KV cache — injected faults
    and host-side errors — so the engine may simply retry the call. When
    False, a genuine device failure surfaced after dispatch: the donated
    cache buffers are gone, device state is lost, and the adapter (and
    its application) must be rebuilt before serving can continue."""

    def __init__(self, msg: str, phase: str = "",
                 seq_ids: Sequence[int] = (), retry_safe: bool = True):
        super().__init__(msg, seq_ids)
        self.phase = phase
        self.retry_safe = retry_safe
