"""Serving integration surface — the importable continuous-batching
contract a vLLM-style engine drives (reference: the vLLM-facing surface of
models/model_wrapper.py — ``vllm_cte_repadding`` :1297-1313 and the
seq_ids-addressed forward :1315-1440; the reference README's north star is
serving through vLLM).

The engine owns scheduling; this adapter owns device state:

  * ``add_requests(seq_ids, prompts)``  — prefill rows into their cache
    lines (cache rows are addressed BY seq_id, so request order is free)
  * ``step(seq_ids=None)``              — one decode step for the given
    (default: all) running rows, repadded to the compiled batch bucket
  * ``step_many(k, seq_ids=None)``      — k fused decode steps in ONE
    device dispatch + ONE host fetch (CB: the jitted lax.scan decode loop;
    paged: the fused paged loop with in-graph KV-slot advance)
  * ``flush()``                         — retire the pipelined in-flight
    dispatch (no-op in eager mode)
  * ``release(seq_ids)``                — free rows (and paged blocks)

Works over either application:
  - ``CausalLMApplication`` with ``is_continuous_batching=True`` —
    contiguous cache rows keyed by seq_id;
  - ``PagedCausalLMApplication`` — block tables keyed by seq_id.

Decode pipeline (see README "Decode pipeline"):

  * ``pipeline_depth=0`` (default) is the eager path, bit-identical to the
    pre-pipeline behavior: every ``step()`` dispatches and synchronously
    fetches its own tokens.
  * ``pipeline_depth=1`` keeps the previous dispatch's sampled tokens ON
    DEVICE and feeds them straight into the next decode call, fetching to
    host asynchronously one step behind — host bookkeeping overlaps device
    compute and the device never idles behind Python between steps.
    ``step()`` then returns the PREVIOUS step's tokens ({} on the first
    call); ``flush()`` drains the last one. Token streams are bit-identical
    to eager (pinned by tests/test_decode_pipeline.py).
  * Deferred-failure contract: a device failure from step N surfaces at
    step N+1's fetch as a :class:`StepFailure` with ``retry_safe=False``;
    every in-flight lookahead step's host bookkeeping (positions, paged KV
    growth) is rolled back to the last DELIVERED token. The
    ``pipeline_flush`` fault point makes this deterministic in tests.
  * Hot-path host bookkeeping is incremental: per-(live set, batch bucket)
    scratch buffers are filled in place instead of rebuilt via
    np.concatenate/np.repeat each step, and the paged block-table array is
    refreshed only for rows whose block list actually grew.
  * The dispatch helpers (``_dispatch_*``) must never materialize device
    values — enforced by the tier-1 AST lint
    ``host-sync`` pass of ``scripts/nxdi_lint.py``.

Chunked, packed, schedulable prefill — paged adapter only (see README
"Chunked prefill"; reference analog: ragged/mixed-batch TPU prefill,
"Ragged Paged Attention" arxiv 2604.15464):

  * each admitted prompt's uncached suffix is split into
    ``prefill_chunk_tokens``-sized chunks driven through the ``_run_paged``
    slot-mapping path (positions are arbitrary), so prompts up to
    ``seq_len`` are admissible regardless of the largest ctx bucket.
    Intermediate chunk samples are discarded; only the final chunk's token
    is delivered. Token streams are bit-identical to monolithic admission
    (pinned by tests/test_chunked_prefill.py).
  * chunks from DIFFERENT sequences pack as ragged rows of one ctx-bucket
    dispatch (each row at its own offset over its own block table), so a
    batch of skewed-length prompts no longer pads every row to the longest
    suffix — reclaimed pad waste is reported via ``nxdi_prefill_pad_waste``
    and ``nxdi_prefill_chunks_total``.
  * ``prefill_budget_tokens`` defers prefill to the scheduler:
    ``add_requests`` only admits (block allocation + chunk state) and
    returns ``{}``; each ``step()``/``step_many()`` then runs AT MOST ONE
    packed chunk dispatch of at most that many prompt tokens before its
    decode work, so a long admission no longer stalls running decodes for
    the whole prefill. First tokens are delivered by the ``step()`` call
    whose dispatch completes the prompt.
  * half-prefilled sequences stay inside the resilience contracts: a chunk
    dispatch failure (``prefill_chunk`` fault point) rolls every sequence
    packed in that dispatch back via ``abort_sequence`` (never-fully-
    written blocks cannot poison the prefix cache), deadlines expire
    pending admissions BEFORE device work, and preemption may evict a
    pending sequence (its ``Preempted.tokens`` is the bare prompt,
    ``n_generated == 0``).

Ragged unified dispatch — paged adapter only (see README "Ragged
dispatch"; serving/ragged/):

  * ``ragged=True`` routes EVERY engine step — decode rows, speculative
    verify windows, pending prefill chunks — through ONE
    ``model_base.paged_ragged_step`` dispatch planned by the
    ``RaggedBatchPlanner``, padded within the unified
    ``autobucketing.ragged_row_buckets`` ladder. Admission always defers
    (``add_requests`` returns ``{}``) and ``prefill_budget_tokens``
    becomes a per-step cap on packed prompt tokens instead of a
    serialization point. Token streams stay bit-identical to the
    two-phase path, with and without ``speculation=`` (pinned by
    tests/test_ragged_dispatch.py).

Resilience contract (see README "Serving resilience"):

  * every boundary failure is typed (``resilience.errors``) — never a bare
    ``ValueError``/``RuntimeError`` (enforced by
    the ``error-paths`` pass of ``scripts/nxdi_lint.py``);
  * ``add_requests`` is **transactional**: it either admits every sequence
    or rolls back all allocations/adapter state from the call and leaves
    device + cache state exactly as before;
  * the paged adapter **preempts** the lowest-priority running sequence
    when the block pool runs dry (``preemption_policy``: "lifo" /
    "fewest_generated" / None), handing back :class:`Preempted` records
    via :meth:`PagedEngineAdapter.take_preempted`;
  * per-request wall-clock deadlines (``deadline_s``) and a
    decode-past-``seq_len`` guard bound each request's budget; both are
    horizon-aware (``step_many(k)`` checks them once for the whole k-step
    horizon, before any device work).
"""

from __future__ import annotations

import bisect
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..modules import autobucketing
from ..modules.block_kv_cache import slots_from_table_into
from ..resilience.errors import (AdmissionError, CapacityError,
                                ConfigurationError, DeadlineExceeded,
                                SequenceStateError, ServingError, StepFailure)
from ..resilience.faults import FAULTS as _FAULTS
from ..resilience.preemption import (PREEMPTION_POLICIES, Preempted,
                                    pick_victim)
from ..telemetry import get_registry
from ..telemetry import metrics as tmetrics
from ..telemetry.request_trace import trace_of as _trace_of
from ..telemetry.trace import get_recorder as _get_recorder


@dataclass
class _SeqState:
    position: int                 # position of last_token
    last_token: int
    running: bool = True
    tokens: List[int] = field(default_factory=list)  # prompt + generated
    prompt_len: int = 0
    admit_idx: int = 0            # adapter-wide admission counter (LIFO)
    deadline: Optional[float] = None   # absolute perf_counter() deadline
    expired_reported: bool = False     # deadline metric counted once
    meta: Any = None              # opaque engine passthrough (tenant, ...)


@dataclass
class _ChunkState:
    """Chunked-prefill progress for one PENDING admission (paged adapter):
    KV for ``prompt[:done]`` is written (or prefix-cached — ``done`` starts
    at the post-cut prefix-hit count); ``[done:]`` still has chunks to run.
    The sequence graduates to a :class:`_SeqState` when its final chunk's
    token materializes."""
    prompt: List[int]
    done: int                     # tokens whose KV is written/cached
    admit_idx: int
    t0: float                     # admission wall time (TTFT anchor)
    deadline: Optional[float] = None
    expired_reported: bool = False
    meta: Any = None              # opaque engine passthrough (tenant, ...)


@dataclass
class _Inflight:
    """One dispatched-but-not-fetched decode step (pipeline_depth >= 1).

    ``states`` pins the exact _SeqState objects the dispatch advanced:
    retire/rollback apply only where the identity still matches, so a row
    released (or preempted) and re-admitted under the same seq_id while
    the step was in flight can never receive the stale token."""
    live: Tuple[int, ...]
    states: Tuple[_SeqState, ...]
    b: int
    pad_to: int
    out: Dict[str, Any]
    t_dispatch: float
    grown: int = 0                # paged KV tokens grown for this dispatch


def _meta_tenant(meta: Any) -> str:
    """Tenant label value from an opaque per-request ``meta`` payload: the
    serving engine passes mappings with a "tenant" key; everything else
    (including the non-engine default None) labels as ""."""
    try:
        return str(meta.get("tenant", ""))
    except AttributeError:
        return ""


def _meta_seed(meta: Any) -> int:
    """Per-request sampling seed from an opaque ``meta`` payload: the
    serving engine passes mappings with a "sampling_seed" key; everything
    else (including the non-engine default None) seeds as 0. Feeds the
    positionally coupled sampling stream (ops/sampling.stream_keys) —
    two requests with the same seed and prompt sample the same tokens."""
    try:
        return int(meta.get("sampling_seed", 0))
    except (AttributeError, TypeError, ValueError):
        return 0


def _meta_adapter(meta: Any) -> Optional[str]:
    """Named LoRA adapter from an opaque per-request ``meta`` payload: the
    serving engine passes mappings with an "adapter" key; everything else
    (including the non-engine default None, and empty/None values) means
    the base model. The adapter resolves the name against its attached
    :class:`~.lora_pool.LoraAdapterPool` at admission."""
    try:
        name = meta.get("adapter", None)
    except AttributeError:
        return None
    return str(name) if name else None


def _common_tenant(tenants) -> str:
    """The single tenant shared by every affected row, or "" when the set
    is empty or mixed — per-call failure counters label with ONE tenant,
    and a cross-tenant failure is attributed to none rather than to an
    arbitrary member."""
    ts = set(tenants)
    return ts.pop() if len(ts) == 1 else ""


def _trace_error(err):
    """Record ``err`` on the flight recorder (attaching ``err.trace_id``)
    when tracing is live; returns ``err`` so raise sites stay one-liners.
    Idempotent per exception — a re-wrapped error keeps its first event."""
    rec = _get_recorder()
    if rec.enabled and getattr(err, "trace_id", None) is None:
        rec.error(err)
    return err


def _async_fetch(x):
    """Start the device->host copy without blocking (no-op for array types
    without the API, e.g. plain numpy under test fakes)."""
    try:
        x.copy_to_host_async()
    except AttributeError:
        pass


class _AdapterTelemetry:
    """Shared engine-adapter instrumentation: TTFT / per-step decode latency
    histograms, live-batch + pad-waste accounting, pipeline depth/overlap/
    steps-per-fetch, one request span per seq_id. Host-side only (measures
    at the adapter boundary); every method is a cheap no-op while telemetry
    is disabled."""

    def __init__(self, engine: str, telemetry=None):
        self.engine = engine
        self._telemetry = telemetry
        self._requests: Dict[int, Dict[str, Any]] = {}

    @property
    def registry(self):
        return self._telemetry if self._telemetry is not None \
            else get_registry()

    def on_add(self, seq_ids: Sequence[int], prompts, t0: float,
               live: int, padded: int, count_rows: bool = True,
               tenants: Optional[Sequence[str]] = None):
        reg = self.registry
        if not reg.enabled:
            return
        if tenants is None:
            tenants = [""] * len(seq_ids)
        ttft = time.perf_counter() - t0
        hist = tmetrics.ttft_histogram(reg)
        for sid, prompt, tenant in zip(seq_ids, prompts, tenants):
            span = reg.start_span("request", engine=self.engine, seq_id=sid,
                                  tenant=tenant)
            span.t_start = t0
            span.event("first_token", ttft_s=ttft, prompt_len=len(prompt))
            self._requests[sid] = {"span": span, "steps": 0,
                                   "t_first": t0 + ttft, "t_last": t0 + ttft,
                                   "tenant": tenant}
            hist.observe(ttft, engine=self.engine, tenant=tenant)
        tmetrics.requests_counter(reg).inc(len(seq_ids), engine=self.engine,
                                           event="added")
        tmetrics.generated_tokens_counter(reg).inc(live, engine=self.engine)
        if count_rows:
            # chunked admissions account their device rows per chunk
            # dispatch (on_prefill_chunk) instead
            self._rows(reg, "prefill", live, padded)

    def on_prefill_chunk(self, rows: int, padded_rows: int,
                         real_tokens: int, padded_tokens: int):
        reg = self.registry
        if not reg.enabled:
            return
        tmetrics.prefill_chunks_counter(reg).inc(rows, engine=self.engine)
        if padded_tokens:
            tmetrics.prefill_pad_waste_histogram(reg).observe(
                1.0 - real_tokens / padded_tokens, engine=self.engine)
        self._rows(reg, "prefill", rows, padded_rows)

    def on_step(self, live_ids: Sequence[int], t0: float, padded: int,
                steps: int = 1):
        reg = self.registry
        if not reg.enabled:
            return
        now = time.perf_counter()
        n = len(live_ids)
        # per-STEP latency even for a fused k-step horizon, so the
        # histogram stays comparable across step()/step_many() modes
        tmetrics.decode_step_histogram(reg).observe((now - t0) / steps,
                                                    engine=self.engine)
        tmetrics.generated_tokens_counter(reg).inc(n * steps,
                                                   engine=self.engine)
        for sid in live_ids:
            info = self._requests.get(sid)
            if info is not None:
                info["steps"] += steps
                info["t_last"] = now
        self._rows(reg, "decode", n, padded, steps=steps)

    def on_spec_step(self, rows: Sequence[Tuple[int, int]], t0: float,
                     padded: int, width: int, drafted: int, accepted: int,
                     mode: str = "greedy"):
        """One speculative engine step: ``rows`` is (seq_id, tokens
        delivered) per live row — per-request TPOT counts every delivered
        token, and the spec counters pin the drafted/accepted split.
        ``mode`` labels the verify discipline (greedy | sampled) so the
        two acceptance regimes never alias in one series."""
        reg = self.registry
        now = time.perf_counter()
        delivered = 0
        for sid, n in rows:
            delivered += n
            info = self._requests.get(sid)
            if info is not None:
                info["steps"] += n
                info["t_last"] = now
        if not reg.enabled:
            return
        tmetrics.decode_step_histogram(reg).observe(now - t0,
                                                    engine=self.engine)
        tmetrics.generated_tokens_counter(reg).inc(delivered,
                                                   engine=self.engine)
        tmetrics.spec_drafted_counter(reg).inc(drafted, engine=self.engine,
                                               mode=mode)
        tmetrics.spec_accepted_counter(reg).inc(accepted,
                                                engine=self.engine,
                                                mode=mode)
        if drafted:
            tmetrics.spec_accept_rate_gauge(reg).set(accepted / drafted,
                                                     engine=self.engine,
                                                     mode=mode)
        tmetrics.spec_verify_width_histogram(reg).observe(
            width, engine=self.engine)
        self._rows(reg, "decode", len(rows), padded)

    def on_ragged_step(self, kind_rows: Dict[str, int], real_tokens: int,
                       padded_tokens: int):
        """One ragged unified dispatch: ``kind_rows`` maps row kind
        (decode/prefill/verify/pad) to rows packed; the pad-waste gauge
        tracks the last dispatch's (padded - real) / padded over the
        unified row-bucket grid."""
        reg = self.registry
        if not reg.enabled:
            return
        counter = tmetrics.ragged_rows_counter(reg)
        for kind, n in kind_rows.items():
            if n:
                counter.inc(n, engine=self.engine, kind=kind)
        if padded_tokens:
            tmetrics.ragged_pad_waste_gauge(reg).set(
                1.0 - real_tokens / padded_tokens, engine=self.engine)

    def on_dispatch(self, depth: int):
        reg = self.registry
        if reg.enabled:
            tmetrics.dispatch_depth_gauge(reg).set(depth, engine=self.engine)

    def on_fetch(self, steps: int, overlap_s: Optional[float] = None):
        reg = self.registry
        if not reg.enabled:
            return
        tmetrics.steps_per_fetch_histogram(reg).observe(steps,
                                                        engine=self.engine)
        if overlap_s is not None:
            tmetrics.host_overlap_histogram(reg).observe(overlap_s,
                                                         engine=self.engine)

    def on_release(self, seq_ids: Sequence[int]):
        # pop unconditionally: requests admitted while telemetry was live
        # must not leak from _requests if it is disabled before release
        reg = self.registry
        released = 0
        for sid in seq_ids:
            info = self._requests.pop(sid, None)
            if info is None:
                continue
            released += 1
            span, steps = info["span"], info["steps"]
            span.event("released", decode_steps=steps)
            if reg.enabled and steps > 0:
                # first token -> LAST decode step, not -> release: a request
                # parked finished while the engine drains others must not
                # inflate its reported per-token latency
                tmetrics.tpot_histogram(reg).observe(
                    (info["t_last"] - info["t_first"]) / steps,
                    engine=self.engine, tenant=info.get("tenant", ""))
            span.end()
        if released and reg.enabled:
            tmetrics.requests_counter(reg).inc(released, engine=self.engine,
                                               event="released")

    def on_preempt(self, seq_id: int, reason: str, tenant: str = ""):
        # like on_release, the span is closed unconditionally so a request
        # preempted after telemetry is disabled cannot leak from _requests
        info = self._requests.pop(seq_id, None)
        if info is not None:
            info["span"].event("preempted", reason=reason)
            info["span"].end()
        reg = self.registry
        if reg.enabled:
            tmetrics.preemptions_counter(reg).inc(engine=self.engine,
                                                  reason=reason,
                                                  tenant=tenant)

    def on_deadline(self, seq_ids: Sequence[int],
                    tenants: Optional[Sequence[str]] = None):
        reg = self.registry
        if not seq_ids or not reg.enabled:
            return
        if tenants is None:
            tenants = [""] * len(seq_ids)
        counter = tmetrics.deadline_expired_counter(reg)
        for tenant in tenants:
            counter.inc(engine=self.engine, tenant=tenant)

    def on_step_failure(self, phase: str, tenant: str = ""):
        reg = self.registry
        if reg.enabled:
            tmetrics.step_failures_counter(reg).inc(engine=self.engine,
                                                    phase=phase,
                                                    tenant=tenant)

    def on_admission_rollback(self):
        reg = self.registry
        if reg.enabled:
            tmetrics.admission_rollbacks_counter(reg).inc(engine=self.engine)

    def _rows(self, reg, phase: str, live: int, padded: int,
              steps: int = 1):
        tmetrics.live_batch_gauge(reg).set(live, engine=self.engine)
        tmetrics.live_rows_counter(reg).inc(live * steps, engine=self.engine,
                                            phase=phase)
        if padded > live:
            tmetrics.pad_rows_counter(reg).inc((padded - live) * steps,
                                               engine=self.engine,
                                               phase=phase)


def _live_rows(seqs: Dict[int, _SeqState],
               seq_ids: Optional[Sequence[int]],
               pending=()) -> List[int]:
    """Running rows for a step call. ``pending`` holds seq_ids admitted but
    still mid-prefill (chunked admissions): they are known — not an error —
    but carry no decodable row yet, so they are skipped."""
    ids = sorted(seqs) if seq_ids is None else list(seq_ids)
    if seq_ids is not None:
        for sid in ids:
            if sid not in seqs and sid not in pending:
                raise SequenceStateError(f"seq_id {sid} is not running "
                                         "(released or never added)")
    return [sid for sid in ids if sid in seqs and seqs[sid].running]


def _validate_admission(seq_ids: Sequence[int],
                        prompts: Sequence[Sequence[int]], seq_len: int):
    """Reject malformed admissions BEFORE any state changes — an empty
    batch or a zero-length prompt must fail typed here, not as an opaque
    numpy ``max()`` crash three layers down."""
    if len(seq_ids) == 0:
        raise AdmissionError("add_requests called with empty seq_ids")
    if len(seq_ids) != len(prompts):
        raise AdmissionError("seq_ids and prompts length mismatch "
                             f"({len(seq_ids)} vs {len(prompts)})")
    if len(set(seq_ids)) != len(seq_ids):
        raise AdmissionError("duplicate seq_ids in one add_requests call")
    for sid, p in zip(seq_ids, prompts):
        if len(p) == 0:
            raise AdmissionError(f"zero-length prompt for seq_id {sid}")
        if len(p) > seq_len:
            raise AdmissionError(
                f"prompt for seq_id {sid} is {len(p)} tokens — beyond the "
                f"compiled seq_len {seq_len}")


def _resolve_deadlines(deadline_s, n: int,
                       t0: float) -> List[Optional[float]]:
    """Per-request absolute deadlines from a scalar (shared) or per-seq
    sequence of relative wall-clock budgets in seconds."""
    if deadline_s is None:
        return [None] * n
    if isinstance(deadline_s, (int, float)):
        return [t0 + float(deadline_s)] * n
    if len(deadline_s) != n:
        raise AdmissionError("deadline_s and seq_ids length mismatch")
    return [None if d is None else t0 + float(d) for d in deadline_s]


def _pre_step_checks(seqs: Dict[int, _SeqState], live: Sequence[int],
                     seq_len: Optional[int], telemetry: _AdapterTelemetry,
                     horizon: int = 1):
    """Per-request budget enforcement, BEFORE any device work or cache
    growth: wall-clock deadlines, then the decode-past-seq_len guard (a
    row at position seq_len-1 holds its last representable token — one
    more step would scatter KV out of bounds). ``horizon`` is the number
    of fused steps about to run (``step_many``); the guard covers the
    whole horizon. ``seq_len`` is None for rolling-window caches
    (slot = pos % window never overflows)."""
    now = time.perf_counter()
    expired = [s for s in live
               if seqs[s].deadline is not None and now >= seqs[s].deadline]
    if expired:
        fresh = [s for s in expired if not seqs[s].expired_reported]
        for s in fresh:
            seqs[s].expired_reported = True
        telemetry.on_deadline(fresh, [_meta_tenant(seqs[s].meta)
                                      for s in fresh])
        raise _trace_error(DeadlineExceeded(
            f"seq_ids {expired} exceeded their wall-clock deadline; "
            "release() them (or re-queue with a fresh budget) and step "
            "again", seq_ids=expired))
    if seq_len is None:
        return
    over = [s for s in live if seqs[s].position + horizon > seq_len]
    if over:
        raise _trace_error(CapacityError(
            f"decode step (horizon {horizon}) for seq_ids {over} would "
            f"write KV past the compiled seq_len {seq_len}; release them "
            "or rebuild with a larger seq_len", seq_ids=over))


def _repeat_row0(x: np.ndarray, pad_to: int) -> np.ndarray:
    """Pad a batch axis to ``pad_to`` by repeating row 0 — THE batch-pad
    invariant (pad rows recompute row 0's data and rewrite its cache
    slots with identical values; reference: vllm_cte_repadding,
    model_wrapper.py:1297-1313)."""
    return np.concatenate([x, np.repeat(x[:1], pad_to - x.shape[0],
                                        axis=0)])


def _pad_paged_rows(pad_to, ids, pos, slots, bt, last):
    """Repeat row 0 up to the batch bucket (see :func:`_repeat_row0`)."""
    b = ids.shape[0]
    if b == pad_to:
        return ids, pos, slots, bt, last
    return tuple(_repeat_row0(x, pad_to) for x in (ids, pos, slots, bt,
                                                   last))


# ---------------------------------------------------------------------------
# Per-composition scratch buffers (incremental host bookkeeping)
# ---------------------------------------------------------------------------

class _CbScratch:
    """Reusable decode-step input buffers for one (live set, batch bucket)
    composition on the contiguous adapter: the per-step np.concatenate /
    np.repeat rebuilds become in-place fills.

    The mutable input buffers are DOUBLE-BUFFERED (ping-pong): jax's CPU
    backend may alias a suitably-aligned numpy array zero-copy, so
    refilling the buffer a still-in-flight pipelined dispatch aliases
    would corrupt its input mid-execution. Each fill() flips buffers; a
    set is only rewritten after its dispatch was retired (depth <= 1)."""

    def __init__(self, live: Sequence[int], pad_to: int):
        b = len(live)
        self.live = tuple(live)
        self.b = b
        self.pad_to = pad_to
        self.sid_p = np.empty((pad_to,), np.int32)   # immutable after init
        self.sid_p[:b] = live
        self.sid_p[b:] = live[0]
        self._bufs = [(np.empty((pad_to, 1), np.int32),
                       np.empty((pad_to, 1), np.int32)) for _ in range(2)]
        self._cur = 0
        self.toks_p, self.pos_p = self._bufs[0]
        # device-feedback re-pad map: pad rows must stay clones of row 0
        self.gather_idx = np.concatenate(
            [np.arange(b, dtype=np.intp),
             np.zeros(pad_to - b, dtype=np.intp)])

    def fill(self, adapter, need_tokens: bool = True):
        self._cur ^= 1
        self.toks_p, self.pos_p = self._bufs[self._cur]
        seqs = adapter.seqs
        for i, s in enumerate(self.live):
            st = seqs[s]
            self.pos_p[i, 0] = st.position
            if need_tokens:
                self.toks_p[i, 0] = st.last_token
        if self.pad_to > self.b:
            self.pos_p[self.b:] = self.pos_p[0, 0]
            if need_tokens:
                self.toks_p[self.b:] = self.toks_p[0, 0]


class _PagedScratch:
    """Reusable decode-step input buffers for one (live set, batch bucket,
    table-width bucket) composition on the paged adapter. The block-table
    array is refreshed incrementally (only rows whose block list grew);
    slot mappings are recomputed in place from the cached table.

    Double-buffered like :class:`_CbScratch` (jax CPU zero-copy aliasing):
    each fill() flips to the other (ids, pos, slots, bt, counts) set, so
    the buffers a still-in-flight dispatch aliases are never rewritten."""

    def __init__(self, live: Sequence[int], pad_to: int, width: int,
                 block_size: int, seeds: Optional[Sequence[int]] = None,
                 aids: Optional[Sequence[int]] = None):
        b = len(live)
        self.live = tuple(live)
        self.b = b
        self.pad_to = pad_to
        self.width = width
        self.last = np.zeros((pad_to,), np.int32)    # immutable after init
        # per-sequence sampling-stream seeds are constants of the live
        # composition (request meta never changes mid-flight), so the
        # buffer is immutable after init like ``last`` — no ping-pong
        self.seeds = np.zeros((pad_to,), np.int32)   # immutable after init
        if seeds is not None:
            self.seeds[:b] = np.asarray(seeds, np.int32)
            if pad_to > b:
                self.seeds[b:] = self.seeds[0]
        # per-row LoRA adapter slots are constants of the live composition
        # too (a slot is pinned for the sequence's whole residency), so
        # the buffer is immutable after init like ``seeds``; None keeps
        # the no-adapter graphs byte-identical (the kwarg is never passed)
        self.aids = None
        if aids is not None:
            self.aids = np.zeros((pad_to,), np.int32)
            self.aids[:b] = np.asarray(aids, np.int32)
            if pad_to > b:
                self.aids[b:] = self.aids[0]
        self._bufs = [(np.empty((pad_to, 1), np.int32),
                       np.empty((pad_to, 1), np.int32),
                       np.empty((pad_to, 1), np.int32),
                       np.zeros((pad_to, width), np.int32),
                       [0] * b) for _ in range(2)]
        self._cur = 0
        self.ids, self.pos, self.slots, self.bt, self.counts = self._bufs[0]
        self.gather_idx = np.concatenate(
            [np.arange(b, dtype=np.intp),
             np.zeros(pad_to - b, dtype=np.intp)])
        self._block_size = block_size

    def fill(self, adapter, need_tokens: bool = True):
        self._cur ^= 1
        (self.ids, self.pos, self.slots, self.bt,
         self.counts) = self._bufs[self._cur]
        seqs = adapter.seqs
        mgr = adapter.app.kv_mgr
        for i, s in enumerate(self.live):
            st = seqs[s]
            self.pos[i, 0] = st.position
            if need_tokens:
                self.ids[i, 0] = st.last_token
        prev0 = self.counts[0]
        mgr.fill_block_table(self.bt[:self.b], self.live, self.counts)
        if self.pad_to > self.b:
            self.pos[self.b:] = self.pos[0, 0]
            if need_tokens:
                self.ids[self.b:] = self.ids[0, 0]
            if self.counts[0] != prev0:
                self.bt[self.b:] = self.bt[0]
        slots_from_table_into(self.slots, self.bt, self.pos,
                              self._block_size)


# ---------------------------------------------------------------------------
# Shared adapter machinery (pipeline + fused multi-step + eager template)
# ---------------------------------------------------------------------------

class _EngineAdapterBase:
    """Decode-path machinery shared by both adapters: the eager step
    template, the depth-1 decode pipeline (device-resident token feedback,
    deferred fetch, lookahead-aware rollback) and ``step_many``. Subclasses
    provide dispatch, scratch construction, KV growth and token
    bookkeeping."""

    engine_name = ""
    _decode_failure_msg = "decode device step failed"

    def _init_decode_path(self, pipeline_depth: int):
        if pipeline_depth not in (0, 1):
            raise ConfigurationError(
                f"pipeline_depth must be 0 (eager) or 1 (one dispatch of "
                f"lookahead), got {pipeline_depth!r}")
        self.pipeline_depth = pipeline_depth
        self._inflight: Optional[_Inflight] = None
        self._ready: Dict[int, int] = {}
        self._scratch = None
        self._spec = None              # SpeculativeDecodePath (paged only)
        self._ragged = None            # RaggedDispatchPath (paged only)
        # degradation-controller actuators (resilience/controller.py):
        # shed flags are consulted per step, so flipping them mid-serve
        # changes DISPATCH SHAPE only — greedy token streams are
        # unaffected (pinned by tests/test_resilience_control.py)
        self._spec_shed = False        # clamp draft widths to 1 (no draft)
        self._ragged_shed = False      # ragged -> two-phase dispatching
        # plain-int host counters (always on — they feed the CPU
        # microbenches, bench.py --host-overhead / --prefill-overhead).
        # The decode counters (dispatches/blocking_fetches/...) count ONLY
        # decode work; chunked prefill keeps its own prefill_* set so the
        # two stay separately comparable.
        self.host_stats: Dict[str, Any] = {
            "dispatches": 0, "device_steps": 0,
            "blocking_fetches": 0, "blocked_s": 0.0,
            "prefill_dispatches": 0, "prefill_blocking_fetches": 0,
            "prefill_blocked_s": 0.0, "prefill_real_tokens": 0,
            "prefill_padded_tokens": 0}

    # -- subclass hooks ----------------------------------------------------
    def _pending_ids(self):
        """seq_ids admitted but still mid-prefill (paged chunked
        admissions); () on adapters without a deferred prefill path."""
        return ()

    def _advance_prefill(self, seq_ids=None):
        """Run at most one packed prefill-chunk dispatch for pending
        admissions; finished sequences' first tokens land in ``_ready``.
        ``seq_ids`` is the step call's explicit target set (None = all):
        an expired pending admission outside it is skipped, not raised —
        a healthy row must not be stalled by an unrelated request's
        budget. No-op on adapters without a deferred prefill path."""

    def _grow_for_step(self, live: List[int], n: int = 1) -> List[int]:
        return live

    def _rollback_step_growth(self, live: Sequence[int], n: int = 1):
        pass

    def _append_token(self, st: _SeqState, tok: int):
        st.last_token = tok

    _step_growth = 0              # paged: KV tokens grown per dispatch

    def _tenant_of(self, seq_ids) -> str:
        """Common tenant label of ``seq_ids`` (running rows), "" when
        mixed/unknown — failure counters attribute per tenant only when
        the attribution is unambiguous."""
        return _common_tenant(_meta_tenant(self.seqs[s].meta)
                              for s in seq_ids if s in self.seqs)

    def _traces_of(self, seq_ids):
        """Request trace ids of ``seq_ids`` (running rows) — the
        attribution payload for steady-state recompile incidents
        (serving/warmup.py)."""
        return [_trace_of(self.seqs[s].meta)
                for s in seq_ids if s in self.seqs]

    # -- fetch helpers (the ONLY places that block on device output) -------
    def _fetch_rows(self, out, b: int) -> np.ndarray:
        t0 = time.perf_counter()
        toks = np.asarray(out["tokens"])
        t1 = time.perf_counter()
        self.host_stats["blocking_fetches"] += 1
        self.host_stats["blocked_s"] += t1 - t0
        rec = _get_recorder()
        if rec.enabled:
            rec.complete("fetch.tokens", t0, cat="adapter", t1=t1,
                         engine=self.engine_name, rows=b)
        return toks.reshape(toks.shape[0], -1)[:b]

    # -- public decode surface ---------------------------------------------
    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        """One decode step for ``seq_ids`` (default: every running row).

        Eager (``pipeline_depth=0``): returns {seq_id: next token} for THIS
        step. Pipelined (``pipeline_depth=1``): dispatches this step and
        returns the PREVIOUS step's tokens ({} on the first call after the
        pipeline empties; drain the last step with :meth:`flush`). Raises
        :class:`DeadlineExceeded` / :class:`CapacityError` before any
        device work when a row is over budget, and :class:`StepFailure`
        when a device step fails — see the class docstring for the
        deferred-failure rollback contract."""
        if self.pipeline_depth:
            return self._step_pipelined(seq_ids)
        return self._step_eager(seq_ids)

    def step_many(self, num_steps: int,
                  seq_ids: Optional[Sequence[int]] = None
                  ) -> Dict[int, List[int]]:
        """``num_steps`` fused decode steps in ONE device dispatch and ONE
        blocking host fetch. Returns {seq_id: [tokens]} in stream order;
        a pipelined adapter's in-flight token is drained first and
        prepended (it is simply the preceding token of the same stream).
        Deadlines and the seq_len guard are enforced once for the whole
        horizon, before any device work. EOS handling stays with the
        engine, at horizon boundaries."""
        if num_steps < 1:
            raise ConfigurationError("step_many requires num_steps >= 1")
        if self._inflight is not None or self._ready:
            self._stash_flush()
        # pending drained tokens stay in self._ready until this call is
        # past every fallible stage — a recoverable DeadlineExceeded /
        # CapacityError / StepFailure must not drop them from the stream
        pending = self._pending_ids()
        live = _live_rows(self.seqs, seq_ids, pending)
        if not live and not pending:
            return {s: [t] for s, t in self._drain_ready().items()}
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        if live:
            _pre_step_checks(self.seqs, live, self._pos_limit,
                             self.telemetry, horizon=num_steps)
        # at most ONE packed prefill-chunk dispatch per horizon — the
        # scheduler knob that keeps a long admission from stalling decode
        self._advance_prefill(seq_ids)
        if not live:
            return {s: [t] for s, t in self._drain_ready().items()}
        t0 = time.perf_counter()
        live = self._grow_for_step(live, num_steps)
        if not live:
            return {s: [t] for s, t in self._drain_ready().items()}
        toks, pad_to = self._run_many(live, num_steps)
        res = {s: [t] for s, t in self._drain_ready().items()}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += num_steps
            row = [int(t) for t in toks[i]]
            for t in row:
                self._append_token(st, t)
            res.setdefault(s, []).extend(row)
        self.telemetry.on_step(live, t0, padded=pad_to, steps=num_steps)
        self.telemetry.on_fetch(num_steps)
        return res

    def flush(self) -> Dict[int, int]:
        """Retire the in-flight pipelined dispatch (if any) and hand back
        every token not yet delivered: {seq_id: token}. {} in eager mode.
        A deferred fetch failure aborts the pipeline (StepFailure,
        ``retry_safe=False``)."""
        ready = self._drain_ready()
        rec, self._inflight = self._inflight, None
        if rec is not None:
            try:
                ready.update(self._retire_or_abort([rec]))
            except BaseException:
                # the drained tokens were already generated and applied to
                # host state — keep them deliverable past the failure
                self._ready = {**ready, **self._ready}
                raise
        return ready

    # -- eager path --------------------------------------------------------
    def _step_eager(self, seq_ids) -> Dict[int, int]:
        pending = self._pending_ids()
        live = _live_rows(self.seqs, seq_ids, pending)
        if not live and not pending:
            return self._drain_ready()
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        if live:
            _pre_step_checks(self.seqs, live, self._pos_limit,
                             self.telemetry)
        self._advance_prefill(seq_ids)
        if not live:
            return self._drain_ready()
        t0 = time.perf_counter()
        live = self._grow_for_step(live)
        if not live:
            return self._drain_ready()
        scr = self._scratch_for(live)
        scr.fill(self)
        cache_before = self.app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("decode_step")
            out = self._dispatch_decode(scr)
            new = self._fetch_rows(out, len(live))
        except ServingError:
            self._rollback_step_growth(live)
            self._scratch = None
            raise
        except Exception as e:
            self._rollback_step_growth(live)
            self._scratch = None
            self.telemetry.on_step_failure("decode", self._tenant_of(live))
            raise _trace_error(StepFailure(
                self._decode_failure_msg + "; positions were not advanced",
                phase="decode", seq_ids=tuple(live),
                retry_safe=self.app.cache is cache_before)) from e
        res = self._drain_ready()    # first tokens of finished prefills
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            tok = int(new[i, 0])
            self._append_token(st, tok)
            res[s] = tok
        self.telemetry.on_step(live, t0, padded=scr.pad_to)
        self.telemetry.on_fetch(1)
        return res

    # -- pipelined path ----------------------------------------------------
    def _step_pipelined(self, seq_ids) -> Dict[int, int]:
        pending = self._pending_ids()
        live = _live_rows(self.seqs, seq_ids, pending)
        if not live and not pending:
            return self.flush()
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        if live:
            _pre_step_checks(self.seqs, live, self._pos_limit,
                             self.telemetry)
        self._advance_prefill(seq_ids)
        if not live:
            return self.flush()
        ready = self._drain_ready()
        try:
            return self._advance_pipeline(live, ready)
        except BaseException:
            # tokens drained (or retired) this call were already generated
            # and applied to host state — keep them deliverable past a
            # recoverable failure instead of dropping them from the stream
            self._ready = {**ready, **self._ready}
            raise

    def _advance_pipeline(self, live: List[int],
                          ready: Dict[int, int]) -> Dict[int, int]:
        prev, self._inflight = self._inflight, None
        if prev is not None and not self._matches(prev, live):
            # live-set changed since the dispatch: drain it synchronously
            ready.update(self._retire_or_abort([prev]))
            prev = None
        t0 = time.perf_counter()
        try:
            live = self._grow_for_step(live)
        except ServingError:
            self._inflight = prev          # growth rolled itself back
            raise
        if not live:
            self._inflight = prev
            return ready
        if prev is not None and not self._matches(prev, live):
            # preemption shrank the batch mid-call: drain the old
            # composition's dispatch before re-padding for the new one
            ready.update(self._retire_or_abort([prev]))
            prev = None
        scr = self._scratch_for(live)
        scr.fill(self, need_tokens=prev is None)
        toks_dev = None if prev is None else self._feedback_tokens(prev, scr)
        cache_before = self.app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("decode_step")
            out = self._dispatch_decode(scr, toks_dev)
        except ServingError:
            self._rollback_step_growth(live)
            self._scratch = None
            self._inflight = prev          # lookahead step is still healthy
            raise
        except Exception as e:
            self._rollback_step_growth(live)
            self._scratch = None
            self._inflight = prev
            self.telemetry.on_step_failure("decode", self._tenant_of(live))
            raise _trace_error(StepFailure(
                self._decode_failure_msg + " at dispatch; the in-flight "
                "lookahead step was preserved",
                phase="decode", seq_ids=tuple(live),
                retry_safe=self.app.cache is cache_before)) from e
        rec = _Inflight(
            live=tuple(live),
            states=tuple(self.seqs[s] for s in live),
            b=len(live), pad_to=scr.pad_to, out=out, t_dispatch=t0,
            grown=self._step_growth)
        for s in live:
            self.seqs[s].position += 1
        if prev is not None:
            ready.update(self._retire_or_abort([prev, rec]))
        self._inflight = rec
        self.telemetry.on_dispatch(1)
        return ready

    def _matches(self, rec: _Inflight, live: Sequence[int]) -> bool:
        return (rec.live == tuple(live)
                and all(self.seqs.get(s) is st
                        for s, st in zip(rec.live, rec.states)))

    def _feedback_tokens(self, prev: _Inflight, scr):
        """The previous dispatch's on-device sampled tokens, re-padded ON
        DEVICE (pad rows must stay clones of row 0 even under stochastic
        sampling) and fed straight back as the next step's input ids — no
        host round trip."""
        toks = prev.out["tokens"].reshape(-1)
        if scr.pad_to > scr.b:
            toks = toks[scr.gather_idx]
        return toks[:, None]

    def _retire(self, rec: _Inflight) -> Dict[int, int]:
        """Materialize ``rec``'s tokens (the ONE blocking sync of the
        pipelined path) and apply the deferred host bookkeeping. Raises
        the raw fetch failure — callers route it through
        :meth:`_abort_pipeline`."""
        if _FAULTS.active:
            _FAULTS.fire("pipeline_flush")
        overlap = time.perf_counter() - rec.t_dispatch
        new = self._fetch_rows(rec.out, rec.b)
        res = {}
        for i, (s, st) in enumerate(zip(rec.live, rec.states)):
            if self.seqs.get(s) is not st:
                continue               # released/preempted while in flight
            tok = int(new[i, 0])
            self._append_token(st, tok)
            res[s] = tok
        self.telemetry.on_step(list(res), rec.t_dispatch, padded=rec.pad_to)
        self.telemetry.on_fetch(1, overlap_s=overlap)
        self.telemetry.on_dispatch(0)
        return res

    def _retire_or_abort(self, records: List[Optional[_Inflight]]
                         ) -> Dict[int, int]:
        try:
            return self._retire(records[0])
        except Exception as e:
            self._abort_pipeline(records, e)

    def _abort_pipeline(self, records: Sequence[Optional[_Inflight]],
                        cause: Exception):
        """A deferred fetch failed: the in-flight step's device output (and
        any dispatch speculatively issued on top of it) is garbage. Unwind
        every in-flight dispatch's host bookkeeping — positions and paged
        KV growth return to the last DELIVERED token — and raise a
        :class:`StepFailure` with ``retry_safe=False`` (the donated device
        cache was consumed by the failed dispatch chain; re-admit or
        rebuild)."""
        self._scratch = None
        seq_ids: Tuple[int, ...] = ()
        for rec in records:
            if rec is None:
                continue
            if not seq_ids:
                seq_ids = rec.live
            for s, st in zip(rec.live, rec.states):
                if self.seqs.get(s) is st:
                    st.position -= 1
            self._unwind_inflight_growth(rec)
        self.telemetry.on_dispatch(0)
        self.telemetry.on_step_failure("decode", self._tenant_of(seq_ids))
        raise _trace_error(StepFailure(
            "pipelined decode fetch failed; every in-flight lookahead step "
            "was rolled back to the last delivered token",
            phase="decode", seq_ids=seq_ids, retry_safe=False)) from cause

    def _unwind_inflight_growth(self, rec: _Inflight):
        pass

    def _drain_ready(self) -> Dict[int, int]:
        if not self._ready:
            return {}
        out, self._ready = self._ready, {}
        return out

    def _stash_flush(self):
        """flush() into the pending buffer, so tokens drained by
        add/release/step_many are handed back by the next returning call
        instead of being dropped."""
        for s, t in self.flush().items():
            self._ready[s] = t

    # -- post-mortem snapshot ----------------------------------------------
    def debug_state(self) -> Dict[str, Any]:
        """Read-only host-side snapshot for post-mortems (surfaced through
        :meth:`~..engine.scheduler.ServingEngine.dump_debug_state` and the
        ``GET /v1/debug/state`` endpoint). JSON-able; never touches device
        state."""
        return {
            "engine": self.engine_name,
            "running_ids": [int(s) for s in sorted(self.seqs)],
            "positions": {int(s): int(st.position)
                          for s, st in self.seqs.items()},
            "tenants": {int(s): _meta_tenant(st.meta)
                        for s, st in self.seqs.items()},
            "pipeline_inflight": (0 if self._inflight is None
                                  else len(self._inflight.live)),
            "ready_undelivered": [int(s) for s in sorted(self._ready)],
            "host_stats": dict(self.host_stats),
        }


class ContinuousBatchingAdapter(_EngineAdapterBase):
    """vLLM-style engine adapter over the contiguous app
    (reference: model_wrapper.py:1297-1440)."""

    engine_name = "cb"

    def __init__(self, app, telemetry=None, pipeline_depth: int = 0):
        cfg = app.tpu_config
        if not cfg.is_continuous_batching:
            raise ConfigurationError("app must be built with "
                                     "is_continuous_batching=True")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}
        self.telemetry = _AdapterTelemetry("cb", telemetry)
        # rolling caches (slot = pos % window) can decode past seq_len
        self._pos_limit = (None if getattr(app.spec, "rolling_window", False)
                           else cfg.seq_len)
        # free rows, ascending — maintained incrementally on add/release
        self._free: List[int] = list(range(self.batch))
        self._init_decode_path(pipeline_depth)

    # -- capacity ---------------------------------------------------------
    @property
    def free_slots(self) -> List[int]:
        return list(self._free)

    # -- lifecycle --------------------------------------------------------
    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]],
                     deadline_s: Union[None, float,
                                       Sequence[Optional[float]]] = None
                     ) -> Dict[int, int]:
        """Prefill ``prompts`` into cache rows ``seq_ids``. Returns
        {seq_id: first generated token}. Rows are padded to the ctx bucket
        (repeat-row-0 batch pad — reference ``vllm_cte_repadding``).
        Transactional: a failure admits nothing (cache rows hold garbage
        only for never-admitted seq_ids, which no live row can read). A
        pipelined in-flight decode step stays in flight — the next step()
        drains it when the live set changes."""
        _validate_admission(seq_ids, prompts, self.app.tpu_config.seq_len)
        for sid in seq_ids:
            if not 0 <= sid < self.batch:
                raise AdmissionError(f"seq_id {sid} out of range "
                                     f"[0,{self.batch})")
            if sid in self.seqs:
                raise AdmissionError(f"seq_id {sid} already running")
        t0 = time.perf_counter()
        deadlines = _resolve_deadlines(deadline_s, len(seq_ids), t0)
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        try:
            width = autobucketing.get_target_bucket(
                self.app.ctx_buckets, int(lens.max()), kind="ctx")
        except ValueError as e:
            raise AdmissionError(f"prompt does not fit any context-encoding "
                                 f"bucket: {e}") from e
        ids = np.zeros((b, width), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        pad_to = self._batch_bucket(b)
        ids_p, sid_p = self._pad_rows(ids, np.asarray(seq_ids, np.int32),
                                      pad_to)
        lens_p = np.concatenate([lens, np.repeat(lens[:1], pad_to - b)])
        cache_before = self.app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("prefill_step")
            out = self.app._run_prefill(ids_p, lens_p, seq_ids=sid_p)
            # materialize INSIDE the try: dispatch is asynchronous, so a
            # genuine device failure only surfaces when the tokens are
            # fetched — it must still be wrapped and rolled back here
            toks = np.asarray(out["tokens"])[:b]
        except ServingError:
            raise
        except Exception as e:
            self.telemetry.on_step_failure("prefill")
            raise _trace_error(StepFailure(
                "prefill device step failed; no sequences were admitted",
                phase="prefill", seq_ids=seq_ids,
                retry_safe=self.app.cache is cache_before)) from e
        res = {}
        for i, sid in enumerate(seq_ids):
            # no tokens/admit_idx bookkeeping here: the CB adapter has no
            # preemption path (rows are fixed slots), so the recompute
            # record the paged adapter keeps would be dead state
            self.seqs[sid] = _SeqState(
                position=int(lens[i]), last_token=int(toks[i]),
                prompt_len=int(lens[i]), deadline=deadlines[i])
            del self._free[bisect.bisect_left(self._free, sid)]
            res[sid] = int(toks[i])
        self.telemetry.on_add(seq_ids, prompts, t0, live=b, padded=pad_to)
        return res

    def release(self, seq_ids: Sequence[int]):
        if self._inflight is not None:
            self._stash_flush()
        for sid in seq_ids:
            self._ready.pop(sid, None)
            if self.seqs.pop(sid, None) is not None:
                bisect.insort(self._free, sid)
        self.telemetry.on_release(seq_ids)

    # -- decode dispatch ---------------------------------------------------
    def _scratch_for(self, live: Sequence[int]) -> _CbScratch:
        pad_to = self._batch_bucket(len(live))
        scr = self._scratch
        if scr is None or scr.live != tuple(live) or scr.pad_to != pad_to:
            scr = self._scratch = _CbScratch(live, pad_to)
        return scr

    def _dispatch_decode(self, scr: _CbScratch, toks_dev=None):
        """Issue ONE decode step to the device without materializing any
        output (region lint: nxdi_lint host-sync pass) — the blocking
        fetch happens in the caller (eager) or at retire time (pipelined).
        ``toks_dev``: previous dispatch's on-device tokens (pipelined
        feedback); None = host tokens from the scratch buffer."""
        ids = scr.toks_p if toks_dev is None else toks_dev
        out = self.app._run_decode(ids, scr.pos_p, seq_ids=scr.sid_p)
        _async_fetch(out["tokens"])
        self.host_stats["dispatches"] += 1
        self.host_stats["device_steps"] += 1
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("dispatch.decode", cat="adapter",
                        engine=self.engine_name, rows=scr.b,
                        pad_to=scr.pad_to, seq_ids=list(scr.live),
                        pipelined=toks_dev is not None)
        return out

    def _run_many(self, live: List[int], num_steps: int):
        """Fused k-step decode through the jitted lax.scan loop
        (model_base.decode_loop) — one dispatch, one fetch."""
        b = len(live)
        pad_to = self._batch_bucket(b)
        first = np.empty((pad_to,), np.int32)
        pos = np.empty((pad_to,), np.int32)
        sid = np.empty((pad_to,), np.int32)
        for i, s in enumerate(live):
            st = self.seqs[s]
            first[i] = st.last_token
            pos[i] = st.position
            sid[i] = s
        first[b:] = first[0]
        pos[b:] = pos[0]
        sid[b:] = sid[0]
        cache_before = self.app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("decode_step")
            out = self.app._run_decode_loop(first, pos, num_steps,
                                            seq_ids=sid)
            self.host_stats["dispatches"] += 1
            self.host_stats["device_steps"] += num_steps
            rec = _get_recorder()
            if rec.enabled:
                rec.instant("dispatch.decode_loop", cat="adapter",
                            engine=self.engine_name, rows=b, pad_to=pad_to,
                            steps=num_steps, seq_ids=list(live))
            toks = self._fetch_rows(out, b)
        except ServingError:
            raise
        except Exception as e:
            self.telemetry.on_step_failure("decode", self._tenant_of(live))
            raise _trace_error(StepFailure(
                "fused decode loop failed; positions were not advanced",
                phase="decode", seq_ids=tuple(live),
                retry_safe=self.app.cache is cache_before)) from e
        return toks, pad_to

    # -- helpers ----------------------------------------------------------
    def _batch_bucket(self, b: int) -> int:
        if b > self.batch:
            raise CapacityError(f"live batch {b} exceeds compiled batch "
                                f"{self.batch}")
        return autobucketing.get_target_bucket(self.app.batch_buckets, b,
                                               kind="batch")

    @staticmethod
    def _pad_rows(ids: np.ndarray, seq_ids: np.ndarray, pad_to: int):
        pad = pad_to - ids.shape[0]
        if pad <= 0:
            return ids, seq_ids
        return (np.concatenate([ids, np.repeat(ids[:1], pad, axis=0)]),
                np.concatenate([seq_ids, np.repeat(seq_ids[:1], pad)]))


class PagedEngineAdapter(_EngineAdapterBase):
    """vLLM-style engine adapter over the PAGED app: block tables keyed by
    seq_id, slot mappings computed from the tables (reference: the
    slot_mapping / active_block_table contract of
    block_kv_cache_manager.py + model_wrapper.py:1297-1313).

    ``preemption_policy`` ("lifo" | "fewest_generated" | None) arms
    recompute preemption: when the block pool cannot satisfy an allocation
    the lowest-priority running sequence is evicted, its blocks reclaimed,
    and a :class:`Preempted` record queued for :meth:`take_preempted` —
    the engine re-queues ``record.tokens`` as a fresh prompt. ``None``
    disables eviction (allocation failures then raise
    :class:`CapacityError` after rolling the call back). Pending chunked
    admissions are eligible victims too (``tokens`` = the bare prompt,
    ``n_generated == 0``).

    ``prefill_chunk_tokens`` bounds one sequence's per-dispatch prefill
    chunk (default: the largest ctx bucket — monolithic-equivalent, but
    prompts longer than that bucket are still admitted by walking them in
    bucket-sized chunks). ``prefill_budget_tokens`` defers prefill to the
    scheduler: ``add_requests`` returns ``{}`` and each ``step()`` runs at
    most one packed chunk dispatch of at most that many prompt tokens
    before its decode work (first tokens arrive from the completing
    ``step()``). Both are documented in README "Chunked prefill"."""

    engine_name = "paged"
    _decode_failure_msg = ("paged decode step failed; KV growth was rolled "
                          "back")
    _step_growth = 1

    def __init__(self, app, telemetry=None,
                 preemption_policy: Optional[str] = "lifo",
                 pipeline_depth: int = 0,
                 prefill_chunk_tokens: Optional[int] = None,
                 prefill_budget_tokens: Optional[int] = None,
                 speculation=None, kv_spill_tier=None,
                 ragged: bool = False, lora_pool=None):
        cfg = app.tpu_config
        if not cfg.is_block_kv_layout:
            raise ConfigurationError("app must be built with "
                                     "is_block_kv_layout=True")
        if (preemption_policy is not None
                and preemption_policy not in PREEMPTION_POLICIES):
            raise ConfigurationError(
                f"unknown preemption_policy {preemption_policy!r}; expected "
                f"one of {PREEMPTION_POLICIES} or None")
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 1:
            raise ConfigurationError("prefill_chunk_tokens must be >= 1")
        if prefill_budget_tokens is not None and prefill_budget_tokens < 1:
            raise ConfigurationError("prefill_budget_tokens must be >= 1")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}
        self.telemetry = _AdapterTelemetry("paged", telemetry)
        self.preemption_policy = preemption_policy
        self.preempted: List[Preempted] = []
        self._admit_counter = 0
        self._pos_limit = (None if getattr(app.spec, "rolling_window", False)
                           else cfg.seq_len)
        # chunked prefill: width ladder clamped at the chunk bucket so
        # chunk dispatches only ever run already-compiled ctx-bucket shapes
        self._chunk_widths = autobucketing.prefill_chunk_buckets(
            app.ctx_buckets, prefill_chunk_tokens)
        self.prefill_chunk_tokens = (
            min(prefill_chunk_tokens, self._chunk_widths[-1])
            if prefill_chunk_tokens is not None else self._chunk_widths[-1])
        self.prefill_budget_tokens = prefill_budget_tokens
        self._chunks: Dict[int, _ChunkState] = {}   # pending admissions
        self._unwritten: set = set()   # allocated blocks not fully written
        self._init_decode_path(pipeline_depth)
        # host-RAM KV spill tier (serving/fleet/kv_tier.py): evicted
        # prefix blocks spill their payloads host-side and re-admit via
        # async H2D restore instead of recompute-prefill (README "Fleet")
        self._kv_tier = kv_spill_tier
        self.host_stats["kv_spilled_blocks"] = 0
        self.host_stats["kv_restored_blocks"] = 0
        if kv_spill_tier is not None:
            app.kv_mgr.set_spill_hook(self._spill_block)
        # multi-LoRA adapter pool (serving/lora_pool.py, README "Multi-LoRA
        # serving"): per-request adapter names (meta "adapter" key) resolve
        # to pinned device slots at admission; every dispatch then carries
        # per-row adapter_ids so ONE step mixes rows from different
        # adapters (dispatches/step unchanged)
        if lora_pool is not None and lora_pool.app is not app:
            raise ConfigurationError(
                "lora_pool must be built over THIS adapter's application "
                "(its stacked slots back the per-row gather)")
        self._lora_pool = lora_pool
        self._lora_slots: Dict[int, int] = {}   # seq_id -> pinned slot
        self._lora_names: Dict[int, str] = {}
        self._adapter_shed = False
        if lora_pool is not None:
            self.host_stats["lora_rows"] = 0
            self.host_stats["lora_shed_requests"] = 0
        if speculation is not None:
            # deferred import: speculation/ imports this module
            from .speculation import SelfDraftProposer
            if isinstance(speculation, int):
                speculation = SelfDraftProposer(speculation)
        if ragged:
            # ragged unified dispatch (serving/ragged/, README "Ragged
            # dispatch"): ONE mixed prefill+decode+verify dispatch per
            # engine step; subsumes the prefill-budget serialization
            # point and composes with speculation=
            from .ragged import RaggedDispatchPath
            self._ragged = RaggedDispatchPath(self, speculation)
        elif speculation is not None:
            from .speculation import SpeculativeDecodePath
            self._spec = SpeculativeDecodePath(self, speculation)

    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]],
                     deadline_s: Union[None, float,
                                       Sequence[Optional[float]]] = None,
                     meta: Optional[Sequence[Any]] = None
                     ) -> Dict[int, int]:
        """Transactional admission: either every sequence is admitted, or
        every ``begin_sequence`` allocation from this call is rolled back
        and cache state is exactly as before (pool pressure may still
        preempt RUNNING sequences first — that eviction is reported via
        :meth:`take_preempted` and survives a subsequent rollback, since
        the preempted work is handed back to the engine either way).

        Prefill is chunked + packed (see the module docstring): the
        uncached suffixes walk through ``prefill_chunk_tokens``-sized
        ragged rows of shared ctx-bucket dispatches, so any prompt up to
        ``seq_len`` is admissible. With ``prefill_budget_tokens`` set the
        device work is deferred entirely: this call returns ``{}`` and
        ``step()`` delivers each first token when its final chunk lands.

        ``meta`` (optional, one opaque object per sequence) is a scheduler
        passthrough: the adapter never interprets it beyond reading a
        "tenant" key for telemetry labels, and hands it back verbatim on
        :class:`Preempted` records so a requeue needs no side tables."""
        from ..modules.block_kv_cache import cut_cached_at_unwritten
        _validate_admission(seq_ids, prompts, self.app.tpu_config.seq_len)
        for sid in seq_ids:
            if sid in self.seqs or sid in self._chunks:
                raise AdmissionError(f"seq_id {sid} already running")
        live_now = len(self.seqs) + len(self._chunks)
        if live_now + len(seq_ids) > self.batch:
            # typed, BEFORE any state change — without this the chunked
            # packer would happily admit (it packs <= batch rows per
            # dispatch and loops) and the overflow would only surface as
            # an untyped bucket error on the next decode step
            raise AdmissionError(
                f"admitting {len(seq_ids)} sequences would put "
                f"{live_now + len(seq_ids)} live/pending rows on a "
                f"compiled batch of {self.batch}")
        t0 = time.perf_counter()
        deadlines = _resolve_deadlines(deadline_s, len(seq_ids), t0)
        if meta is not None and len(meta) != len(seq_ids):
            raise AdmissionError("meta and seq_ids length mismatch")
        metas = list(meta) if meta is not None else [None] * len(seq_ids)
        app = self.app
        bs = app.kv_mgr.spec.block_size
        protect = frozenset(seq_ids)
        begun: List[int] = []
        try:
            for i, sid in enumerate(seq_ids):
                prompt = list(prompts[i])
                while True:
                    try:
                        blocks, c = app.kv_mgr.begin_sequence(sid, prompt)
                        begun.append(sid)
                        break
                    except CapacityError:
                        # never evict a sibling of this very call — the
                        # old monolithic path couldn't either (its seqs
                        # weren't running yet), and a same-call eviction
                        # would hollow out the return dict
                        victim = self._choose_victim(exclude=protect)
                        if victim is None:
                            raise
                        self._preempt(victim, reason="admission")
                # a hit on a block another pending/same-call sequence has
                # not fully written yet must be recomputed, not trusted
                n_hit = int(c) // bs
                c = cut_cached_at_unwritten(blocks, int(c), bs,
                                            self._unwritten)
                c = min(c, len(prompt) - 1)
                self._unwritten.update(blocks[n_hit:])
                if self._kv_tier is not None:
                    # swap instead of recompute: consecutive spilled
                    # full blocks past the device prefix hit restore by
                    # one batched H2D write; restored blocks stay in
                    # _unwritten until the call's first MATERIALIZED
                    # dispatch confirms the write chain, exactly like
                    # chunk-written blocks
                    c = self._restore_spilled(sid, prompt, blocks,
                                              int(c))
                self._admit_counter += 1
                self._chunks[sid] = _ChunkState(
                    prompt=prompt, done=int(c),
                    admit_idx=self._admit_counter, t0=t0,
                    deadline=deadlines[i], meta=metas[i])
                self._bind_adapter(sid, metas[i])
        except ServingError:
            self._rollback_admission(begun)
            raise
        except Exception as e:
            self._rollback_admission(begun)
            self.telemetry.on_step_failure("prefill",
                                           _common_tenant(map(_meta_tenant,
                                                              metas)))
            raise _trace_error(StepFailure(
                "paged admission failed; all allocations from this call "
                "were rolled back", phase="prefill",
                seq_ids=seq_ids, retry_safe=True)) from e
        if self.prefill_budget_tokens is not None or self._ragged is not None:
            # deferred: step() drives the chunks (ragged mode always
            # defers — the unified dispatch packs chunk rows WITH decode
            # rows, so admission never serializes its own device work)
            return {}
        cache_before = app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("prefill_step")
            while any(s in self._chunks for s in seq_ids):
                self._prefill_step(only=protect, defer_telemetry=True)
        except ServingError:
            # transactional: a chunk failure mid-call rolls back the WHOLE
            # call — sequences already past their final chunk included
            self._rollback_admission(begun)
            raise
        except Exception as e:
            self._rollback_admission(begun)
            self.telemetry.on_step_failure("prefill",
                                           _common_tenant(map(_meta_tenant,
                                                              metas)))
            raise _trace_error(StepFailure(
                "paged prefill failed; all allocations from this call were "
                "rolled back", phase="prefill", seq_ids=seq_ids,
                retry_safe=app.cache is cache_before)) from e
        # telemetry only once the WHOLE call is past rollback — a sibling
        # chunk failure must not leave spans/counters for requests that
        # were never admitted
        self.telemetry.on_add(seq_ids, prompts, t0, live=len(seq_ids),
                              padded=len(seq_ids), count_rows=False,
                              tenants=[_meta_tenant(m) for m in metas])
        return {s: self._ready.pop(s) for s in seq_ids}

    def release(self, seq_ids: Sequence[int]):
        if self._inflight is not None:
            self._stash_flush()
        proposer = self._active_proposer
        if proposer is not None:
            proposer.forget(seq_ids)
        for sid in seq_ids:
            self._ready.pop(sid, None)
            self._lora_release(sid)
            if sid in self._chunks:
                # mid-prefill: blocks whose content never fully landed
                # must not survive as prefix-cache hits
                self._abort_prefill_rows([sid])
                continue
            if sid in self.seqs:
                self.seqs.pop(sid)
                self._scratch = None       # its blocks are gone; see add
                if sid in self.app.kv_mgr.tables:
                    self.app.kv_mgr.end_sequence(sid)
        self.telemetry.on_release(seq_ids)

    # -- speculative decode (serving/speculation/) -------------------------
    def step(self, seq_ids: Optional[Sequence[int]] = None,
             token_room: Optional[Dict[int, int]] = None):
        """Non-speculative adapters: one decode step, {seq_id: token}
        (see the base class). With ``speculation=`` attached the step is
        draft-and-verify and returns {seq_id: [tokens]} with 1..k+1
        tokens per row; ``token_room`` (scheduler hook) caps each row's
        tokens-delivered for this step. With ``ragged=True`` every step —
        speculative or not — is ONE unified mixed dispatch through
        serving/ragged/ and returns {seq_id: [tokens]}.

        Degradation (resilience/controller.py): with the ragged path
        SHED the step falls back to two-phase dispatching — through the
        speculative path when a proposer is attached (its own shed flag
        composes), else the plain chunk-then-decode template, which
        already drives pending chunked admissions via
        ``_advance_prefill``. Greedy tokens are identical either way;
        only the dispatch count changes."""
        if self._ragged is not None:
            if not self._ragged_shed:
                return self._ragged.step(seq_ids, token_room)
            if self._ragged.spec_path is not None:
                return self._ragged.spec_path.step(seq_ids, token_room)
            return super().step(seq_ids)   # 1 token/row: room is honored
        if self._spec is not None:
            return self._spec.step(seq_ids, token_room)
        if token_room is not None:
            raise ConfigurationError(
                "token_room is a speculative-decode hook; build the "
                "adapter with speculation= or ragged=True to use it")
        return super().step(seq_ids)

    def step_many(self, num_steps: int,
                  seq_ids: Optional[Sequence[int]] = None
                  ) -> Dict[int, List[int]]:
        """Fused multi-step decode (base class). With ``speculation=``
        (or ``ragged=True``) attached, ``num_steps`` becomes a per-row
        TOKEN budget: the path runs unified engine steps — each one
        materialized dispatch — until every row has delivered its budget
        (rows with high accept rates finish in fewer dispatches; no row
        ever overshoots)."""
        path = self._ragged if self._ragged is not None else self._spec
        if path is None:
            return super().step_many(num_steps, seq_ids)
        if num_steps < 1:
            raise ConfigurationError("step_many requires num_steps >= 1")
        out: Dict[int, List[int]] = {}
        remaining: Dict[int, int] = {}
        targets = seq_ids                  # validated on the first pass only
        for _ in range(num_steps):
            live = _live_rows(self.seqs, targets, self._pending_ids())
            if seq_ids is not None:
                # rows preempted mid-loop must not fail later passes
                targets = [s for s in seq_ids
                           if s in self.seqs or s in self._chunks]
            ids = [s for s in live if remaining.get(s, num_steps) > 0]
            if not ids and not self._pending_ids():
                break
            room = {s: remaining.get(s, num_steps) for s in ids}
            # route through step() so the degradation shed flags apply
            # here too (a shed plain step returns {seq_id: token})
            res = self.step(ids, token_room=room)
            if not res and not ids:
                break                  # pending-only pass made no tokens
            for s, toks in res.items():
                toks = toks if isinstance(toks, list) else [toks]
                out.setdefault(s, []).extend(toks)
                remaining[s] = remaining.get(s, num_steps) - len(toks)
        return out

    # -- decode dispatch ---------------------------------------------------
    @property
    def _active_proposer(self):
        """The draft proposer of whichever decode path is engaged (the
        standalone speculative path OR the ragged unified path), None
        without speculation — release/preemption must drop per-sequence
        proposer state through exactly one of them."""
        return self._proposer_of_path()

    @property
    def speculation_shed(self) -> bool:
        return self._spec_shed

    @property
    def ragged_shed(self) -> bool:
        return self._ragged_shed

    def set_speculation_shed(self, shed: bool) -> None:
        """Degradation-controller actuator: clamp every draft window to
        width 1 so steps run the eager-equivalent width-1 verify — no
        draft dispatches, greedy tokens unchanged. Engaging it drops
        per-sequence proposer state through the ``_active_proposer``
        release path (stale draft caches must not survive the gap);
        Medusa/EAGLE re-seed incrementally on release, exactly like
        after an eviction. Fully reversible; a no-op without a
        proposer."""
        shed = bool(shed)
        if shed == self._spec_shed:
            return
        self._spec_shed = shed
        proposer = self._active_proposer
        if shed and proposer is not None and self.seqs:
            proposer.forget(list(self.seqs))

    def set_ragged_shed(self, shed: bool) -> None:
        """Degradation-controller actuator: route steps through the
        two-phase (chunk dispatch + decode/verify dispatch) template
        instead of the unified ragged dispatch — see :meth:`step`.
        Reversible; a no-op without ``ragged=True``."""
        self._ragged_shed = bool(shed)

    @property
    def adapter_shed(self) -> bool:
        return self._adapter_shed

    def set_adapter_shed(self, shed: bool) -> None:
        """Degradation-controller actuator: admit NEW adapter-tagged
        requests as base-model rows — no pool acquire, so the degraded
        engine spends zero swap H2D traffic and zero adapter-churn risk
        while burning. Already-running rows keep their pinned slots and
        finish under their adapter (a mid-stream model switch would be
        worse than the overload); shed admissions get their meta mapping
        annotated ``lora_shed=True`` so consumers can tell the degraded
        streams apart. Reversible; a no-op without a lora_pool."""
        self._adapter_shed = bool(shed)

    def _proposer_of_path(self):
        if self._spec is not None:
            return self._spec.proposer
        if self._ragged is not None:
            return self._ragged.proposer
        return None

    def _append_token(self, st: _SeqState, tok: int):
        st.last_token = tok
        st.tokens.append(tok)

    def _grow_for_step(self, live: List[int], n: int = 1) -> List[int]:
        return self._grow_with_preemption(live, n)

    def _rollback_step_growth(self, live: Sequence[int], n: int = 1):
        self._rollback_grow(live, n)

    def _unwind_inflight_growth(self, rec: _Inflight):
        if not rec.grown:
            return
        for s, st in zip(rec.live, rec.states):
            if self.seqs.get(s) is st and s in self.app.kv_mgr.tables:
                self.app.kv_mgr.shrink(s, rec.grown)

    def _scratch_for(self, live: Sequence[int]) -> _PagedScratch:
        app = self.app
        pad_to = autobucketing.get_target_bucket(app.batch_buckets,
                                                 len(live), kind="batch")
        width = app._bt_width_for(live)
        scr = self._scratch
        if (scr is None or scr.live != tuple(live) or scr.pad_to != pad_to
                or scr.width != width):
            scr = self._scratch = _PagedScratch(
                live, pad_to, width, app.kv_mgr.spec.block_size,
                seeds=[_meta_seed(self.seqs[s].meta) for s in live],
                aids=self._lora_aids(live))
        return scr

    def _dispatch_decode(self, scr: _PagedScratch, toks_dev=None):
        """Issue ONE paged decode step to the device without materializing
        any output (region lint: nxdi_lint host-sync pass). ``toks_dev``:
        previous dispatch's on-device tokens (pipelined feedback); None =
        host tokens from the scratch buffer."""
        ids = scr.ids if toks_dev is None else toks_dev
        kw = {"row_seeds": scr.seeds}
        if scr.aids is not None:
            kw["adapter_ids"] = scr.aids
        if self.app._steady_state:
            # attribute any unexpected recompile to the batched requests'
            # trace lanes (serving/warmup.py steady-state discipline)
            with self.app.request_context(self._traces_of(scr.live)):
                out = self.app._run_paged(ids, scr.pos, scr.slots, scr.bt,
                                          scr.last, **kw)
        else:
            out = self.app._run_paged(ids, scr.pos, scr.slots, scr.bt,
                                      scr.last, **kw)
        _async_fetch(out["tokens"])
        self.host_stats["dispatches"] += 1
        self.host_stats["device_steps"] += 1
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("dispatch.decode", cat="adapter",
                        engine=self.engine_name, rows=scr.b,
                        pad_to=scr.pad_to, seq_ids=list(scr.live),
                        pipelined=toks_dev is not None)
        return out

    def _run_many(self, live: List[int], num_steps: int):
        """Fused k-step paged decode (model_base.paged_decode_loop): blocks
        for the whole horizon are pre-allocated, slot mappings advance
        IN-GRAPH — one dispatch, one fetch, zero per-token host work."""
        app = self.app
        b = len(live)
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        bt = app.kv_mgr.block_table_array(live, app._bt_width_for(live))
        first = np.empty((b,), np.int32)
        pos = np.empty((b,), np.int32)
        seeds = np.empty((b,), np.int32)
        for i, s in enumerate(live):
            st = self.seqs[s]
            first[i] = st.last_token
            pos[i] = st.position
            seeds[i] = _meta_seed(st.meta)
        aids = self._lora_aids(live)
        if aids is not None:
            aids = np.asarray(aids, np.int32)
        if pad_to > b:
            first = _repeat_row0(first, pad_to)
            pos = _repeat_row0(pos, pad_to)
            bt = _repeat_row0(bt, pad_to)
            seeds = _repeat_row0(seeds, pad_to)
            if aids is not None:
                aids = _repeat_row0(aids, pad_to)
        kw = {"row_seeds": seeds}
        if aids is not None:
            kw["adapter_ids"] = aids
        cache_before = app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("decode_step")
            if app._steady_state:
                with app.request_context(self._traces_of(live)):
                    out = app._run_paged_loop(first, pos, bt, num_steps,
                                              **kw)
            else:
                out = app._run_paged_loop(first, pos, bt, num_steps, **kw)
            self.host_stats["dispatches"] += 1
            self.host_stats["device_steps"] += num_steps
            rec = _get_recorder()
            if rec.enabled:
                rec.instant("dispatch.decode_loop", cat="adapter",
                            engine=self.engine_name, rows=b, pad_to=pad_to,
                            steps=num_steps, seq_ids=list(live))
            toks = self._fetch_rows(out, b)
        except ServingError:
            self._rollback_grow(live, num_steps)
            raise
        except Exception as e:
            self._rollback_grow(live, num_steps)
            self.telemetry.on_step_failure("decode", self._tenant_of(live))
            raise _trace_error(StepFailure(
                "fused paged decode loop failed; KV growth was rolled back "
                "and positions were not advanced",
                phase="decode", seq_ids=tuple(live),
                retry_safe=app.cache is cache_before)) from e
        return toks, pad_to

    # -- scheduler hooks ---------------------------------------------------
    @property
    def running_ids(self) -> Tuple[int, ...]:
        """seq_ids with a decodable row (prefill finished), sorted."""
        return tuple(sorted(self.seqs))

    @property
    def pending_prefill_ids(self) -> Tuple[int, ...]:
        """seq_ids admitted but still mid-prefill (deferred/chunked
        admissions), in admission order."""
        return tuple(sorted(self._chunks,
                            key=lambda s: self._chunks[s].admit_idx))

    @property
    def free_capacity(self) -> int:
        """Batch slots an ``add_requests`` call could still admit into
        (running + pending rows count against the compiled batch)."""
        return self.batch - len(self.seqs) - len(self._chunks)

    def debug_state(self) -> Dict[str, Any]:
        """Base snapshot plus the paged-only view: pending chunked
        admissions with prefill progress, batch headroom, block-pool
        occupancy (incl. unwritten-block tracking) and uncollected
        preemption records."""
        state = super().debug_state()
        mgr = self.app.kv_mgr
        usable = mgr.spec.num_blocks - 1          # block 0 is the null block
        free = int(mgr.allocator.num_free)
        state.update({
            "pending_prefill": {
                int(s): {"done": int(c.done), "total": len(c.prompt),
                         "tenant": _meta_tenant(c.meta)}
                for s, c in self._chunks.items()},
            "free_capacity": self.free_capacity,
            "blocks": {"usable": usable, "free": free,
                       "in_use": usable - free,
                       "unwritten": len(self._unwritten)},
            "preempted_uncollected": [int(r.seq_id) for r in self.preempted],
            "ragged": self._ragged is not None,
        })
        if self._lora_pool is not None:
            state["lora"] = {
                "rows": {int(s): int(slot)
                         for s, slot in self._lora_slots.items()},
                "shed": self._adapter_shed,
                "pool": self._lora_pool.debug_state(),
            }
        return state

    def prefix_warmth(self, prompt: Sequence[int],
                      adapter: Optional[str] = None) -> int:
        """READ-ONLY probe: how many leading tokens of ``prompt`` an
        admission right now would serve from the prefix cache. Peeks the
        :class:`~..modules.block_kv_cache.BlockKVCacheManager` hash state
        without taking references or touching LRU order, and cuts the
        count at the first block whose writer has not landed yet (pending
        chunked admissions) — exactly the cut a real admission would
        apply. Schedulers use it to order admission batches warm-first;
        capped at ``len(prompt) - 1`` like admission itself (the final
        token always runs to produce the first sample). With a host KV
        spill tier attached, consecutive spilled full blocks past the
        device hit count as warm too (an admission would restore, not
        recompute, them) — the fleet router's affinity signal.

        ``adapter`` (optional, the request's named LoRA adapter) extends
        the signal with adapter residency: when a pool is attached and
        the adapter is already device-resident, the admission saves one
        swap's worth of H2D traffic, valued as
        ``prefill_chunk_tokens`` warm tokens (a swap stall is on the
        order of a chunk dispatch) so the router lands a tenant's
        requests where their adapter lives. Read-only both ways — the
        residency probe never touches the pool's LRU order."""
        from ..modules.block_kv_cache import cut_cached_at_unwritten
        cached, blocks = self.app.kv_mgr.probe_cached_tokens(prompt)
        if cached and self._unwritten:
            cached = cut_cached_at_unwritten(
                blocks, cached, self.app.kv_mgr.spec.block_size,
                self._unwritten)
        if self._kv_tier is not None:
            cached = self._tier_warmth(prompt, cached)
        warmth = min(cached, len(prompt) - 1)
        if (adapter is not None and self._lora_pool is not None
                and self._lora_pool.resident(adapter)):
            warmth += self.prefill_chunk_tokens
        return warmth

    # -- host-RAM KV spill tier (serving/fleet/kv_tier.py) -----------------
    def _spill_block(self, blk: int, content_hash: bytes) -> None:
        """Manager eviction hook: copy an LRU-evicted prefix block's
        payload device→host into the spill tier, keyed by its content
        chain hash. Best-effort by contract — a failure (including the
        ``kv_spill`` fault point) is swallowed and counted, never failing
        the allocation whose eviction triggered it. Skips blocks whose
        registered hash never had its content land (``_unwritten``)."""
        if blk in self._unwritten:
            return
        try:
            cache = self.app.cache
            self._kv_tier.spill(content_hash,
                                np.asarray(cache["k"][:, blk]),
                                np.asarray(cache["v"][:, blk]))
            self.host_stats["kv_spilled_blocks"] += 1
        except Exception:
            self._kv_tier.stats["spill_errors"] += 1

    def _tier_warmth(self, prompt: Sequence[int], cached: int) -> int:
        """Extend the device prefix-hit count with consecutive spilled
        full blocks an admission right now would restore instead of
        recompute (read-only; no recency touch)."""
        from ..modules.block_kv_cache import _hash_block
        bs = self.app.kv_mgr.spec.block_size
        parent = b""
        warm = cached
        for bi in range(len(prompt) // bs):
            parent = _hash_block(parent, list(prompt[bi * bs:(bi + 1) * bs]))
            if (bi + 1) * bs <= cached:
                continue                   # device-cached already
            if bi * bs != warm or not self._kv_tier.contains(parent):
                break
            warm = (bi + 1) * bs
        return warm

    def _restore_spilled(self, sid: int, prompt: Sequence[int],
                         blocks: Sequence[int], done: int) -> int:
        """Walk the prompt's full-block chain hashes past the (post-cut)
        device prefix hit through the spill tier; consecutive hits are
        re-admitted by ONE batched async H2D write and their tokens
        skipped from recompute-prefill. Returns the new ``done`` count
        (capped at ``len(prompt) - 1`` like prefix hits — the final token
        always runs to produce the first sample; a restored block that
        covers it is partially rewritten with identical values by the
        final chunk). The ``kv_restore`` fault point fires BEFORE the
        device write, so the transactional admission rollback is exact."""
        from ..modules.block_kv_cache import _hash_block
        tier = self._kv_tier
        bs = self.app.kv_mgr.spec.block_size
        limit = len(prompt) - 1
        parent = b""
        restores: List[Tuple[int, Any]] = []
        new_done = done
        for bi in range(len(prompt) // bs):
            parent = _hash_block(parent,
                                 list(prompt[bi * bs:(bi + 1) * bs]))
            if (bi + 1) * bs <= new_done:
                continue                   # device-cached already
            if bi * bs != new_done or new_done >= limit:
                break                      # mid-block cap or gap: stop
            payload = tier.get(parent)
            if payload is None:
                break
            restores.append((blocks[bi], payload))
            new_done = min((bi + 1) * bs, limit)
        if not restores:
            return done
        if _FAULTS.active:
            _FAULTS.fire("kv_restore")
        self._apply_block_payloads(restores)
        n_tok = new_done - done
        tier.note_restored(len(restores), n_tok)
        self.host_stats["kv_restored_blocks"] += len(restores)
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("kv.restore", cat="fleet", engine=self.engine_name,
                        seq_id=int(sid), blocks=len(restores),
                        tokens=n_tok)
        return new_done

    def _apply_block_payloads(self, restores) -> None:
        """One batched (asynchronously dispatched) H2D write placing
        spilled payloads into their freshly-allocated device blocks. The
        rebound cache feeds every subsequent dispatch, so the call's
        first materialized fetch orders after (and thereby confirms) the
        restore writes — a deferred device failure here surfaces at that
        fetch and rolls the admission back like any chunk failure."""
        idx = np.asarray([b for b, _ in restores], np.intp)
        k = np.stack([np.asarray(p["k"]) for _, p in restores], axis=1)
        v = np.stack([np.asarray(p["v"]) for _, p in restores], axis=1)
        cache = self.app.cache
        self.app.cache = {"k": cache["k"].at[:, idx].set(k),
                          "v": cache["v"].at[:, idx].set(v)}

    # -- multi-LoRA adapter pool (serving/lora_pool.py) --------------------
    def _bind_adapter(self, sid: int, meta: Any) -> None:
        """Resolve a request's named adapter (meta "adapter" key) to a
        pinned device slot at admission. With the ``shed_adapters``
        degradation actuator engaged the request is admitted as a
        base-model row instead — no acquire, no swap H2D traffic — and
        its meta mapping is annotated ``lora_shed=True`` so the stream's
        consumer can tell the degraded output apart. A typed acquire
        failure (CapacityError: every slot pinned; StepFailure: the swap
        itself failed, rolled back) propagates into the admission's
        transactional rollback — nothing is admitted."""
        if self._lora_pool is None:
            return
        name = _meta_adapter(meta)
        if name is None:
            return
        if self._adapter_shed:
            self.host_stats["lora_shed_requests"] += 1
            try:
                meta["lora_shed"] = True
            except TypeError:
                pass
            return
        slot = self._lora_pool.acquire(name)
        self._lora_slots[sid] = slot
        self._lora_names[sid] = name
        self.host_stats["lora_rows"] += 1

    def _lora_release(self, sid: int) -> None:
        """Unpin ``sid``'s adapter slot (release / preemption / admission
        rollback). Idempotent — rollback paths release blindly."""
        name = self._lora_names.pop(sid, None)
        if name is not None:
            self._lora_slots.pop(sid, None)
            self._lora_pool.release(name)

    def _lora_aids(self, sids) -> Optional[List[int]]:
        """Per-row device slots for a dispatch, or None without a pool —
        the adapter_ids kwarg is only ever passed when a pool is
        attached, keeping no-pool graphs byte-identical. Base-model rows
        (no adapter, or admitted shed) gather slot 0, the pinned zero
        adapter."""
        if self._lora_pool is None:
            return None
        return [self._lora_slots.get(s, 0) for s in sids]

    # -- preemption -------------------------------------------------------
    def preempt(self, seq_id: int, reason: str = "scheduler") -> Preempted:
        """Scheduler-driven eviction of one running or pending sequence:
        its blocks are reclaimed (never-written blocks invalidated, not
        freed as servable) and the :class:`Preempted` record — tokens so
        far, remaining deadline, meta passthrough — is returned AND queued
        for :meth:`take_preempted`. A pipelined in-flight token for the
        victim is dropped (the requeue replay regenerates it, same as
        pressure preemption). Raises :class:`SequenceStateError` for an
        unknown/released seq_id."""
        if seq_id not in self.seqs and seq_id not in self._chunks:
            raise SequenceStateError(
                f"cannot preempt seq_id {seq_id}: not running or pending")
        self._preempt(seq_id, reason)
        return self.preempted[-1]

    def take_preempted(self) -> List[Preempted]:
        """Drain :class:`Preempted` records accumulated since the last
        call. The engine re-queues each ``record.tokens`` as a new prompt;
        under greedy sampling the recomputed continuation is bit-identical
        to the uninterrupted run (a token still in the pipeline when its
        sequence is preempted is regenerated by the replay)."""
        out, self.preempted = self.preempted, []
        return out

    def _choose_victim(self, exclude=frozenset()) -> Optional[int]:
        if self.preemption_policy is None:
            return None
        cands = [(sid, st.admit_idx, len(st.tokens) - st.prompt_len)
                 for sid, st in self.seqs.items()
                 if st.running and sid not in exclude]
        # pending chunked admissions are victims too (zero generated
        # tokens: they lose the least decode work of anything live)
        cands += [(sid, cst.admit_idx, 0)
                  for sid, cst in self._chunks.items()
                  if sid not in exclude]
        return pick_victim(self.preemption_policy, cands)

    def _preempt(self, victim: int, reason: str):
        self._ready.pop(victim, None)      # replay regenerates it
        self._lora_release(victim)         # requeue re-acquires via meta
        proposer = self._active_proposer
        if proposer is not None:
            # stateful proposers (Medusa/EAGLE) must not carry the
            # victim's features into a re-admission under the same id
            proposer.forget((victim,))
        cst = self._chunks.pop(victim, None)
        if cst is not None:
            # half-prefilled victim: blocks not fully written must leave
            # the prefix cache (abort, not a plain free); the record's
            # tokens are the bare prompt — nothing was generated yet
            self._abort_pending(victim)
            tenant = _meta_tenant(cst.meta)
            self.preempted.append(Preempted(
                seq_id=victim, tokens=tuple(cst.prompt),
                prompt_len=len(cst.prompt), n_generated=0, reason=reason,
                deadline=cst.deadline, meta=cst.meta,
                trace_id=self._trace_preempt(victim, reason, tenant,
                                             pending=True,
                                             trace=_trace_of(cst.meta))))
            self.telemetry.on_preempt(victim, reason, tenant)
            return
        st = self.seqs.pop(victim)
        self._scratch = None               # victim's blocks are reclaimed
        if victim in self.app.kv_mgr.tables:
            self.app.kv_mgr.end_sequence(victim)
        tenant = _meta_tenant(st.meta)
        self.preempted.append(Preempted(
            seq_id=victim, tokens=tuple(st.tokens),
            prompt_len=st.prompt_len,
            n_generated=len(st.tokens) - st.prompt_len, reason=reason,
            deadline=st.deadline, meta=st.meta,
            trace_id=self._trace_preempt(victim, reason, tenant,
                                         trace=_trace_of(st.meta))))
        self.telemetry.on_preempt(victim, reason, tenant)

    def _trace_preempt(self, victim: int, reason: str, tenant: str,
                       pending: bool = False,
                       trace: Optional[str] = None) -> Optional[str]:
        rec = _get_recorder()
        if not rec.enabled:
            return None
        return rec.instant("preempt", cat="adapter",
                           engine=self.engine_name, seq_id=victim,
                           reason=reason, tenant=tenant, pending=pending,
                           trace=trace)

    def _grow_with_preemption(self, live: Sequence[int],
                              n: int = 1) -> List[int]:
        """Grow every live row's block list by ``n`` tokens, evicting
        victims per the policy when the pool is dry. Returns the rows
        still live (preempted ones removed). If eviction cannot free
        enough, all growth from this call is rolled back and the
        :class:`CapacityError` propagates."""
        app = self.app
        live = list(live)
        queue = list(live)
        grown: List[int] = []
        while queue:
            s = queue[0]
            try:
                app.kv_mgr.grow(s, n)
            except CapacityError:
                victim = self._choose_victim()
                if victim is None:
                    for g in grown:
                        app.kv_mgr.shrink(g, n)
                    raise
                self._preempt(victim, reason="grow")
                for lst in (queue, live, grown):
                    if victim in lst:
                        lst.remove(victim)
                continue
            queue.pop(0)
            grown.append(s)
        return live

    def _rollback_grow(self, live: Sequence[int], n: int = 1):
        for s in live:
            self.app.kv_mgr.shrink(s, n)

    def _rollback_admission(self, seq_ids: Sequence[int]):
        """Abort every sequence begun by the failing add_requests call:
        frees its blocks and purges never-written content hashes from the
        prefix cache (the free count is restored exactly; prefix-HIT
        blocks whose content predates the call stay resident). Sequences
        that already finished their prefill inside the call are unwound
        too — admission is all-or-nothing.

        Reverse admission order matters: when prompts within the call
        share a prefix, later sequences prefix-HIT blocks the first one
        allocated (and hashed) moments earlier — unwinding in reverse
        makes the ORIGINATING sequence's abort the last dereference, so
        its invalidate (not a later sibling's plain free) retires the
        never-written hash."""
        for sid in reversed(list(seq_ids)):
            self._chunks.pop(sid, None)
            self._ready.pop(sid, None)
            self._lora_release(sid)
            if self.seqs.pop(sid, None) is not None:
                self._scratch = None
            self._abort_pending(sid)
        self.telemetry.on_admission_rollback()

    # -- chunked, packed, schedulable prefill ------------------------------
    def _pending_ids(self):
        return self._chunks.keys()

    def _advance_prefill(self, seq_ids=None):
        if self._chunks:
            self._prefill_step(budget=self.prefill_budget_tokens,
                               target=seq_ids)

    def _prefill_step(self, budget: Optional[int] = None, only=None,
                      target=None, defer_telemetry: bool = False):
        """ONE packed chunk dispatch: pending sequences (admission order)
        each contribute their next uncached-suffix chunk as a ragged row
        of a single ctx-bucket ``_run_paged`` call, bounded by ``budget``
        real prompt tokens (None = unbounded). Sequences whose FINAL chunk
        lands graduate to running rows with their first token stashed in
        ``_ready``; intermediate samples are discarded. A dispatch failure
        rolls every sequence packed in THIS dispatch back
        (:meth:`~..modules.block_kv_cache.BlockKVCacheManager.abort_sequence`)
        and raises a typed :class:`StepFailure`. ``defer_telemetry`` (the
        transactional add_requests path) suppresses per-sequence admission
        telemetry — the caller reports the whole call only once it is past
        rollback. ``target`` is the step call's explicit seq_ids set (None
        = all): an expired pending admission is raised only when targeted,
        merely skipped from packing otherwise."""
        chunks = self._chunks
        order = sorted(chunks, key=lambda s: chunks[s].admit_idx)
        if only is not None:
            order = [s for s in order if s in only]
        now = time.perf_counter()
        expired = [s for s in order if chunks[s].deadline is not None
                   and now >= chunks[s].deadline]
        if expired:
            hit = (expired if target is None
                   else [s for s in expired if s in set(target)])
            if hit:
                fresh = [s for s in hit if not chunks[s].expired_reported]
                for s in fresh:
                    chunks[s].expired_reported = True
                self.telemetry.on_deadline(
                    fresh, [_meta_tenant(chunks[s].meta) for s in fresh])
                raise _trace_error(DeadlineExceeded(
                    f"seq_ids {hit} exceeded their wall-clock deadline "
                    "mid-prefill; release() them (or re-queue with a fresh "
                    "budget) and step again", seq_ids=hit))
            # expired but not targeted by this step: don't burn budget on
            # them, and don't stall the targeted healthy rows
            order = [s for s in order if s not in expired]
        rows: List[Tuple[int, int, int, bool]] = []
        left = float("inf") if budget is None else int(budget)
        for s in order:
            if len(rows) == self.batch or left < 1:
                break
            st = chunks[s]
            n = int(min(len(st.prompt) - st.done,
                        self.prefill_chunk_tokens, left))
            rows.append((s, st.done, n, st.done + n == len(st.prompt)))
            left -= n
        if not rows:
            return
        seq_list = tuple(s for s, *_ in rows)
        final_rows = [(i, s) for i, (s, _, _, fin) in enumerate(rows)
                      if fin]
        # tenant attribution captured BEFORE any rollback pops the chunk
        # state (failure counters + trace events need it afterwards)
        row_tenant = _common_tenant(_meta_tenant(chunks[s].meta)
                                    for s in seq_list)
        cache_before = self.app.cache
        t0_chunk = time.perf_counter()
        try:
            if _FAULTS.active:
                _FAULTS.fire("prefill_chunk")
            packed = self._pack_prefill_rows(rows)
            out = self._dispatch_prefill_chunk(packed,
                                               fetch=bool(final_rows))
            # materialize INSIDE the try (dispatch is asynchronous): a
            # genuine device failure surfacing at the fetch must still be
            # wrapped and rolled back here. Intermediate-only dispatches
            # fetch nothing — their samples are discarded unmaterialized.
            new = (self._fetch_prefill_tokens(out) if final_rows
                   else None)
        except ServingError as e:
            self._abort_prefill_rows(seq_list)
            _trace_error(e)                # attach a timeline id in place
            raise
        except Exception as e:
            self._abort_prefill_rows(seq_list)
            self.telemetry.on_step_failure("prefill", row_tenant)
            raise _trace_error(StepFailure(
                "chunked prefill dispatch failed; every partially-"
                "prefilled sequence packed in it was rolled back",
                phase="prefill", seq_ids=seq_list,
                retry_safe=self.app.cache is cache_before)) from e
        rec = _get_recorder()
        if rec.enabled:
            rec.complete("dispatch.prefill_chunk", t0_chunk, cat="adapter",
                         engine=self.engine_name, seq_ids=list(seq_list),
                         rows=len(rows), width=int(packed[0].shape[1]),
                         tokens=sum(n for _, _, n, _ in rows),
                         final_seq_ids=[s for _, s in final_rows],
                         tenant=row_tenant)
        bs = self.app.kv_mgr.spec.block_size
        for s, _, n, _ in rows:
            chunks[s].done += n
        if final_rows:
            # this dispatch's tokens were MATERIALIZED, and the donated
            # cache chain orders every earlier dispatch before it — all
            # covered blocks are now confirmed written. Unfetched
            # intermediate dispatches confirm nothing: a genuine async
            # device failure in one surfaces at a later fetch, and the
            # rollback there must still find their blocks in _unwritten
            # (or their allocate-time hashes would be freed as valid).
            for s2, cst in chunks.items():
                self._unwritten.difference_update(
                    self.app.kv_mgr.tables[s2][:cst.done // bs])
        pad_rows, width = packed[0].shape
        real = sum(n for _, _, n, _ in rows)
        self.host_stats["prefill_real_tokens"] += real
        self.host_stats["prefill_padded_tokens"] += pad_rows * width
        self.telemetry.on_prefill_chunk(len(rows), pad_rows, real,
                                        pad_rows * width)
        for i, s in final_rows:
            st = chunks.pop(s)
            self._unwritten.difference_update(self.app.kv_mgr.tables[s])
            tok = int(new[i, 0])
            self.seqs[s] = _SeqState(
                position=len(st.prompt), last_token=tok,
                tokens=list(st.prompt) + [tok],
                prompt_len=len(st.prompt), admit_idx=st.admit_idx,
                deadline=st.deadline, meta=st.meta)
            self._scratch = None   # live set grew; see add_requests note
            self._ready[s] = tok
            if not defer_telemetry:
                self.telemetry.on_add([s], [st.prompt], st.t0, live=1,
                                      padded=1, count_rows=False,
                                      tenants=[_meta_tenant(st.meta)])

    def _pack_prefill_rows(self, rows):
        """Build the ragged packed-chunk inputs: one row per sequence,
        positions at each row's own suffix offset, slots through its own
        block table; width = smallest ctx bucket covering the longest
        chunk, batch padded by repeating row 0 (the usual invariant)."""
        from ..modules.block_kv_cache import slots_from_table
        app = self.app
        b = len(rows)
        width = autobucketing.get_target_bucket(
            self._chunk_widths, max(n for _, _, n, _ in rows), kind="ctx")
        sids = [s for s, *_ in rows]
        bt = app.kv_mgr.block_table_array(sids, app._bt_width_for(sids))
        ids_w = np.zeros((b, width), np.int32)
        pos_w = np.zeros((b, width), np.int32)
        slot_pos = np.full((b, width), -1, np.int32)
        last = np.zeros((b,), np.int32)
        for i, (s, lo, n, fin) in enumerate(rows):
            st = self._chunks[s]
            ids_w[i, :n] = st.prompt[lo:lo + n]
            pos_w[i] = lo + np.arange(width, dtype=np.int32)
            slot_pos[i, :n] = pos_w[i, :n]
            if fin:
                last[i] = n - 1
        slots = slots_from_table(bt, slot_pos, app.kv_mgr.spec.block_size)
        seeds = np.asarray([_meta_seed(self._chunks[s].meta) for s in sids],
                           np.int32)
        aids = self._lora_aids(sids)
        if aids is not None:
            aids = np.asarray(aids, np.int32)
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        if pad_to > b:
            seeds = _repeat_row0(seeds, pad_to)
            if aids is not None:
                aids = _repeat_row0(aids, pad_to)
        return _pad_paged_rows(pad_to, ids_w, pos_w, slots, bt, last) \
            + (seeds, aids)

    def _dispatch_prefill_chunk(self, packed, fetch: bool = True):
        """Issue ONE packed prefill-chunk dispatch without materializing
        any output (region lint: nxdi_lint host-sync pass) — the final-
        chunk token fetch happens in the caller, one async hop behind.
        ``fetch=False`` (intermediate-only dispatch) skips even the async
        device-to-host copy: those samples are never read."""
        ids_p, pos_p, slots_p, bt_p, last_p, seeds_p, aids_p = packed
        kw = {"row_seeds": seeds_p}
        if aids_p is not None:
            kw["adapter_ids"] = aids_p
        out = self.app._run_paged(ids_p, pos_p, slots_p, bt_p, last_p, **kw)
        if fetch:
            _async_fetch(out["tokens"])
        self.host_stats["prefill_dispatches"] += 1
        return out

    def _fetch_prefill_tokens(self, out) -> np.ndarray:
        """Materialize a final-chunk dispatch's sampled tokens (the one
        blocking sync of a packed admission; async-prefetched)."""
        t0 = time.perf_counter()
        toks = np.asarray(out["tokens"])
        t1 = time.perf_counter()
        self.host_stats["prefill_blocking_fetches"] += 1
        self.host_stats["prefill_blocked_s"] += t1 - t0
        rec = _get_recorder()
        if rec.enabled:
            rec.complete("fetch.tokens", t0, cat="adapter", t1=t1,
                         engine=self.engine_name, phase="prefill")
        return toks.reshape(toks.shape[0], -1)

    def _drop_unwritten(self, sid):
        """Retire ``sid``'s EXCLUSIVE blocks from the unwritten set. Any
        block another still-pending sequence shares stays: a shared prefix
        block keeps its registered hash while any holder references it,
        so its unwritten-ness must keep being tracked until the last
        pending holder confirms the write or tears down."""
        tbl = set(self.app.kv_mgr.tables.get(sid, ()))
        if not tbl:
            return
        for other in self._chunks:
            if other != sid:
                tbl.difference_update(self.app.kv_mgr.tables.get(other, ()))
        self._unwritten -= tbl

    def _abort_pending(self, sid):
        """Tear down one pending/rolled-back sequence's allocations: every
        block whose content never fully landed — the sequence's own
        unwritten tail AND prefix hits on another pending writer's
        still-unwritten blocks — is invalidated so the prefix cache can
        never serve it; fully-written blocks are freed as valid. The
        caller pops the ``_ChunkState`` first."""
        if sid not in self.app.kv_mgr.tables:
            return
        unwritten = set(self.app.kv_mgr.tables[sid]) & self._unwritten
        self._drop_unwritten(sid)
        self.app.kv_mgr.abort_sequence(sid, unwritten=unwritten)

    def _abort_prefill_rows(self, sids):
        """Transactional rollback of partially-prefilled sequences: drop
        their chunk state and abort their allocations — blocks whose
        content never fully landed are invalidated (they must not be
        served as prefix hits), fully-written blocks freed normally.
        REVERSE admission order, like :meth:`_rollback_admission`: the
        originating sequence's invalidate must be the last dereference of
        an intra-call shared-prefix hash."""
        for s in reversed(list(sids)):
            self._chunks.pop(s, None)
            self._abort_pending(s)
