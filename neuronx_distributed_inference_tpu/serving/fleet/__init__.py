"""Fleet layer — the tier above one ServingEngine (ROADMAP item 3).

Three composable prongs, all CPU-verifiable (README "Fleet" is the
contract):

  * :class:`~.router.EngineRouter` — spreads requests across N
    :class:`~..engine.scheduler.ServingEngine` replicas with
    prefix-affinity routing (warmest ``prefix_warmth``, tie-broken by
    least queue depth from ``debug_state()``), a per-replica health
    state machine (healthy/draining/backing_off/probation/dead —
    retry-safe step failures quarantine a replica behind exponential
    backoff with seeded jitter, a clean probing pass re-admits it
    without operator ``undrain()``), and requeue-on-replica-failure
    riding the ``Preempted`` requeue contract (failover streams stay
    bit-identical under greedy decoding);
  * :class:`~.kv_tier.HostKVSpillTier` — a bounded host-RAM tier under
    the device block pool: LRU-evicted prefix blocks spill their
    payloads host-side (content-hash keyed) and re-admit via async H2D
    restore instead of recompute-prefill;
  * :mod:`~.handoff` — disaggregated prefill: a prefill-role engine
    captures a JSON-safe handoff record (serialized ``Preempted`` + the
    spilled KV block payloads) that a decode-role engine admits through
    the ordinary transactional ``add_requests`` path, bit-identical to a
    single-engine run.

Elastic on top (ISSUE 17): :func:`~.handoff.migrate` moves a
MID-DECODE stream between replicas with its KV (the handoff wire form,
live), :class:`~.autoscaler.FleetAutoscaler` closes the loop on fleet
signals (queue / SLO burn / admission headroom) to resize the replica
set with precompile-first admission and drain-by-migration retirement,
and :mod:`~.loadgen` generates the seeded workloads
(``diurnal_ramp`` / ``tenant_burst`` / ``heavy_tail``) that
``bench.py --autoscale-report`` and the chaos campaign replay.
"""

from .aggregator import FleetMetricsAggregator
from .autoscaler import FleetAutoscaler
from .handoff import (HANDOFF_SCHEMA, admit_handoff, capture_handoff,
                      handoff_from_json, handoff_to_json, migrate)
from .kv_tier import HostKVSpillTier
from .loadgen import Arrival, diurnal_ramp, heavy_tail, tenant_burst
from .router import (BACKING_OFF, DEAD, DRAINING, HEALTHY, PROBATION,
                     EngineRouter)

__all__ = [
    "EngineRouter", "HEALTHY", "DRAINING", "BACKING_OFF", "PROBATION",
    "DEAD",
    "HostKVSpillTier", "FleetMetricsAggregator", "FleetAutoscaler",
    "HANDOFF_SCHEMA", "capture_handoff", "admit_handoff", "migrate",
    "handoff_to_json", "handoff_from_json",
    "Arrival", "diurnal_ramp", "tenant_burst", "heavy_tail",
]
