"""Host-RAM KV spill tier — the second storage tier under the device
block pool (ROADMAP item 3 prong b).

Device HBM holds the live block pool; when the prefix cache evicts a
resident block under LRU pressure (``BlockAllocator._pop_block``), its
payload would be gone and a later warm-prefix admission would pay a full
recompute-prefill. With a :class:`HostKVSpillTier` attached to the paged
adapter (``PagedEngineAdapter(kv_spill_tier=...)``):

  * **spill** — the manager's eviction hook
    (:meth:`~...modules.block_kv_cache.BlockKVCacheManager.set_spill_hook`)
    copies the evicted block's K/V payload device→host into this bounded
    pool, keyed by the block's CONTENT CHAIN HASH (the same blake2b chain
    the Python allocator and the handoff records use). Content-hash keying
    makes staleness impossible: a chain hash names a deterministic KV
    payload (same weights, same tokens → same values), so a stored payload
    can never be wrong, only absent.
  * **restore** — at admission, after the device prefix-cache hit is
    cut, the adapter walks the prompt's remaining full-block chain hashes
    through :meth:`HostKVSpillTier.get`; consecutive hits are re-admitted
    by ONE batched async H2D write instead of recompute-prefill, turning
    a recompute-preemption into a swap. Restored streams are bit-identical
    to recomputed ones (pinned by ``tests/test_fleet.py``).

The pool is bounded (``max_blocks``) with oldest-touched-first eviction;
every spill/restore/evict flows through ``nxdi_kv_spill_*`` /
``nxdi_kv_restore_*`` metrics, the always-on :attr:`stats` counters, and
``kv.spill`` / ``kv.restore`` flight-recorder events. The disaggregated
prefill handoff (``fleet/handoff.py``) rides the same pool:
:meth:`seed` loads a received record's block payloads so the decode-side
admission restores them through the identical path.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

from ...resilience.errors import ConfigurationError
from ...resilience.faults import FAULTS as _FAULTS
from ...telemetry import get_registry
from ...telemetry import metrics as tmetrics
from ...telemetry.trace import get_recorder as _get_recorder

__all__ = ["HostKVSpillTier"]


class HostKVSpillTier:
    """Bounded host-RAM pool of spilled KV block payloads, keyed by
    content chain hash. One tier may back several adapters/replicas —
    content-hash keying makes sharing safe (and is exactly how the fleet
    bench shares warmth across replicas of the same weights)."""

    def __init__(self, max_blocks: int = 256, telemetry=None):
        if max_blocks < 1:
            raise ConfigurationError("max_blocks must be >= 1")
        self.max_blocks = max_blocks
        self._telemetry = telemetry
        # hash -> {"k": np (L, Bs, H, D), "v": np (L, Bs, H, D)}
        self._pool: "OrderedDict[bytes, Dict[str, np.ndarray]]" = \
            OrderedDict()
        # always-on host counters (feed bench.py --fleet-load)
        self.stats: Dict[str, int] = {
            "spilled": 0, "restored": 0, "evicted": 0, "hits": 0,
            "misses": 0, "seeded": 0, "spill_errors": 0}

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._pool)

    @property
    def nbytes(self) -> int:
        """Host bytes currently held by the pooled payloads."""
        return sum(p["k"].nbytes + p["v"].nbytes
                   for p in self._pool.values())

    def contains(self, content_hash: bytes) -> bool:
        """Read-only membership probe (no LRU touch) — the tier-aware
        ``prefix_warmth`` extension uses it per queued request."""
        return content_hash in self._pool

    # -- write side --------------------------------------------------------
    def spill(self, content_hash: bytes, k: np.ndarray,
              v: np.ndarray) -> None:
        """Park one evicted block's payload. Deduplicates by hash (a
        re-spill only refreshes recency); evicts the oldest-touched
        payload past ``max_blocks``. The ``kv_spill`` fault point fires
        here — the adapter's eviction hook treats a spill failure as
        best-effort (counted, never failing the allocation that evicted
        the block)."""
        if _FAULTS.active:
            _FAULTS.fire("kv_spill")
        if content_hash in self._pool:
            self._pool.move_to_end(content_hash)
            return
        self._pool[content_hash] = {"k": np.asarray(k), "v": np.asarray(v)}
        self.stats["spilled"] += 1
        evicted = 0
        while len(self._pool) > self.max_blocks:
            self._pool.popitem(last=False)
            evicted += 1
        self.stats["evicted"] += evicted
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("kv.spill", cat="fleet",
                        hash=content_hash.hex()[:16],
                        pool_blocks=len(self._pool))
        reg = self._registry()
        if reg is not None:
            tmetrics.kv_spill_blocks_counter(reg).inc()
            if evicted:
                tmetrics.kv_spill_evictions_counter(reg).inc(evicted)
            tmetrics.kv_spill_bytes_gauge(reg).set(self.nbytes)

    def seed(self, payloads: Dict[bytes, Dict[str, np.ndarray]]) -> None:
        """Load received handoff payloads (decode-side admission path);
        counted separately from pressure spills, same bound/eviction."""
        for h, p in payloads.items():
            fresh = h not in self._pool
            self._pool[h] = {"k": np.asarray(p["k"]),
                             "v": np.asarray(p["v"])}
            self._pool.move_to_end(h)
            if fresh:
                self.stats["seeded"] += 1
        evicted = 0
        while len(self._pool) > self.max_blocks:
            self._pool.popitem(last=False)
            evicted += 1
        self.stats["evicted"] += evicted
        reg = self._registry()
        if reg is not None:
            if evicted:
                tmetrics.kv_spill_evictions_counter(reg).inc(evicted)
            tmetrics.kv_spill_bytes_gauge(reg).set(self.nbytes)

    # -- read side ---------------------------------------------------------
    def get(self, content_hash: bytes
            ) -> Optional[Dict[str, np.ndarray]]:
        """The payload for ``content_hash`` (touching its recency), or
        None. Payloads stay resident after a hit — a shared prefix may be
        restored by many admissions."""
        p = self._pool.get(content_hash)
        if p is None:
            self.stats["misses"] += 1
            return None
        self._pool.move_to_end(content_hash)
        self.stats["hits"] += 1
        return p

    def note_restored(self, n_blocks: int, n_tokens: int) -> None:
        """Restore accounting, called by the adapter after its batched
        H2D write was issued (stats + metrics live here so every consumer
        of one shared tier aggregates in one place)."""
        self.stats["restored"] += n_blocks
        reg = self._registry()
        if reg is not None:
            tmetrics.kv_restore_blocks_counter(reg).inc(n_blocks)
            tmetrics.kv_restore_tokens_counter(reg).inc(n_tokens)

    def _registry(self):
        if self._telemetry is not None:
            return self._telemetry if self._telemetry.enabled else None
        reg = get_registry()
        return reg if reg.enabled else None
