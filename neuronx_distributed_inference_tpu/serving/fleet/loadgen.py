"""Seeded workload profiles for fleet benches and chaos campaigns
(ISSUE 17 prong c).

Pure host code, no device or JAX dependency: each profile returns a
time-sorted list of :class:`Arrival` records — *when* a request lands,
*what* prompt it carries, *how many* tokens it wants, and *whose*
tenant it bills to — that ``bench.py --autoscale-report`` replays
against an :class:`~.router.EngineRouter` on a virtual clock. All
randomness flows through one seeded :class:`random.Random`, so a
profile is a pure function of its arguments: the committed
``artifacts/bench_autoscale_r17.json`` is reproducible bit-for-bit.

Three shapes, matching the traffic families the autoscaler must
survive:

  * :func:`diurnal_ramp` — a half-sine ramp from ``base_rate`` up to
    ``peak_rate`` and back (one "day"): drives ≥1 scale-up on the way
    up and ≥1 scale-down on the way back down, with the hysteresis
    dead band visible in between;
  * :func:`tenant_burst` — steady background traffic plus one tenant
    slamming in a rectangular burst: exercises per-tenant SLO burn
    feeding the merged burn index;
  * :func:`heavy_tail` — Poisson arrivals whose prompt lengths follow
    a bounded Pareto: a few giant prompts amid many small ones, the
    classic admission-headroom killer.

Arrival times come from an inhomogeneous Poisson process simulated by
thinning against the profile's peak rate — standard, and exact for
piecewise-smooth rate functions.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Callable, List, Sequence, Tuple

from ...resilience.errors import ConfigurationError

__all__ = ["Arrival", "diurnal_ramp", "tenant_burst", "heavy_tail"]


@dataclass(frozen=True)
class Arrival:
    """One request of a generated workload: submit at ``t`` seconds
    (virtual, offset from profile start)."""
    t: float
    prompt: Tuple[int, ...]
    max_new_tokens: int
    tenant: str


def _check_common(duration_s: float, vocab: int,
                  prompt_len: Tuple[int, int],
                  max_new_tokens: int) -> None:
    if duration_s <= 0:
        raise ConfigurationError("duration_s must be > 0")
    if vocab < 2:
        raise ConfigurationError("vocab must be >= 2")
    lo, hi = prompt_len
    if not (1 <= lo <= hi):
        raise ConfigurationError(
            f"prompt_len must be (lo, hi) with 1 <= lo <= hi, got "
            f"{prompt_len}")
    if max_new_tokens < 1:
        raise ConfigurationError("max_new_tokens must be >= 1")


def _prompt(rng: random.Random, vocab: int,
            prompt_len: Tuple[int, int]) -> Tuple[int, ...]:
    n = rng.randint(prompt_len[0], prompt_len[1])
    return tuple(rng.randrange(vocab) for _ in range(n))


def _thinned_poisson(rng: random.Random, duration_s: float,
                     rate_fn: Callable[[float], float],
                     peak_rate: float) -> List[float]:
    """Arrival times of an inhomogeneous Poisson process with intensity
    ``rate_fn(t)`` on [0, duration), by thinning a homogeneous process
    at ``peak_rate`` (Lewis & Shedler): exact as long as
    ``rate_fn <= peak_rate`` everywhere, which the callers guarantee by
    construction."""
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak_rate)
        if t >= duration_s:
            return out
        if rng.random() * peak_rate <= rate_fn(t):
            out.append(t)


def diurnal_ramp(duration_s: float = 60.0, *, base_rate: float = 0.5,
                 peak_rate: float = 8.0, vocab: int = 512,
                 prompt_len: Tuple[int, int] = (4, 12),
                 max_new_tokens: int = 8, tenant: str = "default",
                 seed: int = 0) -> List[Arrival]:
    """One synthetic "day": request rate follows
    ``base + (peak - base) * sin(pi * t / duration)`` — quiet, ramp to
    peak mid-window, ramp back down. The canonical autoscaler workload:
    the up-slope must trigger a scale-up, the down-slope a scale-down,
    and the dead band in between must hold the fleet steady."""
    _check_common(duration_s, vocab, prompt_len, max_new_tokens)
    if not 0 < base_rate < peak_rate:
        raise ConfigurationError(
            f"need 0 < base_rate < peak_rate (got {base_rate}, "
            f"{peak_rate})")
    rng = random.Random(seed)

    def rate(t: float) -> float:
        return base_rate + (peak_rate - base_rate) * math.sin(
            math.pi * t / duration_s)

    return [Arrival(t=t, prompt=_prompt(rng, vocab, prompt_len),
                    max_new_tokens=max_new_tokens, tenant=tenant)
            for t in _thinned_poisson(rng, duration_s, rate, peak_rate)]


def tenant_burst(duration_s: float = 60.0, *, base_rate: float = 1.0,
                 burst_rate: float = 8.0, burst_start_s: float = 20.0,
                 burst_len_s: float = 10.0, vocab: int = 512,
                 prompt_len: Tuple[int, int] = (4, 12),
                 max_new_tokens: int = 8,
                 tenants: Sequence[str] = ("bg", "burst"),
                 seed: int = 0) -> List[Arrival]:
    """Steady background traffic from ``tenants[0]`` at ``base_rate``,
    plus ``tenants[1]`` slamming a rectangular burst of ``burst_rate``
    for ``burst_len_s`` starting at ``burst_start_s`` — the shape that
    makes one tenant's SLO burn spike while the fleet average looks
    fine, exercising the merged-burn (max, not mean) scale-up signal."""
    _check_common(duration_s, vocab, prompt_len, max_new_tokens)
    if base_rate <= 0 or burst_rate <= 0:
        raise ConfigurationError("rates must be > 0")
    if not 0 <= burst_start_s < duration_s or burst_len_s <= 0:
        raise ConfigurationError(
            "burst window must start inside [0, duration_s) with "
            "burst_len_s > 0")
    if len(tenants) != 2:
        raise ConfigurationError(
            "tenants must be (background, burster) — exactly 2 names")
    rng = random.Random(seed)
    bg = [Arrival(t=t, prompt=_prompt(rng, vocab, prompt_len),
                  max_new_tokens=max_new_tokens, tenant=tenants[0])
          for t in _thinned_poisson(rng, duration_s,
                                    lambda t: base_rate, base_rate)]
    burst_end = min(burst_start_s + burst_len_s, duration_s)
    burst = [Arrival(t=t, prompt=_prompt(rng, vocab, prompt_len),
                     max_new_tokens=max_new_tokens, tenant=tenants[1])
             for t in _thinned_poisson(
                 rng, duration_s,
                 lambda t: (burst_rate
                            if burst_start_s <= t < burst_end else 0.0),
                 burst_rate)]
    return sorted(bg + burst, key=lambda a: a.t)


def heavy_tail(duration_s: float = 60.0, *, rate: float = 2.0,
               vocab: int = 512, alpha: float = 1.5,
               min_prompt: int = 4, max_prompt: int = 48,
               max_new_tokens: int = 8, tenant: str = "default",
               seed: int = 0) -> List[Arrival]:
    """Poisson arrivals whose prompt lengths follow a bounded Pareto
    (``P(L > x) ~ x^-alpha`` truncated to [min_prompt, max_prompt]):
    mostly small prompts with rare giants — the shape that drains
    admission headroom (blocks AND slots) in lumps rather than
    smoothly, exercising the free-slots scale-up signal."""
    _check_common(duration_s, vocab, (min_prompt, max_prompt),
                  max_new_tokens)
    if rate <= 0:
        raise ConfigurationError("rate must be > 0")
    if alpha <= 0:
        raise ConfigurationError("alpha must be > 0 (tail exponent)")
    rng = random.Random(seed)
    out: List[Arrival] = []
    for t in _thinned_poisson(rng, duration_s, lambda t: rate, rate):
        # inverse-CDF sample of a bounded Pareto on [min, max]
        u = rng.random()
        lo, hi = float(min_prompt), float(max_prompt)
        x = (lo ** -alpha - u * (lo ** -alpha - hi ** -alpha)) \
            ** (-1.0 / alpha)
        n = max(min_prompt, min(max_prompt, int(round(x))))
        prompt = tuple(rng.randrange(vocab) for _ in range(n))
        out.append(Arrival(t=t, prompt=prompt,
                           max_new_tokens=max_new_tokens, tenant=tenant))
    return out
