"""Replicated-engine router — the fleet front tier (ROADMAP item 3
prong a).

An :class:`EngineRouter` owns N named :class:`~..engine.scheduler.
ServingEngine` replicas and presents one submit/stream surface above
them:

  * **prefix-affinity routing** — a request goes to the HEALTHY replica
    whose ``adapter.prefix_warmth(tokens)`` is highest (with a host KV
    spill tier attached, spilled warmth counts too), tie-broken by least
    load read from the replica's ``debug_state()`` (queue depth, then
    active requests) — the same snapshot ``GET /v1/debug/state`` serves;
  * **health states** — ``healthy`` (routable), ``draining`` (no new
    admissions, running/queued work finishes; :meth:`drain` /
    :meth:`undrain`), ``dead`` (failed or closed; never routed again).
    A replica whose engine raises an unrecoverable
    :class:`~...resilience.errors.StepFailure` — or turns up closed — is
    marked dead automatically by :meth:`run_pass`;
  * **requeue on replica failure** — every in-flight request of a dead
    replica is re-submitted to a surviving one riding the
    :class:`~...resilience.preemption.Preempted` requeue contract
    (``admission_kwargs()``): the recompute prompt is the original
    prompt plus every token already delivered, so under greedy decoding
    the stitched fleet stream is bit-identical to an uninterrupted run
    (pinned by ``tests/test_fleet.py``).

The router is synchronous like the engine (:meth:`run_pass` /
:meth:`run_until_drained` drive it); callers get ordinary
:class:`~..engine.streams.TokenStream` objects whose tokens survive
failovers. Routing/drain/requeue decisions land on the flight recorder
(``fleet.route`` / ``fleet.drain``) and the ``nxdi_fleet_*`` metrics.

**Elastic fleet** (ISSUE 17): the replica set is no longer static —
:meth:`add_replica` / :meth:`remove_replica` resize the rotation (the
:class:`~.autoscaler.FleetAutoscaler` attached via ``autoscaler=`` is
consulted once per :meth:`run_pass` and drives them closed-loop),
:meth:`drain` gains a ``mode="migrate"`` that MOVES running sequences
to surviving replicas via live decode→decode migration
(:func:`~.handoff.migrate`) instead of letting drain throw warm KV
away, and :meth:`rebalance` defragments prefix-affinity hotspots by
migrating streams off the most-loaded replica.
"""

from __future__ import annotations

import contextlib
import itertools
import random
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ...resilience.errors import (ConfigurationError, ReplicaUnavailable,
                                  ServingError, StepFailure)
from ...resilience.preemption import Preempted
from ...telemetry import get_registry, set_registry
from ...telemetry import metrics as tmetrics
from ...telemetry.request_trace import new_trace_id, trace_of
from ...telemetry.trace import get_recorder as _get_recorder
from ..engine.streams import TokenStream
from .aggregator import FleetMetricsAggregator

__all__ = ["EngineRouter", "HEALTHY", "DRAINING", "BACKING_OFF",
           "PROBATION", "DEAD"]

#: Replica health states (the README "Degradation & chaos" state
#: machine):
#:   healthy     — routable for new admissions
#:   draining    — no new admissions; running AND already-queued work
#:                 finishes normally (``undrain`` returns it to healthy)
#:   backing_off — quarantined after retry-safe step failures; not
#:                 driven and not routable until its
#:                 exponential-backoff-with-jitter timer expires
#:   probation   — backoff expired; the next ``run_pass`` is a PROBE —
#:                 a clean pass re-admits it (healthy), another
#:                 retry-safe failure escalates the backoff, and
#:                 ``max_replica_failures`` consecutive failures (or
#:                 any non-retry-safe failure) escalate to dead
#:   dead        — failed unrecoverably, retry-exhausted, or closed;
#:                 its in-flight requests were requeued elsewhere
HEALTHY, DRAINING, DEAD = "healthy", "draining", "dead"
BACKING_OFF, PROBATION = "backing_off", "probation"


@dataclass
class _Replica:
    name: str
    engine: Any
    state: str = HEALTHY
    # retry/backoff bookkeeping (the ReplicaHealth state machine)
    failures: int = 0              # consecutive retry-safe failures
    backoff_s: float = 0.0         # current backoff interval (pre-jitter)
    backoff_until: float = 0.0     # absolute perf_counter() gate
    quarantines: int = 0
    was_draining: bool = False     # restore DRAINING after a probe pass


@dataclass
class _FleetRequest:
    """Router-side record of one request: the immutable spec plus the
    mutable binding to whichever replica currently serves it."""
    request_id: str
    prompt: List[int]
    max_new_tokens: int
    tenant: str
    priority: int
    deadline: Optional[float]          # absolute perf_counter(), or None
    stop_tokens: tuple
    stream: TokenStream                # the fleet-level stream
    replica: str = ""
    inner: Optional[TokenStream] = None
    pumped: int = 0                    # tokens taken from current inner
    n_requeues: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)


class EngineRouter:
    """Prefix-affinity router over named ServingEngine replicas.

    ``replicas`` maps replica name -> engine (an iterable of engines gets
    auto-names ``r0..rN-1``). ``max_requeues`` bounds how many replica
    failures one request may survive before its stream fails typed.

    ``metrics_registries`` (optional) maps replica name -> a dedicated
    :class:`~...telemetry.MetricsRegistry`: the router then scopes the
    process-global registry to that replica's own while driving it
    (submit / run_pass / failover resubmit — the engine reads the global
    registry at call time), so each replica accumulates its OWN series in
    one process exactly as N processes would, and
    :attr:`EngineRouter.aggregator` serves the fleet-wide merged
    exposition (every series labeled ``replica=<name>``) behind
    ``GET /v1/metrics``. Without it, replicas share the global registry
    and ``aggregator`` is None."""

    def __init__(self, replicas, *, max_requeues: int = 2,
                 metrics_registries: Optional[Dict[str, Any]] = None,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 backoff_multiplier: float = 2.0,
                 backoff_jitter: float = 0.25,
                 quarantine_after: int = 2,
                 max_replica_failures: int = 5, seed: int = 0,
                 autoscaler: Optional[Any] = None):
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ConfigurationError(
                "backoff_base_s must be > 0 and <= backoff_max_s")
        if backoff_multiplier < 1.0:
            raise ConfigurationError("backoff_multiplier must be >= 1")
        if not 0 <= backoff_jitter < 1:
            raise ConfigurationError("backoff_jitter must be in [0, 1)")
        if quarantine_after < 1 or max_replica_failures < 1:
            raise ConfigurationError(
                "quarantine_after and max_replica_failures must be >= 1")
        if not isinstance(replicas, dict):
            replicas = {f"r{i}": e for i, e in enumerate(replicas)}
        if not replicas:
            raise ConfigurationError("EngineRouter needs >= 1 replica")
        for name, eng in replicas.items():
            if not hasattr(eng, "run_pass") or not hasattr(eng, "adapter"):
                raise ConfigurationError(
                    f"replica {name!r} is not a ServingEngine surface")
        self.replicas: Dict[str, _Replica] = {
            name: _Replica(name, eng) for name, eng in replicas.items()}
        self.max_requeues = max_requeues
        if metrics_registries is not None:
            unknown = set(metrics_registries) - set(self.replicas)
            missing = set(self.replicas) - set(metrics_registries)
            if unknown or missing:
                # partial coverage is worse than none: an uncovered
                # replica's series land in the process-global registry
                # and the aggregated scrape silently omits them
                raise ConfigurationError(
                    "metrics_registries must cover every replica exactly "
                    f"(unknown: {sorted(unknown)}, missing: "
                    f"{sorted(missing)}; replicas: {sorted(self.replicas)})")
        self._registries = metrics_registries
        self.aggregator = (FleetMetricsAggregator(metrics_registries)
                           if metrics_registries else None)
        self._requests: Dict[str, _FleetRequest] = {}
        self._done: List[str] = []     # newest finished ids (bounded)
        self._traces: Dict[str, str] = {}   # request_id -> trace (bounded)
        self._rid_counter = itertools.count()
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.backoff_multiplier = backoff_multiplier
        self.backoff_jitter = backoff_jitter
        self.quarantine_after = quarantine_after
        self.max_replica_failures = max_replica_failures
        self._rng = random.Random(seed)    # seeded jitter: reproducible
        self.stats: Dict[str, int] = {
            "routed": 0, "affinity_warm": 0, "affinity_cold": 0,
            "requeues": 0, "replica_failures": 0, "completed": 0,
            "drains": 0, "quarantines": 0, "probes": 0,
            "probe_readmits": 0, "migrations": 0, "migrate_failures": 0,
            "migrated_kv_tokens": 0, "migrate_drains": 0, "rebalances": 0}
        if autoscaler is not None and not hasattr(autoscaler, "update"):
            raise ConfigurationError(
                "autoscaler= must expose update(router) — pass a "
                "serving.fleet.autoscaler.FleetAutoscaler")
        self.autoscaler = autoscaler

    @contextlib.contextmanager
    def _scoped_registry(self, name: str):
        """Swap the replica's dedicated registry into the global slot
        while its engine runs (no-op without ``metrics_registries``).
        The engine and adapter read ``get_registry()`` at call time, so
        this is all the isolation one process needs."""
        if self._registries is None or name not in self._registries:
            yield
            return
        prev = get_registry()
        set_registry(self._registries[name])
        try:
            yield
        finally:
            set_registry(prev)

    # -- public surface ----------------------------------------------------
    def submit(self, tokens: Sequence[int], max_new_tokens: int, *,
               tenant: str = "default", priority: int = 0,
               deadline_s: Optional[float] = None,
               stop_tokens: Sequence[int] = (),
               request_id: Optional[str] = None,
               adapter: Optional[str] = None) -> TokenStream:
        """Route one request to the warmest healthy replica and return
        its fleet-level :class:`TokenStream` (tokens survive replica
        failovers). Raises :class:`ReplicaUnavailable` when no replica is
        healthy; replica-side admission errors propagate unchanged.

        ``adapter`` names the request's LoRA adapter (README "Multi-LoRA
        serving"): it rides ``meta["adapter"]`` to the replica's engine
        AND extends the affinity score — a replica whose pool already has
        the adapter device-resident scores warmer, so a tenant's requests
        land where their adapter lives instead of forcing a swap."""
        tokens = [int(t) for t in tokens]
        rid = (request_id if request_id is not None
               else f"f{next(self._rid_counter)}")
        if rid in self._requests:
            raise ServingError(f"request_id {rid!r} already in flight")
        now = time.perf_counter()
        # the FLEET owns the request trace: the replica engine continues
        # this id rather than minting its own, so one trace follows the
        # request across failovers and replicas
        tid = new_trace_id()
        req = _FleetRequest(
            request_id=rid, prompt=tokens, max_new_tokens=max_new_tokens,
            tenant=tenant, priority=priority,
            deadline=None if deadline_s is None else now + deadline_s,
            stop_tokens=tuple(int(t) for t in stop_tokens),
            stream=TokenStream(rid, tenant),
            meta={"request_id": rid, "tenant": tenant,
                  "priority": priority, "trace": tid})
        if adapter is not None:
            req.meta["adapter"] = adapter
        name, warmth = self._pick(tokens, adapter=adapter)
        rep = self.replicas[name]
        kw = {} if adapter is None else {"adapter": adapter}
        with self._scoped_registry(name):
            req.inner = rep.engine.submit(
                tokens, max_new_tokens, tenant=tenant, priority=priority,
                deadline_s=deadline_s, stop_tokens=stop_tokens,
                request_id=rid, trace_id=tid, **kw)
        req.replica = name
        req.stream._cancel_cb = lambda: self.cancel(rid)
        self._requests[rid] = req
        self._traces[rid] = tid
        while len(self._traces) > 1024:      # bounded, like _done
            del self._traces[next(iter(self._traces))]
        self._note_route(req, name, warmth, requeue=False)
        return req.stream

    def trace_id_of(self, request_id: str) -> Optional[str]:
        """The fleet-level trace id of a request submitted through this
        router (None for unknown/aged-out ids) — the lookup behind
        ``GET /v1/debug/trace/<id>`` on a fleet frontend."""
        return self._traces.get(request_id)

    def export_slo(self) -> None:
        """Export every replica engine's SLO gauges into that replica's
        OWN registry — called by the frontend's ``GET /v1/metrics`` path
        so the fleet-aggregated scrape carries the ``nxdi_slo_*`` series
        too. A no-op without ``metrics_registries``: the gauges carry no
        replica label of their own, so exporting N trackers into one
        shared registry would let the last replica silently overwrite
        the others."""
        if self._registries is None:
            return
        for name, rep in self.replicas.items():
            slo = getattr(rep.engine, "slo", None)
            if slo is not None:
                slo.export(self._registries[name])

    def registry_of(self, engine) -> Optional[Any]:
        """The scoped registry of the replica wrapping ``engine`` (id
        comparison), or None — the frontend uses this to export ITS
        engine's scrape-time SLO gauges into the right source."""
        if self._registries is None:
            return None
        for name, rep in self.replicas.items():
            if rep.engine is engine:
                return self._registries[name]
        return None

    def memory_report(self) -> Dict[str, Any]:
        """Per-replica HBM ledgers (serving/warmup.py
        :func:`~..warmup.memory_ledger`), keyed by replica name — the
        fleet section of ``GET /v1/debug/memory``. Each ledger's gauges
        are refreshed into that replica's scoped registry (when
        ``metrics_registries`` is set), so the fleet-aggregated scrape
        carries ``nxdi_hbm_*{replica=...}`` series. A replica that is
        dead — or DIES between enumeration and its ledger walk (closed
        engine, vanished adapter) — reports a ``{"state": "dead"}``
        stub instead of sinking the endpoint; other ledger failures
        report ``{"error": ...}``."""
        out: Dict[str, Any] = {}
        for name, rep in sorted(list(self.replicas.items())):
            if rep.state == DEAD or getattr(rep.engine, "closed", False):
                out[name] = {"state": "dead"}
                continue
            try:
                from ..warmup import memory_ledger
                reg = (self._registries[name]
                       if self._registries is not None else None)
                out[name] = memory_ledger(rep.engine.adapter, registry=reg)
            except ServingError:
                # the replica died under us mid-report (released
                # adapter, torn-down engine): stub it like DEAD rather
                # than failing the whole fleet endpoint
                out[name] = {"state": "dead"}
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def cancel(self, request_id: str) -> bool:
        """Cancel wherever the request currently lives; returns False for
        unknown/finished ids."""
        req = self._requests.get(request_id)
        if req is None or req.stream.finished:
            return False
        rep = self.replicas.get(req.replica)
        if rep is not None and rep.state != DEAD:
            rep.engine.cancel(request_id)
        self._finish(req, "cancelled", req.stream.cancelled_error())
        return True

    @property
    def has_work(self) -> bool:
        return bool(self._requests)

    def run_pass(self) -> int:
        """One fleet pass: drive every live replica's scheduling pass —
        quarantining replicas whose pass needed retry-safe step retries
        (the ReplicaHealth state machine: healthy → backing_off →
        probation → healthy | dead), marking unrecoverably failed or
        closed ones dead — then pump replica streams into the fleet
        streams, requeueing any request whose replica died. Returns
        tokens delivered to fleet streams."""
        now = time.perf_counter()
        for rep in list(self.replicas.values()):
            if rep.state == DEAD:
                continue
            if getattr(rep.engine, "closed", False):
                self._mark_dead(rep, reason="closed")
                continue
            if rep.state == BACKING_OFF:
                if now < rep.backoff_until:
                    continue           # quarantined: not driven, not routed
                rep.state = PROBATION
                self.stats["probes"] += 1
                self._trace_state(rep, reason="probe")
            probing = rep.state == PROBATION
            before = self._step_retries_of(rep)
            try:
                with self._scoped_registry(rep.name):
                    rep.engine.run_pass()
            except StepFailure as e:
                if e.retry_safe:
                    self._quarantine(rep, now)
                    continue
                self._mark_dead(rep, reason="step_failure")
                continue
            if self._step_retries_of(rep) > before:
                # the engine absorbed retry-safe step failures this pass
                # — the replica is flaky; back off before burning more
                # passes (and, on probation, the probe failed)
                self._quarantine(rep, now)
            elif probing:
                # a clean probing pass re-admits the replica — no
                # operator undrain() needed
                rep.state = DRAINING if rep.was_draining else HEALTHY
                rep.was_draining = False
                rep.failures = 0
                rep.backoff_s = 0.0
                self.stats["probe_readmits"] += 1
                self._trace_state(rep, reason="probe_readmit")
            else:
                rep.failures = 0       # healthy pass resets the streak
        delivered = 0
        for req in list(self._requests.values()):
            delivered += self._pump(req)
        if self.autoscaler is not None:
            # closed-loop consult, once per fleet pass: the controller
            # reads the fresh post-pump signals (queue depth, merged
            # SLO burn, admission headroom) and may resize the rotation
            self.autoscaler.update(self)
        return delivered

    def backoff_wait_s(self) -> float:
        """The shortest remaining quarantine backoff (capped at
        ``backoff_max_s``), 0.0 when nothing is backing off — drivers
        (:meth:`run_until_drained`, the chaos campaign) sleep this out
        when a pass makes no progress instead of spinning their pass
        budgets down while the wall clock barely advances."""
        now = time.perf_counter()
        waits = [rep.backoff_until - now
                 for rep in self.replicas.values()
                 if rep.state == BACKING_OFF]
        if not waits:
            return 0.0
        return min(max(min(waits), 0.0), self.backoff_max_s)

    def run_until_drained(self, max_passes: int = 100000) -> None:
        passes = 0
        while self.has_work:
            delivered = self.run_pass()
            passes += 1
            if passes >= max_passes:
                raise ServingError(
                    f"fleet made no progress in {max_passes} passes — "
                    "router wedged (file a bug with the fleet stats)")
            if delivered:
                continue
            wait = self.backoff_wait_s()
            if wait:
                time.sleep(wait)

    # -- health ------------------------------------------------------------
    def drain(self, name: str, mode: str = "finish") -> int:
        """Stop routing NEW requests to ``name``. ``mode="finish"`` (the
        default): running and queued work finishes normally on the
        replica. ``mode="migrate"``: every RUNNING in-flight request is
        additionally MOVED to a surviving healthy replica via live
        decode→decode migration (:func:`~.handoff.migrate`) — the KV
        travels, nothing recomputes from scratch; requests that cannot
        migrate (still queued / mid-prefill, no eligible destination)
        keep finishing on the draining replica, counted in
        ``stats["migrate_failures"]``. Returns the number migrated.

        Drain-while-quarantined is explicit: draining a ``backing_off``
        / ``probation`` replica records the intent (``was_draining``) —
        the drain completes when the probe re-admits the replica
        (landing it in ``draining``, not ``healthy``) or escalates to
        dead per ``max_replica_failures``. Idempotent; a dead replica
        stays dead."""
        if mode not in ("finish", "migrate"):
            raise ConfigurationError(
                f"drain mode {mode!r} is not one of ('finish', "
                "'migrate')")
        rep = self._replica(name)
        if rep.state == HEALTHY:
            rep.state = DRAINING
            self.stats["drains"] += 1
            self._trace_state(rep, reason="drain")
        elif rep.state in (BACKING_OFF, PROBATION):
            # explicit drain-while-quarantined: the probe re-admit path
            # honors was_draining, so the drain completes as soon as
            # the replica re-enters rotation (or it escalates to dead)
            if not rep.was_draining:
                rep.was_draining = True
                self.stats["drains"] += 1
                self._trace_state(rep, reason="drain_quarantined")
        elif rep.state == DEAD:
            return 0
        if mode != "migrate":
            return 0
        self.stats["migrate_drains"] += 1
        from .handoff import migrate
        from ...resilience.errors import HandoffError
        moved = 0
        for req in list(self._requests.values()):
            if req.replica != name or req.stream.finished:
                continue
            try:
                migrate(self, req.request_id, src=name)
                moved += 1
            except HandoffError:
                # not migratable (queued, mid-prefill, no destination,
                # or an injected fault): it keeps serving on the
                # draining replica — drain still completes normally
                self.stats["migrate_failures"] += 1
        return moved

    def undrain(self, name: str) -> None:
        """Return a draining replica to healthy (dead ones stay dead)."""
        rep = self._replica(name)
        if rep.state == DRAINING:
            rep.state = HEALTHY
            self._trace_state(rep, reason="undrain")

    # -- elastic fleet -----------------------------------------------------
    def add_replica(self, name: str, engine, *,
                    registry: Optional[Any] = None) -> None:
        """Join a new replica to the rotation, healthy and routable
        immediately — the :class:`~.autoscaler.FleetAutoscaler` calls
        this only AFTER the replica's precompile walk reported zero
        compiles, so admission never exposes traffic to compile stalls.
        When the router scopes per-replica registries, ``registry`` is
        required (a fresh :class:`~...telemetry.MetricsRegistry` is
        auto-created if omitted) and the fleet aggregator starts merging
        it; without scoped registries ``registry`` must stay None."""
        if name in self.replicas:
            raise ConfigurationError(
                f"replica name {name!r} already in the fleet; have "
                f"{sorted(self.replicas)}")
        if not hasattr(engine, "run_pass") or not hasattr(engine, "adapter"):
            raise ConfigurationError(
                f"replica {name!r} is not a ServingEngine surface")
        if self._registries is None:
            if registry is not None:
                raise ConfigurationError(
                    "this router does not scope per-replica registries "
                    "(metrics_registries=None) — registry= must be None")
        else:
            if registry is None:
                from ...telemetry import MetricsRegistry
                registry = MetricsRegistry()
            self._registries[name] = registry
            if self.aggregator is not None:
                self.aggregator.sources[name] = registry
        self.replicas[name] = _Replica(name, engine)
        self._trace_state(self.replicas[name], reason="join")

    def remove_replica(self, name: str) -> None:
        """Drop a replica from the rotation entirely (vs. :meth:`drain`,
        which keeps it parked). Refused while any in-flight fleet
        request is still bound to it — drain/migrate first. The scoped
        registry (and its aggregator source) leaves with it, so the
        fleet scrape stops advertising the retired replica."""
        rep = self._replica(name)
        bound = [rid for rid, req in self._requests.items()
                 if req.replica == name and not req.stream.finished]
        if bound:
            raise ServingError(
                f"replica {name!r} still serves {len(bound)} in-flight "
                f"request(s) ({sorted(bound)[:4]}...) — drain(mode="
                "'migrate') before remove_replica")
        self._trace_state(rep, reason="remove")
        del self.replicas[name]
        if self._registries is not None:
            self._registries.pop(name, None)
        if self.aggregator is not None:
            self.aggregator.sources.pop(name, None)

    def rebalance(self, max_moves: int = 4) -> int:
        """Defragment prefix-affinity hotspots: while the most-loaded
        healthy replica carries at least 2 more running streams than the
        least-loaded one, live-migrate one stream from hot to cold
        (warmest-on-destination first, so the move costs the least
        recompute-adjacent warmth). Bounded by ``max_moves`` per call;
        returns how many streams moved. Streams that refuse to migrate
        (mid-prefill, fault-injected) are skipped, not retried."""
        if max_moves < 1:
            raise ConfigurationError("rebalance max_moves must be >= 1")
        from ...resilience.errors import HandoffError
        from .handoff import migrate
        moved = 0
        skipped: set = set()
        while moved < max_moves:
            counts: Dict[str, int] = {
                n: 0 for n, rep in self.replicas.items()
                if rep.state == HEALTHY}
            for req in self._requests.values():
                if req.replica in counts and not req.stream.finished:
                    counts[req.replica] += 1
            hot = max(sorted(counts), key=lambda n: counts[n],
                      default=None)
            # only the DESTINATION needs a spill tier (the payload
            # lands through KVSpillTier.seed); any replica can donate
            sinks = {n: c for n, c in counts.items()
                     if n != hot
                     and hasattr(self.replicas[n].engine.adapter,
                                 "_kv_tier")}
            if hot is None or not sinks:
                break
            cold = min(sorted(sinks), key=lambda n: sinks[n])
            if counts[hot] - counts[cold] < 2:
                break
            candidates = [req for req in self._requests.values()
                          if req.replica == hot
                          and not req.stream.finished
                          and req.request_id not in skipped]
            if not candidates:
                break
            progressed = False
            for req in candidates:
                try:
                    migrate(self, req.request_id, src=hot, dst=cold)
                except HandoffError:
                    skipped.add(req.request_id)
                    continue
                moved += 1
                progressed = True
                break
            if not progressed:
                break
        if moved:
            self.stats["rebalances"] += 1
        return moved

    def _pick_migration_dst(self, req: _FleetRequest,
                            exclude: str) -> str:
        """The destination replica for one live migration: healthy, not
        the source, and spill-tier-capable (the KV payload lands through
        ``KVSpillTier.seed``); warmest on the sequence-so-far first,
        then least load, then stable name order. Raises
        :class:`~...resilience.errors.HandoffError` when no replica
        qualifies (the un-migrated stream keeps serving on the source)."""
        from ...resilience.errors import HandoffError
        seq = list(req.prompt) + list(req.stream.tokens)
        best = None
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if name == exclude or rep.state != HEALTHY:
                continue
            if getattr(rep.engine, "closed", False):
                continue
            if not hasattr(rep.engine.adapter, "_kv_tier"):
                continue               # nowhere to land the KV payload
            try:
                warmth = int(rep.engine.adapter.prefix_warmth(seq))
            except ServingError:
                warmth = 0
            load = getattr(rep.engine, "load", None)
            if load is None:
                ds = rep.engine.debug_state()
                load = (ds["queue"]["depth"], len(ds["active"]))
            key = (-warmth, tuple(load), name)
            if best is None or key < best[0]:
                best = (key, name)
        if best is None:
            raise HandoffError(
                f"no migration destination for {req.request_id!r}: no "
                f"healthy spill-tier-capable replica besides {exclude!r}")
        return best[1]

    def _replica(self, name: str) -> _Replica:
        rep = self.replicas.get(name)
        if rep is None:
            raise ConfigurationError(f"unknown replica {name!r}; have "
                                     f"{sorted(self.replicas)}")
        return rep

    def _step_retries_of(self, rep: _Replica) -> int:
        """The replica engine's cumulative retry-safe step-failure count
        — the health sensor. ``ServingEngine`` absorbs retry-safe
        :class:`StepFailure`s internally (``stats["step_retries"]``), so
        the router watches the counter's delta per pass instead of an
        exception that never propagates. 0 for foreign engine surfaces
        (they surface failures by raising, handled in :meth:`run_pass`)."""
        return int(getattr(rep.engine, "stats", {}).get("step_retries", 0))

    def _quarantine(self, rep: _Replica, now: float) -> None:
        """One retry-safe failure observed: extend the consecutive
        streak, escalate the exponential backoff (with seeded jitter so
        N replicas quarantined by one incident do not probe in
        lockstep), and park the replica in ``backing_off`` — or
        escalate to dead once the streak exhausts
        ``max_replica_failures``."""
        rep.failures += 1
        if rep.failures >= self.max_replica_failures:
            self._mark_dead(rep, reason="retry_exhausted")
            return
        if (rep.state in (HEALTHY, DRAINING)
                and rep.failures < self.quarantine_after):
            return                     # the engine's own retry may heal it
        if rep.state == DRAINING:
            rep.was_draining = True
        rep.backoff_s = (self.backoff_base_s if rep.backoff_s == 0.0
                         else min(rep.backoff_s * self.backoff_multiplier,
                                  self.backoff_max_s))
        jitter = 1.0 + self._rng.uniform(-self.backoff_jitter,
                                         self.backoff_jitter)
        rep.backoff_until = now + rep.backoff_s * jitter
        rep.state = BACKING_OFF
        rep.quarantines += 1
        self.stats["quarantines"] += 1
        self._trace_state(rep, reason="quarantine")

    def _mark_dead(self, rep: _Replica, reason: str) -> None:
        if rep.state == DEAD:
            return
        rep.state = DEAD
        self.stats["replica_failures"] += 1
        self._trace_state(rep, reason=reason)
        if not getattr(rep.engine, "closed", False):
            # escalated dead with a LIVE engine (retry-exhausted): cancel
            # its in-flight fleet requests so their device state is
            # reclaimed — the inner "cancelled" finish from a DEAD
            # replica is exactly what _pump requeues onto a survivor
            for req in list(self._requests.values()):
                if req.replica == rep.name and req.inner is not None \
                        and not req.inner.finished:
                    rep.engine.cancel(req.request_id)
        if not any(r.state == HEALTHY for r in self.replicas.values()):
            # the operator page: nothing left to route to — surface the
            # stranded depth instead of letting them learn from a shed
            rec = _get_recorder()
            if rec.enabled:
                rec.instant("fleet.all_dead", cat="fleet",
                            replica=rep.name, reason=reason,
                            in_flight=len(self._requests))

    def _trace_state(self, rep: _Replica, reason: str) -> None:
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("fleet.drain", cat="fleet", replica=rep.name,
                        state=rep.state, reason=reason,
                        failures=rep.failures,
                        backoff_s=round(rep.backoff_s, 4))

    # -- routing -----------------------------------------------------------
    def _pick(self, tokens: Sequence[int],
              adapter: Optional[str] = None):
        """(replica name, its warmth) for a new admission: warmest
        prefix first, then least load (queue depth, then active count —
        the same numbers ``debug_state()`` serves, read through the
        lightweight ``ServingEngine.load`` accessor), then stable name
        order. A replica whose engine turns up closed is marked dead
        here rather than routed to (its in-flight work fails over on the
        next pass). ``adapter`` extends warmth with LoRA residency —
        a replica whose pool holds the named adapter device-resident
        scores a swap's worth of tokens warmer (read-only probe)."""
        best = None
        for name in sorted(self.replicas):
            rep = self.replicas[name]
            if rep.state != HEALTHY:
                continue
            if getattr(rep.engine, "closed", False):
                self._mark_dead(rep, reason="closed")
                continue
            try:
                if adapter is not None:
                    warmth = int(rep.engine.adapter.prefix_warmth(
                        tokens, adapter=adapter))
                else:
                    warmth = int(rep.engine.adapter.prefix_warmth(tokens))
            except ServingError:
                warmth = 0
            except TypeError:
                # foreign adapter surface without the adapter= extension
                warmth = int(rep.engine.adapter.prefix_warmth(tokens))
            load = getattr(rep.engine, "load", None)
            if load is None:           # foreign engine surface
                ds = rep.engine.debug_state()
                load = (ds["queue"]["depth"], len(ds["active"]))
            key = (-warmth, tuple(load), name)
            if best is None or key < best[0]:
                best = (key, name, warmth)
        if best is None:
            by_state: Dict[str, int] = {}
            for rep in self.replicas.values():
                by_state[rep.state] = by_state.get(rep.state, 0) + 1
            raise ReplicaUnavailable(
                "no healthy replica (states: "
                + ", ".join(f"{k}={v}" for k, v in sorted(by_state.items()))
                + f"); {len(self._requests)} in-flight request(s) pending "
                "on this router — shed or retry elsewhere")
        return best[1], best[2]

    def _note_route(self, req: _FleetRequest, name: str, warmth: int,
                    requeue: bool) -> None:
        self.stats["routed"] += 1
        affinity = "warm" if warmth > 0 else "cold"
        self.stats[f"affinity_{affinity}"] += 1
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("fleet.route", cat="fleet",
                        request_id=req.request_id, replica=name,
                        warmth=warmth, affinity=affinity, requeue=requeue)
        reg = get_registry()
        if reg.enabled:
            tmetrics.fleet_routed_counter(reg).inc(replica=name,
                                                   affinity=affinity)

    # -- delivery / failover -----------------------------------------------
    def _pump(self, req: _FleetRequest) -> int:
        """Move newly generated tokens from the replica stream into the
        fleet stream; forward normal finishes; requeue when the replica
        FAILED the request — an error finish, or a "cancelled" finish
        issued by a dead/closed replica's teardown (a router-initiated
        cancel finishes the FLEET stream first, so it never reaches
        here)."""
        inner = req.inner
        if inner is None or req.stream.finished:
            return 0
        n_new = inner.n_tokens - req.pumped      # O(1) idle-pass check
        if n_new:
            for tok in inner.tokens_from(req.pumped):
                req.stream.put(tok)
            req.pumped += n_new
        if inner.finished:
            replica_lost = (inner.finish_reason == "cancelled"
                            and self.replicas[req.replica].state == DEAD)
            if inner.finish_reason == "error" or replica_lost:
                self._requeue(req, inner.error)
            else:
                self._finish(req, inner.finish_reason, inner.error)
        return n_new

    def _requeue(self, req: _FleetRequest, cause) -> None:
        """Failover one request whose replica died: re-submit prompt +
        delivered tokens (the :class:`Preempted` recompute contract) to a
        surviving replica with the remaining token budget."""
        failed = req.replica
        if req.n_requeues >= self.max_requeues:
            self._finish(req, "error", cause)
            return
        delivered = req.stream.n_tokens
        remaining = req.max_new_tokens - delivered
        if remaining <= 0:              # budget met just as the replica died
            self._finish(req, "length")
            return
        rec = Preempted(
            seq_id=-1, tokens=tuple(req.prompt + req.stream.tokens),
            prompt_len=len(req.prompt), n_generated=delivered,
            # req.meta carries the fleet trace id, so the surviving
            # replica's continuation stitches onto the SAME trace
            reason="replica_failure", deadline=req.deadline,
            meta=dict(req.meta) if req.meta else
            {"request_id": req.request_id, "tenant": req.tenant,
             "priority": req.priority})
        try:
            name, warmth = self._pick(rec.tokens)
            with self._scoped_registry(name):
                req.inner = self.replicas[name].engine.submit_record(
                    rec, remaining, stop_tokens=req.stop_tokens,
                    request_id=req.request_id)
        except ServingError as e:
            self._finish(req, "error", e)
            return
        req.replica = name
        req.pumped = 0
        req.n_requeues += 1
        self.stats["requeues"] += 1
        self._note_route(req, name, warmth, requeue=True)
        trec = _get_recorder()
        if trec.enabled:
            trec.instant("trace.requeue", cat="request",
                         trace=trace_of(rec.meta),
                         request_id=req.request_id,
                         reason="replica_failure",
                         from_replica=failed, to_replica=name,
                         n_delivered=delivered)
        reg = get_registry()
        if reg.enabled:
            tmetrics.fleet_requeues_counter(reg).inc(replica=failed)

    def _finish(self, req: _FleetRequest, reason: str,
                error=None) -> None:
        req.stream.finish(reason, error)
        self._requests.pop(req.request_id, None)
        self._done.append(req.request_id)
        del self._done[:-256]          # bounded, like the stream registry
        if reason in ("length", "stop"):
            self.stats["completed"] += 1

    # -- observability -----------------------------------------------------
    def debug_state(self) -> Dict[str, Any]:
        """JSON-able fleet snapshot — served as the ``fleet`` section of
        ``GET /v1/debug/state`` when the frontend is built with
        ``fleet=``: per-replica health + load, router stats, and the
        in-flight request → replica binding."""
        now = time.perf_counter()
        replicas = {}
        for name, rep in list(self.replicas.items()):
            entry: Dict[str, Any] = {"state": rep.state,
                                     "failures": rep.failures,
                                     "quarantines": rep.quarantines}
            if rep.state == BACKING_OFF:
                entry["backoff_remaining_s"] = round(
                    max(rep.backoff_until - now, 0.0), 4)
            if rep.state != DEAD:
                try:
                    ds = rep.engine.debug_state()
                except Exception:
                    # the replica died between enumeration and report
                    # (engine torn down under us): serve a dead stub
                    # instead of sinking GET /v1/debug/state
                    replicas[name] = {"state": DEAD,
                                      "failures": rep.failures,
                                      "quarantines": rep.quarantines}
                    continue
                entry.update(queue_depth=ds["queue"]["depth"],
                             active=len(ds["active"]),
                             closed=ds["closed"])
            replicas[name] = entry
        return {
            "replicas": replicas,
            "stats": dict(self.stats),
            "in_flight": {rid: req.replica
                          for rid, req in self._requests.items()},
        }
