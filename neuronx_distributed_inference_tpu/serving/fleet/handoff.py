"""Disaggregated prefill → decode handoff (ROADMAP item 3 prong c).

A **prefill-role** engine runs admission + chunked prefill only (any
:class:`~..adapter.PagedEngineAdapter`); once a sequence's first token
has materialized, :func:`capture_handoff` snapshots it into a JSON-safe
**handoff record** — a superset of the serialized
:class:`~...resilience.preemption.Preempted` requeue payload plus the
sequence's fully-written KV block payloads (content-chain-hash keyed,
read device→host) — and releases it from the prefill engine.

A **decode-role** engine admits the record with :func:`admit_handoff`:
the block payloads seed its :class:`~.kv_tier.HostKVSpillTier`, and the
record's recompute prompt goes through the ordinary transactional
``add_requests`` path, whose spill-restore step re-admits the KV by
async H2D copy instead of recompute-prefill. Because the record's tokens
ride the exact ``Preempted`` replay contract (prompt + every sampled
token; the last sampled token's KV intentionally unwritten), the decode
engine's greedy continuation is **bit-identical to a single-engine run**
(pinned by ``tests/test_fleet.py``).

The record is pure JSON (payloads base64-encoded with dtype/shape), so
it crosses process boundaries: ``json.dumps(handoff_to_json(rec))`` on
the prefill host, ``handoff_from_json(json.loads(...))`` on the decode
host. Failures are typed :class:`~...resilience.errors.HandoffError`
with the failing side's engine state unchanged (capture reads before it
releases; admission is transactional), and the ``handoff`` fault point
makes both sides' failure paths deterministic in tests.

**Live decode→decode migration** (ISSUE 17) generalizes the same wire
form: :func:`migrate` captures a MID-DECODE sequence off one fleet
replica (fully-written blocks, delivered tokens, remaining deadline
budget, the fleet trace id — all riding the ``nxdi-handoff-v1`` record
with backward-compatible field additions ``kind`` / ``delivered_tokens``
/ ``trace``) and re-admits it on another replica so the client stream
CONTINUES bit-identically: the destination seeds its spill tier, the
transactional admission restores the KV in one batched H2D write, and
only the uncovered suffix recomputes. The source sequence is released
ONLY after the destination accepted the record, so a failure at either
fault point (``migrate_capture`` / ``migrate_admit``) leaves BOTH
engines unchanged — free pools exact, the un-migrated stream still
serving on the source.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, Optional

import numpy as np

from ...resilience.errors import HandoffError, ServingError
from ...resilience.faults import FAULTS as _FAULTS
from ...resilience.preemption import Preempted
from ...telemetry import get_registry
from ...telemetry import metrics as tmetrics
from ...telemetry.request_trace import trace_of
from ...telemetry.trace import get_recorder as _get_recorder

__all__ = ["HANDOFF_SCHEMA", "capture_handoff", "admit_handoff",
           "migrate", "handoff_to_json", "handoff_from_json"]

HANDOFF_SCHEMA = "nxdi-handoff-v1"


def _capture(adapter, seq_id: int, *, point: str, reason: str,
             now: Optional[float] = None):
    """Read-only capture core shared by :func:`capture_handoff` and
    :func:`migrate`: snapshot one RUNNING sequence into a handoff
    record WITHOUT releasing it (the caller decides when — handoff
    releases immediately, migration only after the destination accepted
    the record). ``point`` is the fault point traversed; ``reason``
    lands in the ``Preempted`` payload. Returns ``(record, pre)``;
    raises :class:`HandoffError` with the adapter unchanged."""
    st = adapter.seqs.get(seq_id)
    if st is None:
        state = ("still mid-prefill" if seq_id in getattr(
            adapter, "_chunks", {}) else "not running")
        raise HandoffError(
            f"cannot capture seq_id {seq_id}: {state} — hand off after "
            "its first token materializes", seq_ids=(seq_id,))
    mgr = adapter.app.kv_mgr
    bs = mgr.spec.block_size
    table = mgr.tables[seq_id]
    try:
        if _FAULTS.active:
            # literal point names: the fault-points lint pass checks
            # fire() sites statically, so no parameterized fire here
            if point == "migrate_capture":
                _FAULTS.fire("migrate_capture")
            else:
                _FAULTS.fire("handoff")
        # full blocks whose every slot was written: (bi+1)*bs <= position
        # (position indexes the last SAMPLED token, whose KV is unwritten)
        cache = adapter.app.cache
        kv_blocks = []
        parent = b""
        for bi in range(st.position // bs):
            parent = _chain_hash(parent, st.tokens[bi * bs:(bi + 1) * bs])
            blk = table[bi]
            kv_blocks.append({
                "hash": parent,
                "k": np.asarray(cache["k"][:, blk]),
                "v": np.asarray(cache["v"][:, blk]),
            })
    except ServingError:
        raise
    except Exception as e:
        raise HandoffError(
            f"{reason} capture of seq_id {seq_id} failed; the sequence "
            "is still running on the source engine",
            seq_ids=(seq_id,)) from e
    pre = Preempted(
        seq_id=seq_id, tokens=tuple(st.tokens), prompt_len=st.prompt_len,
        n_generated=len(st.tokens) - st.prompt_len, reason=reason,
        deadline=st.deadline, meta=st.meta)
    record = {
        "schema": HANDOFF_SCHEMA,
        "preempted": pre.to_json(now=now),
        "block_size": bs,
        "kv_blocks": kv_blocks,
        # v1-compatible field additions (ISSUE 17): admitters that
        # predate them ignore unknown keys, so old records stay valid
        "kind": reason,
        "delivered_tokens": pre.n_generated,
        "trace": trace_of(pre.meta),
    }
    return record, pre


def capture_handoff(adapter, seq_id: int,
                    now: Optional[float] = None) -> Dict[str, Any]:
    """Snapshot one RUNNING sequence of a prefill-role adapter into a
    handoff record and release it. The record holds the serialized
    ``Preempted`` payload (tokens = prompt + everything sampled,
    remaining deadline budget, meta passthrough) plus the K/V payloads of
    every fully-written block (positions ``[0, position)`` — the last
    sampled token's KV is intentionally absent, exactly like a
    preemption-requeue). Raises :class:`HandoffError` for a pending
    (mid-prefill) or unknown seq_id, leaving the adapter unchanged."""
    record, pre = _capture(adapter, seq_id, point="handoff",
                           reason="handoff", now=now)
    adapter.release([seq_id])
    kv_blocks = record["kv_blocks"]
    rec = _get_recorder()
    if rec.enabled:
        # meta rides the record verbatim, so the trace id recorded here
        # is the SAME one the decode side stitches onto at admit
        rec.instant("handoff.send", cat="fleet", seq_id=int(seq_id),
                    tokens=len(pre.tokens), blocks=len(kv_blocks),
                    engine=adapter.engine_name, trace=trace_of(pre.meta))
    reg = get_registry()
    if reg.enabled:
        tmetrics.handoffs_counter(reg).inc(role="send")
    return record


def admit_handoff(adapter, record: Dict[str, Any], seq_id: int,
                  now: Optional[float] = None) -> Dict[int, int]:
    """Admit a handoff record on a decode-role adapter: seed its spill
    tier with the record's block payloads, then run the ordinary
    transactional ``add_requests`` — the spill-restore step re-admits the
    KV via H2D copy and only the uncovered suffix recomputes. Returns the
    adapter's first-token dict (``{}`` under a deferred prefill budget).
    Raises :class:`HandoffError` for a malformed record or a decode
    adapter without a spill tier; admission failures propagate typed with
    the decode engine rolled back (transactional)."""
    tier = getattr(adapter, "_kv_tier", None)
    if tier is None:
        raise HandoffError(
            "decode-role adapter has no kv_spill_tier — build it with "
            "PagedEngineAdapter(app, kv_spill_tier=HostKVSpillTier(...)) "
            "so the handoff KV can be restored instead of recomputed")
    try:
        if _FAULTS.active:
            _FAULTS.fire("handoff")
        if record.get("schema") != HANDOFF_SCHEMA:
            raise KeyError(f"not an {HANDOFF_SCHEMA} record: "
                           f"schema={record.get('schema')!r}")
        if int(record["block_size"]) != adapter.app.kv_mgr.spec.block_size:
            raise KeyError(
                f"handoff block_size {record['block_size']} != decode "
                f"engine's {adapter.app.kv_mgr.spec.block_size}")
        pre = Preempted.from_json(record["preempted"], now=now)
        payloads = {b["hash"]: {"k": b["k"], "v": b["v"]}
                    for b in record["kv_blocks"]}
    except ServingError:
        raise
    except Exception as e:
        raise HandoffError(
            f"handoff admission failed before any decode-engine state "
            f"changed: {e}") from e
    tier.seed(payloads)
    first = adapter.add_requests(**pre.admission_kwargs(seq_id=seq_id,
                                                        now=now))
    rec = _get_recorder()
    if rec.enabled:
        rec.instant("handoff.recv", cat="fleet", seq_id=int(seq_id),
                    tokens=len(pre.tokens), blocks=len(payloads),
                    engine=adapter.engine_name, trace=trace_of(pre.meta))
    reg = get_registry()
    if reg.enabled:
        tmetrics.handoffs_counter(reg).inc(role="recv")
    return first


def migrate(router, request_id: str, src: Optional[str] = None,
            dst: Optional[str] = None,
            now: Optional[float] = None) -> str:
    """Live decode→decode migration of one in-flight fleet request:
    capture its mid-decode sequence off the source replica (fully
    written blocks via the spill-tier wire form, delivered tokens,
    remaining deadline budget, the fleet trace id) and re-admit it on
    the destination so the client stream CONTINUES bit-identically —
    the KV moves, only the uncovered suffix recomputes.

    ``src`` defaults to the replica currently serving the request (and
    must match it when given); ``dst`` defaults to the warmest other
    healthy replica with a spill tier (``EngineRouter._pick_migration_
    dst``). Returns the destination replica name.

    Failure semantics (the ``migrate_capture`` / ``migrate_admit``
    fault points): the source sequence is released ONLY after the
    destination accepted the record, so a typed :class:`HandoffError`
    from either side leaves BOTH engines unchanged — free pools exact,
    the un-migrated stream keeps serving on the source."""
    req = router._requests.get(request_id)
    if req is None or req.stream.finished:
        raise HandoffError(
            f"cannot migrate request {request_id!r}: not in flight on "
            "this router")
    if src is None:
        src = req.replica
    elif src != req.replica:
        raise HandoffError(
            f"request {request_id!r} is served by replica "
            f"{req.replica!r}, not {src!r}")
    src_rep = router._replica(src)
    if src_rep.state == "dead":
        raise HandoffError(
            f"source replica {src!r} is dead — its requests fail over "
            "through the requeue-recompute path, not migration")
    if dst is None:
        dst = router._pick_migration_dst(req, exclude=src)
    dst_rep = router._replica(dst)
    if dst == src:
        raise HandoffError(f"migration source and destination are both "
                           f"{src!r}")
    tier = getattr(dst_rep.engine.adapter, "_kv_tier", None)
    if tier is None:
        raise HandoffError(
            f"destination replica {dst!r} has no kv_spill_tier — the "
            "migrated KV could not be restored, only recomputed; build "
            "the decode adapters with kv_spill_tier=HostKVSpillTier(...)")
    # flush already-sampled tokens into the fleet stream first so the
    # delivered count and the capture agree exactly
    router._pump(req)
    if req.stream.finished or request_id not in router._requests:
        raise HandoffError(
            f"request {request_id!r} finished while migration started — "
            "nothing to move")
    sid = src_rep.engine.seq_id_of(request_id)
    if sid is None:
        raise HandoffError(
            f"request {request_id!r} is not running on {src!r} yet "
            "(queued or mid-prefill) — migrate after its first token "
            "materializes")
    record, pre = _capture(src_rep.engine.adapter, sid,
                           point="migrate_capture", reason="migrate",
                           now=now)
    delivered = req.stream.n_tokens
    if tuple(pre.tokens) != tuple(req.prompt) + tuple(req.stream.tokens):
        raise HandoffError(
            f"request {request_id!r} capture disagrees with the fleet "
            f"stream ({len(pre.tokens)} captured tokens vs "
            f"{len(req.prompt)} prompt + {delivered} delivered) — "
            "source unchanged, not migrating")
    # the adapter's prompt_len/n_generated describe its LOCAL admission
    # (after a prior requeue or migration the recompute prompt already
    # contains earlier generations), so re-anchor the record to the
    # FLEET-level split — exactly what EngineRouter._requeue submits
    pre = Preempted(
        seq_id=pre.seq_id, tokens=pre.tokens,
        prompt_len=len(req.prompt), n_generated=delivered,
        reason="migrate", deadline=pre.deadline, meta=pre.meta)
    record["preempted"] = pre.to_json(now=now)
    record["delivered_tokens"] = delivered
    remaining = req.max_new_tokens - delivered
    if remaining <= 0:
        raise HandoffError(
            f"request {request_id!r} has no remaining token budget — "
            "let it finish on the source")
    payloads = {b["hash"]: {"k": b["k"], "v": b["v"]}
                for b in record["kv_blocks"]}
    with router._scoped_registry(dst):
        try:
            if _FAULTS.active:
                _FAULTS.fire("migrate_admit")
        except ServingError:
            raise
        except Exception as e:
            raise HandoffError(
                f"migration admit of request {request_id!r} on {dst!r} "
                "failed before any destination state changed; the "
                "stream keeps serving on the source") from e
        tier.seed(payloads)
        inner = dst_rep.engine.submit_record(
            pre, remaining, stop_tokens=req.stop_tokens,
            request_id=request_id)
    # the destination owns the request now: tear the source copy down
    # (cancel finishes the OLD inner stream and releases the sequence's
    # device state; the fleet stream never sees it — rebind below)
    with router._scoped_registry(src):
        src_rep.engine.cancel(request_id)
    req.inner = inner
    req.replica = dst
    req.pumped = 0
    router.stats["migrations"] += 1
    router.stats["migrated_kv_tokens"] += (
        len(record["kv_blocks"]) * int(record["block_size"]))
    rec = _get_recorder()
    if rec.enabled:
        tid = trace_of(pre.meta)
        rec.instant("handoff.send", cat="fleet", seq_id=int(sid),
                    tokens=len(pre.tokens), blocks=len(payloads),
                    engine=src_rep.engine.adapter.engine_name, trace=tid)
        rec.instant("handoff.recv", cat="fleet", seq_id=int(sid),
                    tokens=len(pre.tokens), blocks=len(payloads),
                    engine=dst_rep.engine.adapter.engine_name, trace=tid)
        rec.instant("trace.requeue", cat="request", trace=tid,
                    request_id=request_id, reason="migrate",
                    from_replica=src, to_replica=dst,
                    n_delivered=delivered)
    reg = get_registry()
    if reg.enabled:
        tmetrics.handoffs_counter(reg).inc(role="migrate_send")
        tmetrics.handoffs_counter(reg).inc(role="migrate_recv")
    return dst


# ---------------------------------------------------------------------------
# JSON wire format (cross-process)
# ---------------------------------------------------------------------------

def handoff_to_json(record: Dict[str, Any]) -> Dict[str, Any]:
    """Pure-JSON form of a handoff record: block payloads become base64
    raw bytes + dtype/shape (bfloat16 and friends round-trip via
    ml_dtypes names), hashes become hex strings."""
    out = dict(record)
    blocks = []
    for b in record["kv_blocks"]:
        k, v = np.asarray(b["k"]), np.asarray(b["v"])
        blocks.append({
            "hash": b["hash"].hex(),
            "dtype": k.dtype.name,
            "shape": list(k.shape),
            "k": base64.b64encode(k.tobytes()).decode("ascii"),
            "v": base64.b64encode(v.tobytes()).decode("ascii"),
        })
    out["kv_blocks"] = blocks
    return out


def handoff_from_json(data: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`handoff_to_json`. Raises
    :class:`HandoffError` on malformed input."""
    try:
        out = dict(data)
        blocks = []
        for b in data["kv_blocks"]:
            dtype = _np_dtype(b["dtype"])
            shape = tuple(int(s) for s in b["shape"])
            blocks.append({
                "hash": bytes.fromhex(b["hash"]),
                "k": np.frombuffer(base64.b64decode(b["k"]),
                                   dtype=dtype).reshape(shape),
                "v": np.frombuffer(base64.b64decode(b["v"]),
                                   dtype=dtype).reshape(shape),
            })
        out["kv_blocks"] = blocks
        return out
    except HandoffError:
        raise
    except Exception as e:
        raise HandoffError(f"malformed handoff JSON: {e}") from e


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype from its name, reaching into ml_dtypes for the
    accelerator dtypes numpy itself does not know (bfloat16, fp8)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _chain_hash(parent: bytes, tokens) -> bytes:
    from ...modules.block_kv_cache import _hash_block
    return _hash_block(parent, list(tokens))
