"""Disaggregated prefill → decode handoff (ROADMAP item 3 prong c).

A **prefill-role** engine runs admission + chunked prefill only (any
:class:`~..adapter.PagedEngineAdapter`); once a sequence's first token
has materialized, :func:`capture_handoff` snapshots it into a JSON-safe
**handoff record** — a superset of the serialized
:class:`~...resilience.preemption.Preempted` requeue payload plus the
sequence's fully-written KV block payloads (content-chain-hash keyed,
read device→host) — and releases it from the prefill engine.

A **decode-role** engine admits the record with :func:`admit_handoff`:
the block payloads seed its :class:`~.kv_tier.HostKVSpillTier`, and the
record's recompute prompt goes through the ordinary transactional
``add_requests`` path, whose spill-restore step re-admits the KV by
async H2D copy instead of recompute-prefill. Because the record's tokens
ride the exact ``Preempted`` replay contract (prompt + every sampled
token; the last sampled token's KV intentionally unwritten), the decode
engine's greedy continuation is **bit-identical to a single-engine run**
(pinned by ``tests/test_fleet.py``).

The record is pure JSON (payloads base64-encoded with dtype/shape), so
it crosses process boundaries: ``json.dumps(handoff_to_json(rec))`` on
the prefill host, ``handoff_from_json(json.loads(...))`` on the decode
host. Failures are typed :class:`~...resilience.errors.HandoffError`
with the failing side's engine state unchanged (capture reads before it
releases; admission is transactional), and the ``handoff`` fault point
makes both sides' failure paths deterministic in tests.
"""

from __future__ import annotations

import base64
from typing import Any, Dict, Optional

import numpy as np

from ...resilience.errors import HandoffError, ServingError
from ...resilience.faults import FAULTS as _FAULTS
from ...resilience.preemption import Preempted
from ...telemetry import get_registry
from ...telemetry import metrics as tmetrics
from ...telemetry.request_trace import trace_of
from ...telemetry.trace import get_recorder as _get_recorder

__all__ = ["HANDOFF_SCHEMA", "capture_handoff", "admit_handoff",
           "handoff_to_json", "handoff_from_json"]

HANDOFF_SCHEMA = "nxdi-handoff-v1"


def capture_handoff(adapter, seq_id: int,
                    now: Optional[float] = None) -> Dict[str, Any]:
    """Snapshot one RUNNING sequence of a prefill-role adapter into a
    handoff record and release it. The record holds the serialized
    ``Preempted`` payload (tokens = prompt + everything sampled,
    remaining deadline budget, meta passthrough) plus the K/V payloads of
    every fully-written block (positions ``[0, position)`` — the last
    sampled token's KV is intentionally absent, exactly like a
    preemption-requeue). Raises :class:`HandoffError` for a pending
    (mid-prefill) or unknown seq_id, leaving the adapter unchanged."""
    st = adapter.seqs.get(seq_id)
    if st is None:
        state = ("still mid-prefill" if seq_id in getattr(
            adapter, "_chunks", {}) else "not running")
        raise HandoffError(
            f"cannot capture seq_id {seq_id}: {state} — hand off after "
            "its first token materializes", seq_ids=(seq_id,))
    mgr = adapter.app.kv_mgr
    bs = mgr.spec.block_size
    table = mgr.tables[seq_id]
    try:
        if _FAULTS.active:
            _FAULTS.fire("handoff")
        # full blocks whose every slot was written: (bi+1)*bs <= position
        # (position indexes the last SAMPLED token, whose KV is unwritten)
        cache = adapter.app.cache
        kv_blocks = []
        parent = b""
        for bi in range(st.position // bs):
            parent = _chain_hash(parent, st.tokens[bi * bs:(bi + 1) * bs])
            blk = table[bi]
            kv_blocks.append({
                "hash": parent,
                "k": np.asarray(cache["k"][:, blk]),
                "v": np.asarray(cache["v"][:, blk]),
            })
    except ServingError:
        raise
    except Exception as e:
        raise HandoffError(
            f"handoff capture of seq_id {seq_id} failed; the sequence "
            "is still running on the prefill engine",
            seq_ids=(seq_id,)) from e
    pre = Preempted(
        seq_id=seq_id, tokens=tuple(st.tokens), prompt_len=st.prompt_len,
        n_generated=len(st.tokens) - st.prompt_len, reason="handoff",
        deadline=st.deadline, meta=st.meta)
    adapter.release([seq_id])
    record = {
        "schema": HANDOFF_SCHEMA,
        "preempted": pre.to_json(now=now),
        "block_size": bs,
        "kv_blocks": kv_blocks,
    }
    rec = _get_recorder()
    if rec.enabled:
        # meta rides the record verbatim, so the trace id recorded here
        # is the SAME one the decode side stitches onto at admit
        rec.instant("handoff.send", cat="fleet", seq_id=int(seq_id),
                    tokens=len(pre.tokens), blocks=len(kv_blocks),
                    engine=adapter.engine_name, trace=trace_of(pre.meta))
    reg = get_registry()
    if reg.enabled:
        tmetrics.handoffs_counter(reg).inc(role="send")
    return record


def admit_handoff(adapter, record: Dict[str, Any], seq_id: int,
                  now: Optional[float] = None) -> Dict[int, int]:
    """Admit a handoff record on a decode-role adapter: seed its spill
    tier with the record's block payloads, then run the ordinary
    transactional ``add_requests`` — the spill-restore step re-admits the
    KV via H2D copy and only the uncovered suffix recomputes. Returns the
    adapter's first-token dict (``{}`` under a deferred prefill budget).
    Raises :class:`HandoffError` for a malformed record or a decode
    adapter without a spill tier; admission failures propagate typed with
    the decode engine rolled back (transactional)."""
    tier = getattr(adapter, "_kv_tier", None)
    if tier is None:
        raise HandoffError(
            "decode-role adapter has no kv_spill_tier — build it with "
            "PagedEngineAdapter(app, kv_spill_tier=HostKVSpillTier(...)) "
            "so the handoff KV can be restored instead of recomputed")
    try:
        if _FAULTS.active:
            _FAULTS.fire("handoff")
        if record.get("schema") != HANDOFF_SCHEMA:
            raise KeyError(f"not an {HANDOFF_SCHEMA} record: "
                           f"schema={record.get('schema')!r}")
        if int(record["block_size"]) != adapter.app.kv_mgr.spec.block_size:
            raise KeyError(
                f"handoff block_size {record['block_size']} != decode "
                f"engine's {adapter.app.kv_mgr.spec.block_size}")
        pre = Preempted.from_json(record["preempted"], now=now)
        payloads = {b["hash"]: {"k": b["k"], "v": b["v"]}
                    for b in record["kv_blocks"]}
    except ServingError:
        raise
    except Exception as e:
        raise HandoffError(
            f"handoff admission failed before any decode-engine state "
            f"changed: {e}") from e
    tier.seed(payloads)
    first = adapter.add_requests(**pre.admission_kwargs(seq_id=seq_id,
                                                        now=now))
    rec = _get_recorder()
    if rec.enabled:
        rec.instant("handoff.recv", cat="fleet", seq_id=int(seq_id),
                    tokens=len(pre.tokens), blocks=len(payloads),
                    engine=adapter.engine_name, trace=trace_of(pre.meta))
    reg = get_registry()
    if reg.enabled:
        tmetrics.handoffs_counter(reg).inc(role="recv")
    return first


# ---------------------------------------------------------------------------
# JSON wire format (cross-process)
# ---------------------------------------------------------------------------

def handoff_to_json(record: Dict[str, Any]) -> Dict[str, Any]:
    """Pure-JSON form of a handoff record: block payloads become base64
    raw bytes + dtype/shape (bfloat16 and friends round-trip via
    ml_dtypes names), hashes become hex strings."""
    out = dict(record)
    blocks = []
    for b in record["kv_blocks"]:
        k, v = np.asarray(b["k"]), np.asarray(b["v"])
        blocks.append({
            "hash": b["hash"].hex(),
            "dtype": k.dtype.name,
            "shape": list(k.shape),
            "k": base64.b64encode(k.tobytes()).decode("ascii"),
            "v": base64.b64encode(v.tobytes()).decode("ascii"),
        })
    out["kv_blocks"] = blocks
    return out


def handoff_from_json(data: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`handoff_to_json`. Raises
    :class:`HandoffError` on malformed input."""
    try:
        out = dict(data)
        blocks = []
        for b in data["kv_blocks"]:
            dtype = _np_dtype(b["dtype"])
            shape = tuple(int(s) for s in b["shape"])
            blocks.append({
                "hash": bytes.fromhex(b["hash"]),
                "k": np.frombuffer(base64.b64decode(b["k"]),
                                   dtype=dtype).reshape(shape),
                "v": np.frombuffer(base64.b64decode(b["v"]),
                                   dtype=dtype).reshape(shape),
            })
        out["kv_blocks"] = blocks
        return out
    except HandoffError:
        raise
    except Exception as e:
        raise HandoffError(f"malformed handoff JSON: {e}") from e


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype from its name, reaching into ml_dtypes for the
    accelerator dtypes numpy itself does not know (bfloat16, fp8)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _chain_hash(parent: bytes, tokens) -> bytes:
    from ...modules.block_kv_cache import _hash_block
    return _hash_block(parent, list(tokens))
