"""Fleet-wide metrics aggregation — N replica registries, one scrape.

A real fleet runs one registry per replica process; the in-process fleet
(`EngineRouter(metrics_registries=...)`) keeps one dedicated
:class:`~...telemetry.MetricsRegistry` per replica by scoping the global
slot while each replica runs. Either way, an operator wants ONE
Prometheus scrape for the fleet: :class:`FleetMetricsAggregator` merges
the sources under a ``replica`` label — every series of every replica is
re-emitted as ``name{replica="<name>",...}`` with its HELP/TYPE header
written once — so ``nxdi_request_ttft_seconds`` from two replicas lands
as two labeled series of one metric family, exactly what a
fleet-latency dashboard joins on.

Sources are deliberately loose: a live ``MetricsRegistry`` (read at
scrape time), an already-taken ``snapshot()`` dict (the cross-process
case — ship each replica's snapshot over the wire and aggregate
centrally), or a zero-arg callable returning either. The merge is pure
and allocation-light; nothing here runs unless someone scrapes.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ...resilience.errors import ConfigurationError
from ...telemetry.registry import _escape_help, render_series

__all__ = ["FleetMetricsAggregator"]

FLEET_METRICS_SCHEMA = "nxdi-fleet-metrics-v1"


class FleetMetricsAggregator:
    """Merge per-replica metric sources into one exposition (see module
    docstring). ``sources`` maps replica name -> registry | snapshot
    dict | callable."""

    def __init__(self, sources: Dict[str, Any]):
        if not sources:
            raise ConfigurationError(
                "FleetMetricsAggregator needs >= 1 source")
        self.sources = dict(sources)

    # -- source resolution -------------------------------------------------
    @staticmethod
    def _resolve(source: Any) -> Dict[str, Any]:
        if callable(source) and not hasattr(source, "snapshot"):
            source = source()
        if hasattr(source, "snapshot"):
            source = source.snapshot()
        if not isinstance(source, dict) or "metrics" not in source:
            raise ConfigurationError(
                "fleet metrics source must be a MetricsRegistry, a "
                "snapshot() dict, or a callable returning one (got "
                f"{type(source).__name__})")
        return source

    def snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Per-replica ``registry.snapshot()`` dicts, resolved now."""
        return {name: self._resolve(src)
                for name, src in sorted(self.sources.items())}

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able fleet dump: the per-replica snapshots under one
        schema header (the debug/artifact counterpart of the text
        exposition)."""
        return {"schema": FLEET_METRICS_SCHEMA,
                "replicas": self.snapshots()}

    # -- exposition --------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4) of the merged fleet: one
        HELP/TYPE header per metric family, every replica's series
        re-labeled with ``replica=<name>`` (first label, so fleet joins
        group naturally). Sample rendering goes through the registry's
        own :func:`~...telemetry.registry.render_series`, so this
        surface can never drift from the single-process exposition."""
        # family name -> {"type", "help", "lines": [...]} in first-seen
        # order per replica-sorted iteration (deterministic output)
        families: Dict[str, Dict[str, Any]] = {}
        for replica, snap in self.snapshots().items():
            for name in sorted(snap["metrics"]):
                fam = snap["metrics"][name]
                slot = families.setdefault(
                    name, {"type": fam["type"], "help": fam.get("help", ""),
                           "lines": []})
                for series in fam["series"]:
                    slot["lines"].extend(render_series(
                        name, fam["type"], series,
                        extra_labels={"replica": replica}))
        out: List[str] = []
        for name in sorted(families):
            fam = families[name]
            if fam["help"]:
                out.append(f"# HELP {name} {_escape_help(fam['help'])}")
            out.append(f"# TYPE {name} {fam['type']}")
            out.extend(fam["lines"])
        return "\n".join(out) + "\n" if out else ""
