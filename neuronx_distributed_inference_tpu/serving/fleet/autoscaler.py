"""Closed-loop fleet autoscaler — elastic replica count (ISSUE 17).

A :class:`FleetAutoscaler` is attached to an
:class:`~.router.EngineRouter` (``autoscaler=``) and consulted exactly
once per :meth:`~.router.EngineRouter.run_pass`. Each evaluation reads
three fleet-aggregated signals:

  * **queue pressure** — mean engine queue depth per HEALTHY replica
    (the same ``ServingEngine.load`` tuple the router's routing key
    reads);
  * **SLO burn** — the max multiwindow
    :meth:`~...telemetry.slo.SLOTracker.burn_index` across every
    replica that carries a tracker (replicas without one contribute
    0.0, so virtual-clock benches never mix clock domains);
  * **admission headroom** — the min
    :func:`~..warmup.admission_headroom` ``free_slots`` across HEALTHY
    replicas (slots, not blocks: a fleet can be block-rich and still
    reject on batch slots).

and then applies the same hysteresis discipline as the
:class:`~...resilience.controller.DegradationController`: *enter* and
*exit* thresholds live on opposite sides of a dead band (validated at
construction), a signal must HOLD past ``min_hold_s`` before any
action, and every action opens a ``cooldown_s`` window during which
nothing else may fire — so a noisy boundary cannot flap the fleet.

**Scale-up is precompile-first.** The injectable ``replica_factory``
builds the engine; the autoscaler then walks
:func:`~..warmup.precompile` against the process's shared persistent
compilation cache and only admits the replica
(:meth:`~.router.EngineRouter.add_replica`) if the report says
``n_compiles == 0`` — a replica that would compile under traffic is
closed and rejected instead (``stats["rejected_cold"]``), because a
compile stall behind live decode traffic is exactly the latency cliff
the warmup plane exists to prevent.

**Scale-down is two-phase.** Initiate: pick the least-loaded
self-spawned (else least-loaded healthy) replica above
``min_replicas`` and ``drain(mode="migrate")`` it — running streams
move to survivors carrying their KV, nothing recomputes. Reap: on
later evaluations, once the victim holds no fleet-bound requests and
no engine work, :meth:`~.router.EngineRouter.remove_replica` drops it
(closing the engine if this autoscaler spawned it).

Every evaluation refreshes the ``nxdi_fleet_replicas{state}`` gauge
and every action lands on the flight recorder (``fleet.scale_up`` /
``fleet.scale_down``). The whole evaluation is a fault point
(``autoscale``): an injected trip aborts it with the fleet unchanged.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from ...resilience.errors import ConfigurationError
from ...resilience.faults import FAULTS as _FAULTS
from ...resilience.faults import InjectedFault
from ...telemetry import get_registry
from ...telemetry import metrics as tmetrics
from ...telemetry.trace import get_recorder as _get_recorder
from .router import DEAD, HEALTHY

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Closed-loop replica-count controller (see module docstring).

    ``replica_factory`` is a zero-arg callable returning a new engine,
    a ``(name, engine)`` pair, or ``(name, engine, registry)`` — names
    default to ``auto0..N``; a registry is auto-created when the router
    scopes per-replica registries and the factory supplies none.

    Enter/exit threshold pairs must leave a dead band (exit strictly
    calmer than enter) or construction raises
    :class:`~...resilience.errors.ConfigurationError` — the same
    construction-time validation discipline as
    :func:`~...resilience.controller.check_policy`.
    """

    def __init__(self, replica_factory: Callable[[], Any], *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 queue_enter: float = 8.0, queue_exit: float = 2.0,
                 burn_enter: float = 1.0, burn_exit: float = 0.25,
                 headroom_enter_slots: int = 0,
                 headroom_exit_slots: int = 2,
                 min_hold_s: float = 0.0, cooldown_s: float = 1.0,
                 min_interval_s: float = 0.0,
                 now_fn: Callable[[], float] = time.perf_counter):
        if not callable(replica_factory):
            raise ConfigurationError(
                "replica_factory must be a zero-arg callable returning "
                "an engine, (name, engine), or (name, engine, registry)")
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ConfigurationError(
                "need 1 <= min_replicas <= max_replicas "
                f"(got {min_replicas}..{max_replicas})")
        if queue_exit >= queue_enter:
            raise ConfigurationError(
                f"queue_exit ({queue_exit}) must be < queue_enter "
                f"({queue_enter}) — no dead band means flapping")
        if burn_exit >= burn_enter:
            raise ConfigurationError(
                f"burn_exit ({burn_exit}) must be < burn_enter "
                f"({burn_enter}) — no dead band means flapping")
        if headroom_exit_slots <= headroom_enter_slots:
            raise ConfigurationError(
                f"headroom_exit_slots ({headroom_exit_slots}) must be > "
                f"headroom_enter_slots ({headroom_enter_slots}) — no "
                "dead band means flapping")
        if min_hold_s < 0 or cooldown_s < 0 or min_interval_s < 0:
            raise ConfigurationError(
                "min_hold_s, cooldown_s and min_interval_s must be >= 0")
        self.replica_factory = replica_factory
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.queue_enter = float(queue_enter)
        self.queue_exit = float(queue_exit)
        self.burn_enter = float(burn_enter)
        self.burn_exit = float(burn_exit)
        self.headroom_enter_slots = int(headroom_enter_slots)
        self.headroom_exit_slots = int(headroom_exit_slots)
        self.min_hold_s = float(min_hold_s)
        self.cooldown_s = float(cooldown_s)
        self.min_interval_s = float(min_interval_s)
        self._now = now_fn
        self._next_eval = 0.0
        self._hot_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._cooldown_until = 0.0
        self._spawn_counter = 0
        self._spawned: set = set()       # replica names this controller made
        self._retiring: Dict[str, bool] = {}   # name -> self-spawned?
        self.stats: Dict[str, int] = {
            "evaluations": 0, "scale_ups": 0, "scale_downs": 0,
            "reaped": 0, "rejected_cold": 0, "aborted": 0}
        #: action timeline for ``bench.py --autoscale-report``:
        #: [{"t", "action", "replica", ...}, ...]
        self.history: List[Dict[str, Any]] = []

    # -- signals -----------------------------------------------------------
    def signals(self, router) -> Dict[str, float]:
        """The three fleet-aggregated inputs of one evaluation, also
        served to the bench report: mean queue depth per healthy
        replica, max merged SLO burn, min free batch slots."""
        from ..warmup import admission_headroom
        healthy = [rep for rep in router.replicas.values()
                   if rep.state == HEALTHY
                   and not getattr(rep.engine, "closed", False)]
        queues, burns, slots = [], [0.0], []
        for rep in healthy:
            load = getattr(rep.engine, "load", None)
            if load is None:
                ds = rep.engine.debug_state()
                load = (ds["queue"]["depth"], len(ds["active"]))
            queues.append(float(load[0]))
            slo = getattr(rep.engine, "slo", None)
            if slo is not None:
                burns.extend(slo.burn_index().values())
            try:
                slots.append(
                    float(admission_headroom(rep.engine.adapter)
                          .get("free_slots", 0)))
            except Exception:
                pass                   # replica died mid-signal: skip it
        n = len(healthy)
        return {
            "healthy": float(n),
            "queue": (sum(queues) / n) if n else 0.0,
            "burn": max(burns),
            "free_slots": min(slots) if slots else 0.0,
        }

    # -- evaluation --------------------------------------------------------
    def update(self, router) -> Optional[str]:
        """One closed-loop evaluation (called by ``run_pass``). Returns
        ``"scale_up"`` / ``"scale_down"`` when an action fired, else
        None. An injected ``autoscale`` fault aborts the evaluation
        before ANY state changes (``stats["aborted"]``) — the fleet is
        left exactly as found."""
        now = self._now()
        if now < self._next_eval:
            return None
        self._next_eval = now + self.min_interval_s
        try:
            if _FAULTS.active:
                _FAULTS.fire("autoscale")
        except InjectedFault:
            self.stats["aborted"] += 1
            return None
        self.stats["evaluations"] += 1
        self._reap(router)
        sig = self.signals(router)
        self._refresh_gauge(router)
        n_live = int(sig["healthy"]) + len(
            [n for n in self._retiring if n in router.replicas])
        hot = (sig["queue"] >= self.queue_enter
               or sig["burn"] >= self.burn_enter
               or sig["free_slots"] <= self.headroom_enter_slots)
        calm = (sig["queue"] <= self.queue_exit
                and sig["burn"] <= self.burn_exit
                and sig["free_slots"] >= self.headroom_exit_slots)
        # explicit None checks: 0.0 is a legitimate virtual-clock
        # timestamp, not "never held"
        self._hot_since = (
            now if self._hot_since is None else self._hot_since
        ) if hot else None
        self._calm_since = (
            now if self._calm_since is None else self._calm_since
        ) if calm else None
        if now < self._cooldown_until:
            return None
        if (hot and n_live < self.max_replicas
                and now - self._hot_since >= self.min_hold_s):
            return self._scale_up(router, now, sig)
        if (calm and int(sig["healthy"]) > self.min_replicas
                and now - self._calm_since >= self.min_hold_s):
            return self._scale_down(router, now, sig)
        return None

    # -- scale-up ----------------------------------------------------------
    def _scale_up(self, router, now: float,
                  sig: Dict[str, float]) -> Optional[str]:
        from ..warmup import precompile
        made = self.replica_factory()
        registry = None
        if isinstance(made, tuple):
            if len(made) == 3:
                name, engine, registry = made
            else:
                name, engine = made
        else:
            name, engine = f"auto{self._spawn_counter}", made
        self._spawn_counter += 1
        # precompile-first gate: the replica walks its whole plan
        # against the shared persistent compilation cache BEFORE it can
        # take traffic; anything that would compile under load is
        # rejected here, where it costs nothing
        try:
            report = precompile(engine.adapter.app, registry=registry)
        except Exception:
            report = None
        if report is None or int(report.get("n_compiles", 1)) != 0:
            self.stats["rejected_cold"] += 1
            self._note(now, "reject_cold", name,
                       n_compiles=None if report is None
                       else report.get("n_compiles"))
            close = getattr(engine, "close", None)
            if close is not None:
                close()
            return None
        if router._registries is None:
            registry = None
        router.add_replica(name, engine, registry=registry)
        self._spawned.add(name)
        self.stats["scale_ups"] += 1
        self._cooldown_until = now + self.cooldown_s
        self._hot_since = None
        self._note(now, "scale_up", name,
                   n_compiles=int(report["n_compiles"]),
                   queue=round(sig["queue"], 3),
                   burn=round(sig["burn"], 3),
                   free_slots=sig["free_slots"])
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("fleet.scale_up", cat="fleet", replica=name,
                        reason="pressure",
                        n_compiles=int(report["n_compiles"]),
                        queue=round(sig["queue"], 3),
                        burn=round(sig["burn"], 3),
                        free_slots=sig["free_slots"])
        self._refresh_gauge(router)
        return "scale_up"

    # -- scale-down --------------------------------------------------------
    def _scale_down(self, router, now: float,
                    sig: Dict[str, float]) -> Optional[str]:
        victim = self._pick_victim(router)
        if victim is None:
            return None
        migrated = router.drain(victim, mode="migrate")
        self._retiring[victim] = victim in self._spawned
        self.stats["scale_downs"] += 1
        self._cooldown_until = now + self.cooldown_s
        self._calm_since = None
        self._note(now, "scale_down", victim, migrated=migrated,
                   queue=round(sig["queue"], 3),
                   burn=round(sig["burn"], 3))
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("fleet.scale_down", cat="fleet", replica=victim,
                        reason="idle", migrated=migrated,
                        queue=round(sig["queue"], 3),
                        burn=round(sig["burn"], 3))
        self._refresh_gauge(router)
        return "scale_down"

    def _pick_victim(self, router) -> Optional[str]:
        """Least-loaded healthy replica, preferring ones this
        controller spawned (retire elastic capacity before seed
        capacity), never below ``min_replicas`` healthy."""
        ranked = []
        for name in sorted(router.replicas):
            rep = router.replicas[name]
            if rep.state != HEALTHY or name in self._retiring:
                continue
            load = getattr(rep.engine, "load", None) or (0, 0)
            ranked.append((name not in self._spawned, tuple(load), name))
        if len(ranked) <= self.min_replicas:
            return None
        return min(ranked)[2]

    # -- retirement reaper -------------------------------------------------
    def _reap(self, router) -> None:
        """Phase 2 of scale-down: remove retiring replicas once their
        migrated-away drain has fully quiesced (no fleet-bound
        requests, no engine work)."""
        for name in list(self._retiring):
            rep = router.replicas.get(name)
            if rep is None:
                self._retiring.pop(name)
                continue
            bound = any(req.replica == name and not req.stream.finished
                        for req in router._requests.values())
            if bound or (rep.state != DEAD
                         and getattr(rep.engine, "has_work", False)):
                continue
            spawned = self._retiring.pop(name)
            engine = rep.engine
            try:
                router.remove_replica(name)
            except Exception:
                self._retiring[name] = spawned
                continue
            if spawned and not getattr(engine, "closed", False):
                close = getattr(engine, "close", None)
                if close is not None:
                    close()
            self.stats["reaped"] += 1

    # -- telemetry ---------------------------------------------------------
    def _refresh_gauge(self, router) -> None:
        reg = get_registry()
        if not reg.enabled:
            return
        gauge = tmetrics.fleet_replicas_gauge(reg)
        counts: Dict[str, int] = {}
        for rep in router.replicas.values():
            counts[rep.state] = counts.get(rep.state, 0) + 1
        for state in ("healthy", "draining", "backing_off",
                      "probation", "dead"):
            gauge.set(counts.get(state, 0), state=state)

    def _note(self, now: float, action: str, replica: str,
              **extra: Any) -> None:
        entry: Dict[str, Any] = {"t": round(now, 4), "action": action,
                                 "replica": replica}
        entry.update(extra)
        self.history.append(entry)
        del self.history[:-4096]       # bounded, like the router's _done
