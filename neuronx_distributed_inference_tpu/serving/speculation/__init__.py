"""Speculative continuous batching — draft-and-verify decode on the paged
serving engine (ROADMAP item 4; see README "Speculative serving").

The subsystem has two halves:

* :mod:`.proposer` — the draft side. :class:`SelfDraftProposer` is the
  always-available baseline (the target model greedily drafts its own
  continuation through one fused masked loop);
  :class:`PerturbedSelfDraftProposer` deterministically corrupts a draft
  column (pinned partial-accept tests and chaos drills);
  :class:`MedusaProposer` / :class:`EagleProposer` adapt the existing
  non-serving proposers from ``models/speculation.py`` to the
  continuous-batching world (per-sequence feature/draft-cache state,
  eviction-aware).
* :mod:`.verifier` — :class:`SpeculativeDecodePath`, the engine-step
  machinery: per-row candidate widths padded within the
  ``autobucketing.spec_width_buckets`` ladder, ONE batched k+1-token
  verify dispatch per engine step with in-graph greedy acceptance, KV
  grown for the draft window then shrunk to the accepted prefix, and
  per-sequence accept cursors feeding variable tokens-per-step streams.

Attach by constructing the adapter with ``speculation=``::

    eng = PagedEngineAdapter(app, speculation=SelfDraftProposer(k=3))
    eng.add_requests([0], [prompt])
    eng.step()        # -> {0: [t1, t2, t3, t4]} (accepted + bonus)

Correctness never depends on the proposer: whatever it drafts, the
delivered tokens are the target's own greedy choices (verified), so
accepted-token streams are bit-identical to non-speculative decode —
a bad proposer only costs acceptance rate, never output quality.
"""

from .proposer import (DraftProposer, EagleProposer, MedusaProposer,
                       PerturbedSelfDraftProposer, SelfDraftProposer)
from .verifier import SpeculativeDecodePath

__all__ = [
    "DraftProposer", "SelfDraftProposer", "PerturbedSelfDraftProposer",
    "MedusaProposer", "EagleProposer", "SpeculativeDecodePath",
]
