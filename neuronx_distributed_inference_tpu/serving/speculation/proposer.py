"""Draft proposers for speculative serving (see package docstring).

A proposer's ONLY job is to guess the target model's next ``k`` greedy
tokens per row; the verifier checks every guess against the target in one
dispatch, so a proposer can never corrupt the output stream — accepted
tokens are the target's own greedy choices whatever was drafted. Bad
drafts cost acceptance rate (fewer tokens per verify dispatch), nothing
else.

The proposer contract is deliberately device-friendly: ``propose``
returns the draft tokens as a DEVICE array and ``on_verify`` receives the
verify graph's hidden features as a device array — drafts and features
never round-trip through the host (``host_stats["blocking_fetches"]``
counts exactly one sync per speculative step, the verify fetch).

Proposers carrying per-sequence state (Medusa features, the EAGLE draft
cache) key it by seq_id and drop it on :meth:`DraftProposer.forget` —
the adapter calls it from release/preemption/rollback, so an evicted
sequence can never poison a re-admission under the same id.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...resilience.errors import ConfigurationError

__all__ = ["DraftProposer", "SelfDraftProposer",
           "PerturbedSelfDraftProposer", "MedusaProposer", "EagleProposer"]


class DraftProposer:
    """Base proposer: ``max_drafts`` bounds the candidate width the
    verifier budgets for (width = drafts + 1); ``wants_hidden`` asks the
    verify graph to hand back its hidden features (Medusa/EAGLE feed on
    them; the self-draft baseline keeps the graph lean without them)."""

    name = "base"
    wants_hidden = False

    def __init__(self, k: int):
        if k < 1:
            raise ConfigurationError(
                f"speculation needs k >= 1 draft tokens, got {k}")
        self.max_drafts = int(k)

    def bind(self, adapter) -> None:
        """Called once when the adapter adopts this proposer."""

    def propose(self, ctx):
        """Draft up to ``ctx.num_drafts`` tokens per row. Returns a
        (padded_batch, ctx.num_drafts) int32 array (device arrays
        welcome), or None to skip drafting entirely (the step degenerates
        to an eager-equivalent width-1 verify)."""
        raise NotImplementedError

    def on_verify(self, ctx, tokens: np.ndarray, n_emit: np.ndarray,
                  hidden) -> None:
        """Post-verify feedback: ``tokens``/``n_emit`` are the fetched
        accept results for the live rows, ``hidden`` the device features
        (None unless ``wants_hidden``)."""

    def forget(self, seq_ids: Sequence[int]) -> None:
        """Drop per-sequence state (release / preemption / rollback)."""


class SelfDraftProposer(DraftProposer):
    """Greedy-k SELF-drafting — the always-available baseline: the target
    model drafts its own continuation through one fused masked loop
    (``model_base.paged_spec_draft_loop``), so no extra weights, no extra
    memory, and (under greedy sampling) every draft matches the verify
    graph's greedy choice — the accept rate is pinned at 1.0 and each
    engine step delivers the full k+1 tokens for two dispatches."""

    name = "self_draft"

    def propose(self, ctx):
        if ctx.num_drafts < 1:
            return None
        return ctx.path._dispatch_spec_draft(ctx)


class PerturbedSelfDraftProposer(SelfDraftProposer):
    """Self-draft with draft column ``corrupt_at`` deterministically
    corrupted (+1 mod vocab): the corrupted draft can never equal the
    target's greedy choice, so acceptance stops exactly there —
    ``corrupt_at`` drafts accepted per full-width step, a FIXED partial
    accept rate. This is the pinned <1.0 fixture the accept bookkeeping,
    KV shrink and rejection paths are tested against (and a chaos drill:
    a broken proposer must only cost throughput, never correctness)."""

    name = "perturbed_self_draft"

    def __init__(self, k: int, corrupt_at: int = 1):
        super().__init__(k)
        if not 0 <= corrupt_at < k:
            raise ConfigurationError(
                f"corrupt_at must be in [0, {k}), got {corrupt_at}")
        self.corrupt_at = corrupt_at
        self._vocab: Optional[int] = None

    def bind(self, adapter) -> None:
        self._vocab = adapter.app.spec.vocab_size

    def propose(self, ctx):
        drafts = super().propose(ctx)
        if drafts is None or ctx.num_drafts <= self.corrupt_at:
            return drafts
        import jax.numpy as jnp
        col = drafts[:, self.corrupt_at]
        return jnp.asarray(drafts).at[:, self.corrupt_at].set(
            (col + 1) % self._vocab)


class MedusaProposer(DraftProposer):
    """Serving adapter over the medusa heads of ``models/speculation.py``
    (:func:`~...models.speculation.medusa_propose`, chain mode): head j
    predicts the token j+2 positions past the feature's, so the chain
    [head_0 .. head_{k-1}] drafted from the feature of position p-1 lines
    up exactly with candidate columns 1..k at positions p+1..p+k.

    Per-row features come from the verify graph itself (``wants_hidden``):
    after each step the feature at the bonus position is stored per
    seq_id. A row with no feature yet (fresh admission — the chunked
    paged prefill exposes no hidden states) drafts nothing its first
    step; the verify bonus token both advances it and seeds its feature.
    """

    name = "medusa"
    wants_hidden = True

    def __init__(self, k: int):
        super().__init__(k)
        self._feat: Dict[int, Any] = {}
        self._propose_fn = None
        self._hidden_size = 0

    def bind(self, adapter) -> None:
        import jax
        from ...models.speculation import medusa_propose
        spec = adapter.app.spec
        if spec.medusa_heads < self.max_drafts:
            raise ConfigurationError(
                f"MedusaProposer(k={self.max_drafts}) needs >= k medusa "
                f"heads; the target spec has {spec.medusa_heads}")
        self._hidden_size = spec.hidden_size
        self._params = adapter.app.params
        self._propose_fn = jax.jit(partial(medusa_propose, spec),
                                   static_argnames=("top_k",))

    def propose(self, ctx):
        if ctx.num_drafts < 1 or not any(s in self._feat
                                         for s in ctx.live):
            return None
        return ctx.path._dispatch_propose(self, ctx)

    def _propose_device(self, ctx):
        """Device work of one medusa chain proposal (called through the
        verifier's ``_dispatch_propose`` lint region)."""
        import jax.numpy as jnp
        zero = jnp.zeros((self._hidden_size,), jnp.float32)
        feats = [self._feat.get(s, zero) for s in ctx.live]
        feats += [feats[0]] * (ctx.padded_batch - len(feats))
        props = self._propose_fn(self._params, jnp.stack(feats),
                                 top_k=1)
        return props[:, :ctx.num_drafts, 0]

    def on_verify(self, ctx, tokens, n_emit, hidden) -> None:
        import jax.numpy as jnp
        # hidden is padded to the batch bucket; n_emit covers live rows
        feat = jnp.take_along_axis(
            hidden[:len(ctx.live)],
            jnp.asarray(n_emit - 1)[:, None, None], axis=1)[:, 0]
        for i, s in enumerate(ctx.live):
            self._feat[s] = feat[i]

    def forget(self, seq_ids: Sequence[int]) -> None:
        for s in seq_ids:
            self._feat.pop(s, None)


class EagleProposer(DraftProposer):
    """Serving adapter over the EAGLE draft of ``models/speculation.py``:
    the chain rollout (:func:`~...models.speculation.eagle_propose_scored`
    shape, greedy top-1) proposes from a small fused draft model whose
    contiguous KV cache rows are keyed by a STABLE per-sequence slot
    (seq_ids-addressed writes), and after every verify the draft cache is
    refreshed with the verified (token, target-feature) pairs — the same
    post-acceptance refresh the fused non-serving path runs.

    Serving difference vs ``EagleDecoder``: the paged prefill path
    exposes no prompt hidden states, so the draft cache is primed
    INCREMENTALLY from the verified feature stream instead of from a
    prefill pass — early drafts for a fresh row are uninformed (low
    accept rate, never wrong output) and sharpen as verified context
    accumulates. Rows are dropped from the slot map on ``forget``.
    """

    name = "eagle"
    wants_hidden = True

    def __init__(self, draft_spec, draft_params, k: int,
                 input_norm: bool = False):
        super().__init__(k)
        self.draft_spec = draft_spec
        self.draft_params = draft_params
        self.input_norm = input_norm
        self._slots: Dict[int, int] = {}
        self._free: List[int] = []
        self._feat: Dict[int, Any] = {}
        self.draft_cache = None

    def bind(self, adapter) -> None:
        import dataclasses
        import jax
        from ...models.speculation import eagle_forward
        from ...modules.kv_cache import KVCacheSpec, init_cache
        app = adapter.app
        cfg = app.tpu_config
        self._seq_len = cfg.seq_len
        self._hidden_size = self.draft_spec.hidden_size
        self._free = list(range(adapter.batch))
        self.draft_cache = init_cache(KVCacheSpec(
            num_layers=self.draft_spec.num_layers,
            batch_size=adapter.batch, max_seq_len=cfg.seq_len,
            num_kv_heads=self.draft_spec.gqa.num_kv_heads,
            head_dim=self.draft_spec.head_dim,
            dtype=self.draft_spec.kv_dtype), app.mesh)
        # seq_ids-addressed draft-cache rows: the target cfg is NOT
        # continuous-batching (paged), so flip the flag on a copy — the
        # draft cache must key rows by the stable slot, not batch order
        draft_cfg = dataclasses.replace(cfg, is_continuous_batching=True)

        def chain(params, cache, first, feat, pos, sids, widths,
                  num_steps):
            import jax.numpy as jnp
            seq_len = cfg.seq_len

            def dstep(carry, j):
                tok, hid, p, cch = carry
                # per-row width clamp: a finished row's draft-KV write is
                # pushed past seq_len (dropped) and its carry frozen, so
                # ragged widths never write outside a row's window
                valid = j < widths - 1
                wpos = jnp.where(valid, p, seq_len)
                out = eagle_forward(self.draft_spec, draft_cfg, params,
                                    cch, tok[:, None], hid[:, None, :],
                                    wpos[:, None], sids, self.input_norm)
                ntok = jnp.where(
                    valid,
                    jnp.argmax(out["logits"][:, -1, :],
                               axis=-1).astype(jnp.int32), tok)
                nhid = jnp.where(valid[:, None],
                                 out["hidden"][:, -1, :], hid)
                return (ntok, nhid, jnp.where(valid, p + 1, p),
                        out["cache"]), ntok

            (_, _, _, cch), toks = jax.lax.scan(
                dstep, (first, feat, pos, cache),
                jnp.arange(num_steps))
            return jnp.transpose(toks, (1, 0)), cch

        self._chain = jax.jit(chain, static_argnames=("num_steps",))
        self._refresh = jax.jit(
            partial(eagle_forward, self.draft_spec, draft_cfg,
                    input_norm=self.input_norm), donate_argnums=(1,))

    def _slot_of(self, sid: int) -> int:
        if sid not in self._slots:
            self._slots[sid] = self._free.pop()
        return self._slots[sid]

    def propose(self, ctx):
        if ctx.num_drafts < 1:
            return None
        return ctx.path._dispatch_propose(self, ctx)

    def _row_arrays(self, ctx):
        import jax.numpy as jnp
        zero = jnp.zeros((self._hidden_size,),
                         self.draft_spec.dtype)
        feats = [self._feat.get(s, zero) for s in ctx.live]
        sids = [self._slot_of(s) for s in ctx.live]
        pad = ctx.padded_batch - len(feats)
        feats += [feats[0]] * pad
        sids += [sids[0]] * pad
        return jnp.stack(feats), np.asarray(sids, np.int32)

    def _propose_device(self, ctx):
        import jax.numpy as jnp
        feats, sids = self._row_arrays(ctx)
        toks, self.draft_cache = self._chain(
            self.draft_params, self.draft_cache,
            jnp.asarray(ctx.first), feats, jnp.asarray(ctx.positions),
            jnp.asarray(sids), jnp.asarray(ctx.widths),
            num_steps=ctx.num_drafts)
        return toks

    def on_verify(self, ctx, tokens, n_emit, hidden) -> None:
        ctx.path._dispatch_eagle_refresh(self, ctx, hidden)
        import jax.numpy as jnp
        # hidden is padded to the batch bucket; n_emit covers live rows
        feat = jnp.take_along_axis(
            hidden[:len(ctx.live)],
            jnp.asarray(n_emit - 1)[:, None, None], axis=1)[:, 0]
        for i, s in enumerate(ctx.live):
            self._feat[s] = feat[i]

    def _refresh_device(self, ctx, hidden):
        """Draft-cache refresh with the VERIFIED pairs: slot p+j gets
        (candidate token at p+j, target feature at p+j-1); columns past
        each row's width are pushed to seq_len so their writes drop."""
        import jax.numpy as jnp
        feats, sids = self._row_arrays(ctx)
        cand = ctx.cand                                # (Bp, W) device
        hid_seq = jnp.concatenate(
            [feats[:, None, :].astype(hidden.dtype),
             hidden[:, :-1, :]], axis=1) if cand.shape[1] > 1 \
            else feats[:, None, :].astype(hidden.dtype)
        w = cand.shape[1]
        idx = jnp.arange(w, dtype=jnp.int32)[None, :]
        pos = jnp.asarray(ctx.positions)[:, None] + idx
        pos = jnp.where(idx < jnp.asarray(ctx.widths)[:, None], pos,
                        self._seq_len)
        out = self._refresh(self.draft_params, self.draft_cache, cand,
                            hid_seq, pos, jnp.asarray(sids))
        self.draft_cache = out["cache"]

    def forget(self, seq_ids: Sequence[int]) -> None:
        for s in seq_ids:
            self._feat.pop(s, None)
            slot = self._slots.pop(s, None)
            if slot is not None:
                self._free.append(slot)
