"""The speculative engine-step machinery (see package docstring).

:class:`SpeculativeDecodePath` owns one engine step of draft-and-verify
decode on a :class:`~..adapter.PagedEngineAdapter`:

  1. per-row candidate widths — ``k+1`` clamped by seq_len headroom and
     the scheduler's per-row token room — padded to the
     ``autobucketing.spec_width_buckets`` ladder (a fully clamped batch
     degenerates to an eager-equivalent width-1 verify);
  2. per-row KV growth for the whole candidate window (preemption-aware:
     pool pressure evicts victims exactly like the non-speculative grow);
  3. the proposer's draft pass (device-resident tokens — drafts never
     round-trip through the host, in eager AND pipelined modes);
  4. ONE batched k+1-token verify dispatch over the existing
     block-table/slot-mapping graph with in-graph acceptance
     (``model_base.paged_spec_verify``) — greedy exact-match, or
     gumbel-coupled rejection sampling when the adapter runs seeded
     sampled decode (README "Sampled speculation & compressed decode") —
     columns past a row's width at slot -1 (dropped writes);
  5. host accept bookkeeping: per-sequence accept cursors advance
     ``_SeqState.position``/``tokens`` by ``num_emitted``, KV shrinks to
     the accepted prefix (``BlockKVCacheManager.shrink``), and the step
     returns variable tokens-per-row ``{seq_id: [tokens]}``.

Failure contract: ``spec_draft``/``spec_verify`` fault points fire at the
two dispatches; any failure shrinks every packed row's KV growth back to
its last ACCEPTED token and leaves positions untouched, then raises a
typed :class:`~...resilience.errors.StepFailure` — no half-accepted cache
poisoning (pinned by tests/test_spec_serving.py). The dispatch helpers
(``_dispatch_spec_draft`` / ``_dispatch_spec_verify``) must never
materialize device values — tier-1 lint region
(the ``host-sync`` pass of ``scripts/nxdi_lint.py``); the single
blocking sync per step is
the verify fetch.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...modules import autobucketing
from ...modules.block_kv_cache import slots_from_table
from ...resilience.errors import (CapacityError, ConfigurationError,
                                  ServingError, StepFailure)
from ...resilience.faults import FAULTS as _FAULTS
from ...telemetry.trace import get_recorder as _get_recorder
from ..adapter import (_async_fetch, _live_rows, _meta_seed,
                       _pre_step_checks, _repeat_row0, _trace_error)
from .proposer import DraftProposer

__all__ = ["SpeculativeDecodePath", "validate_spec_sampling"]

logger = logging.getLogger("nxdi_tpu")


def validate_spec_sampling(sampling_config, where: str) -> str:
    """Resolve a speculative path's verify mode from the adapter's
    on-device sampling config: ``"greedy"`` (no config, or
    ``do_sample=False``) or ``"sampled"`` (seeded coupled sampling —
    ``do_sample=True`` with ``stream_seed`` set). UNSEEDED sampling is
    the one still-refused configuration: without a stream seed every
    dispatch draws fresh noise, so verify could never reproduce the
    target draw a draft must match and the emitted stream would depend
    on batch composition."""
    if sampling_config is None or not sampling_config.do_sample:
        return "greedy"
    if sampling_config.stream_seed is None:
        raise ConfigurationError(
            f"{where} supports sampled speculation only for SEEDED "
            "streams: set on_device_sampling_config.stream_seed (coupled "
            "rejection sampling replays the per-position gumbel draw the "
            "draft must match). Supported: greedy (do_sample=False, no "
            "seed needed) and seeded sampling; unseeded do_sample is "
            "not.")
    return "sampled"


@dataclass
class _SpecContext:
    """Everything a proposer needs to draft for one engine step. Arrays
    are already padded to the batch bucket (pad rows clone row 0 — the
    usual invariant); ``cand`` is filled in before ``on_verify`` so
    feature-refreshing proposers (EAGLE) see the verified candidates."""
    path: "SpeculativeDecodePath"
    live: Tuple[int, ...]          # live seq_ids, dispatch row order
    b: int                         # live rows (before batch padding)
    padded_batch: int
    num_drafts: int                # bucketed width - 1
    first: np.ndarray              # (Bp,) last accepted tokens
    positions: np.ndarray          # (Bp,) their positions
    widths: np.ndarray             # (Bp,) per-row candidate widths
    block_table: np.ndarray        # (Bp, table-width bucket)
    seeds: np.ndarray = None       # (Bp,) per-row sampling stream seeds
    aids: np.ndarray = None        # (Bp,) per-row LoRA adapter slots
    cand: Any = field(default=None)  # (Bp, W) device candidates


class SpeculativeDecodePath:
    """Draft-and-verify stepping for one paged adapter + one proposer."""

    def __init__(self, adapter, proposer: DraftProposer):
        if not isinstance(proposer, DraftProposer):
            raise ConfigurationError(
                "speculation= takes a DraftProposer (e.g. "
                f"SelfDraftProposer(k)), got {type(proposer).__name__}")
        cfg = adapter.app.tpu_config
        if adapter._pos_limit is None:
            raise ConfigurationError(
                "speculative decode over rolling-window caches is not "
                "supported (the accept window needs absolute positions)")
        self.mode = validate_spec_sampling(cfg.on_device_sampling_config,
                                           where="speculative serving")
        self.adapter = adapter
        self.proposer = proposer
        self.max_width = proposer.max_drafts + 1
        self.width_buckets = autobucketing.spec_width_buckets(self.max_width)
        stats = adapter.host_stats
        for key in ("spec_steps", "spec_draft_dispatches",
                    "spec_verify_dispatches", "spec_drafted_tokens",
                    "spec_accepted_tokens"):
            stats.setdefault(key, 0)
        proposer.bind(adapter)

    # -- the speculative engine step ---------------------------------------
    def step(self, seq_ids: Optional[Sequence[int]] = None,
             token_room: Optional[Dict[int, int]] = None
             ) -> Dict[int, List[int]]:
        """One speculative engine step: at most one prefill-chunk
        dispatch (mixed load), one draft pass and EXACTLY one verify
        dispatch; returns ``{seq_id: [accepted tokens + bonus]}`` with
        1..k+1 tokens per row. ``token_room`` (scheduler hook) clamps a
        row's candidate width so a step never overshoots its remaining
        token budget."""
        ad = self.adapter
        if ad._inflight is not None:
            ad._stash_flush()          # retire a pre-spec pipelined step
        pending = ad._pending_ids()
        live = _live_rows(ad.seqs, seq_ids, pending)

        def drain() -> Dict[int, List[int]]:
            return {s: [t] for s, t in ad._drain_ready().items()}

        if not live and not pending:
            return drain()
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        if live:
            # deadlines + the 1-token floor BEFORE any draft work; the
            # spec window itself is clamped per row, never raised on
            _pre_step_checks(ad.seqs, live, ad._pos_limit, ad.telemetry,
                             horizon=1)
        ad._advance_prefill(seq_ids)
        if not live:
            return drain()
        t0 = time.perf_counter()
        limit = ad._pos_limit
        # degradation shed: every window clamps to width 1 — the step
        # degenerates to the eager-equivalent verify (no draft dispatch,
        # same tokens in both modes: greedy argmax trivially, coupled
        # sampling because the position-keyed draws are path-invariant);
        # see PagedEngineAdapter.set_speculation_shed
        max_w = 1 if ad._spec_shed else self.max_width
        widths = {}
        for s in live:
            w = min(max_w, limit - ad.seqs[s].position)
            if token_room is not None and s in token_room:
                w = min(w, token_room[s])
            widths[s] = max(1, int(w))
        live = self._grow_for_spec(live, widths)
        if not live:
            return drain()
        # _ready (first tokens from finished prefills) is drained only
        # after the fallible stages: a StepFailure mid-verify leaves them
        # deliverable by the next returning call instead of dropping them
        res = self._draft_verify_accept(live, widths, t0)
        out = drain()
        for s, row in res.items():
            out.setdefault(s, []).extend(row)
        return out

    # -- internals ---------------------------------------------------------
    def _grow_for_spec(self, live: List[int],
                       widths: Dict[int, int]) -> List[int]:
        """Grow every row's block list to cover its candidate window,
        evicting victims per the adapter's preemption policy when the
        pool runs dry (rows preempted mid-grow leave ``live``). On an
        unevictable CapacityError all growth from this call is rolled
        back before the raise."""
        ad = self.adapter
        mgr = ad.app.kv_mgr
        live = list(live)
        queue = list(live)
        grown: List[int] = []
        while queue:
            s = queue[0]
            try:
                mgr.grow(s, widths[s])
            except CapacityError:
                victim = ad._choose_victim()
                if victim is None:
                    for g in grown:
                        mgr.shrink(g, widths[g])
                    raise
                ad._preempt(victim, reason="grow")
                for lst in (queue, live, grown):
                    if victim in lst:
                        lst.remove(victim)
                continue
            queue.pop(0)
            grown.append(s)
        return live

    def _rollback(self, live: Sequence[int], widths: Dict[int, int]):
        for s in live:
            self.adapter.app.kv_mgr.shrink(s, widths[s])

    def run_draft(self, live: List[int], widths: Dict[int, int],
                  rollback) -> Tuple[Any, int, _SpecContext]:
        """The draft preamble shared by the standalone speculative step
        and the ragged unified step: build the row-0-padded
        :class:`_SpecContext` over ``live``, fire the ``spec_draft``
        fault point, and run the proposer's draft pass. On any failure
        ``rollback()`` unwinds the caller's KV growth before the typed
        raise. Returns ``(drafts or None, bucketed width W, ctx)`` —
        a sat-out proposer (``drafts is None`` with ``W > 1``) leaves
        the unused-window release to the caller."""
        ad = self.adapter
        app = ad.app
        b = len(live)
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        wmax = max(widths[s] for s in live)
        W = autobucketing.get_target_bucket(self.width_buckets, wmax,
                                            kind="spec")
        first = np.asarray([ad.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([ad.seqs[s].position for s in live], np.int32)
        wid = np.asarray([widths[s] for s in live], np.int32)
        seeds = np.asarray([_meta_seed(ad.seqs[s].meta) for s in live],
                           np.int32)
        aids = ad._lora_aids(live)
        if aids is not None:
            aids = np.asarray(aids, np.int32)
        bt = app.kv_mgr.block_table_array(live, app._bt_width_for(live))
        if pad_to > b:
            first, pos, wid, seeds, bt = (_repeat_row0(x, pad_to)
                                          for x in (first, pos, wid,
                                                    seeds, bt))
            if aids is not None:
                aids = _repeat_row0(aids, pad_to)
        ctx = _SpecContext(path=self, live=tuple(live), b=b,
                           padded_batch=pad_to, num_drafts=W - 1,
                           first=first, positions=pos, widths=wid,
                           block_table=bt, seeds=seeds, aids=aids)
        cache_before = app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("spec_draft")
            if app._steady_state:
                # a draft-pass compile in steady state is an incident like
                # any other: attribute it to the live rows' request traces
                with app.request_context(ad._traces_of(live)):
                    drafts = (self.proposer.propose(ctx) if W > 1 else None)
            else:
                drafts = (self.proposer.propose(ctx) if W > 1 else None)
        except ServingError as e:
            rollback()
            _trace_error(e)
            raise
        except Exception as e:
            rollback()
            ad.telemetry.on_step_failure("spec", ad._tenant_of(live))
            raise _trace_error(StepFailure(
                "speculative draft pass failed; KV growth was rolled back "
                "and positions were not advanced",
                phase="spec_draft", seq_ids=tuple(live),
                retry_safe=app.cache is cache_before)) from e
        return drafts, W, ctx

    def _draft_verify_accept(self, live: List[int], widths: Dict[int, int],
                             t0: float) -> Dict[int, List[int]]:
        import jax.numpy as jnp
        ad = self.adapter
        app = ad.app
        tenant = ad._tenant_of(live)
        drafts, W, ctx = self.run_draft(
            live, widths, lambda: self._rollback(live, widths))
        b, pad_to = ctx.b, ctx.padded_batch
        first, pos, wid, bt = (ctx.first, ctx.positions, ctx.widths,
                               ctx.block_table)
        if drafts is None and W > 1:
            # the proposer sat this step out: release the unused window
            for s in live:
                if widths[s] > 1:
                    app.kv_mgr.shrink(s, widths[s] - 1)
                    widths[s] = 1
            wid = np.ones_like(wid)
            W = 1
            ctx.num_drafts = 0
            ctx.widths = wid
        first_dev = jnp.asarray(first)[:, None]
        cand = (first_dev if W == 1 else
                jnp.concatenate([first_dev, jnp.asarray(drafts)[:, :W - 1]],
                                axis=1))
        ctx.cand = cand
        cols = np.arange(W, dtype=np.int32)[None, :]
        pos_w = pos[:, None] + cols
        slot_pos = np.where(cols < wid[:, None], pos_w, -1)
        slots = slots_from_table(bt, slot_pos, app.kv_mgr.spec.block_size)
        # re-snapshot AFTER the draft: stale draft KV past the accepted
        # prefix is rewritten before any read, so a failure in front of
        # the verify dispatch leaves a retryable cache — only a crash
        # inside the dispatch itself (donated buffers consumed) is not
        cache_before = app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("spec_verify")
            out = self._dispatch_spec_verify(ctx, cand, pos_w, slots)
            toks, n_emit = self._fetch_verify(out, b)
        except ServingError as e:
            self._rollback(live, widths)
            _trace_error(e)
            raise
        except Exception as e:
            self._rollback(live, widths)
            ad.telemetry.on_step_failure("spec", tenant)
            raise _trace_error(StepFailure(
                "speculative verify dispatch failed; every packed row was "
                "rolled back to its last accepted token",
                phase="spec_verify", seq_ids=tuple(live),
                retry_safe=app.cache is cache_before)) from e
        res: Dict[int, List[int]] = {}
        drafted = accepted = delivered = 0
        rows = []
        for i, s in enumerate(live):
            st = ad.seqs[s]
            w = widths[s]
            n = int(n_emit[i])
            row = [int(t) for t in toks[i, :n]]
            st.position += n
            for t in row:
                ad._append_token(st, t)
            if w > n:
                app.kv_mgr.shrink(s, w - n)
            res[s] = row
            drafted += w - 1
            accepted += n - 1
            delivered += n
            rows.append((s, n))
        stats = ad.host_stats
        stats["spec_steps"] += 1
        stats["spec_drafted_tokens"] += drafted
        stats["spec_accepted_tokens"] += accepted
        ad.telemetry.on_spec_step(rows, t0, padded=pad_to, width=W,
                                  drafted=drafted, accepted=accepted,
                                  mode=self.mode)
        try:
            self.proposer.on_verify(ctx, toks, n_emit,
                                    out.get("hidden")
                                    if self.proposer.wants_hidden else None)
        except Exception:
            # the step's tokens are already accepted and delivered — a
            # broken proposer must only cost acceptance rate, never the
            # output stream: drop its per-sequence state and keep serving
            logger.warning(
                "speculative proposer %r failed in on_verify; its "
                "per-sequence state was dropped (seq_ids=%s)",
                self.proposer.name, list(live), exc_info=True)
            self.proposer.forget(live)
        return res

    # -- dispatch regions (nxdi_lint host-sync pass) -----------------------
    def _dispatch_spec_draft(self, ctx: _SpecContext):
        """Issue the self-draft loop WITHOUT materializing any output —
        the draft tokens stay on device and feed the verify dispatch
        directly (in eager and pipelined modes alike)."""
        ad = self.adapter
        kw = {"row_seeds": ctx.seeds}
        if ctx.aids is not None:
            kw["adapter_ids"] = ctx.aids
        out = ad.app._run_spec_draft(ctx.first, ctx.positions,
                                     ctx.block_table, ctx.widths,
                                     ctx.num_drafts, **kw)
        ad.host_stats["dispatches"] += 1
        ad.host_stats["spec_draft_dispatches"] += 1
        ad.host_stats["device_steps"] += ctx.num_drafts
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("dispatch.spec_draft", cat="adapter",
                        engine=ad.engine_name, rows=ctx.b,
                        pad_to=ctx.padded_batch, drafts=ctx.num_drafts,
                        seq_ids=list(ctx.live))
        return out["tokens"]

    def _dispatch_propose(self, proposer, ctx: _SpecContext):
        """Proposer-side draft dispatch (Medusa heads / EAGLE chain):
        device work only, tokens stay on device."""
        ad = self.adapter
        toks = proposer._propose_device(ctx)
        ad.host_stats["dispatches"] += 1
        ad.host_stats["spec_draft_dispatches"] += 1
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("dispatch.spec_draft", cat="adapter",
                        engine=ad.engine_name, rows=ctx.b,
                        pad_to=ctx.padded_batch, drafts=ctx.num_drafts,
                        proposer=proposer.name, seq_ids=list(ctx.live))
        return toks

    def _dispatch_eagle_refresh(self, proposer, ctx: _SpecContext, hidden):
        """EAGLE draft-cache refresh dispatch (verified pairs)."""
        ad = self.adapter
        proposer._refresh_device(ctx, hidden)
        ad.host_stats["dispatches"] += 1
        ad.host_stats["spec_draft_dispatches"] += 1

    def _dispatch_spec_verify(self, ctx: _SpecContext, cand, pos_w, slots):
        """Issue THE verify dispatch (one per engine step) without
        materializing any output; the async copies are started so the
        fetch one call later is cheap."""
        ad = self.adapter
        kw = {"row_seeds": ctx.seeds}
        if ctx.aids is not None:
            kw["adapter_ids"] = ctx.aids
        out = ad.app._run_spec_verify(
            cand, pos_w, slots, ctx.block_table, ctx.widths,
            want_hidden=self.proposer.wants_hidden, **kw)
        _async_fetch(out["tokens"])
        _async_fetch(out["num_emitted"])
        ad.host_stats["dispatches"] += 1
        ad.host_stats["spec_verify_dispatches"] += 1
        ad.host_stats["device_steps"] += 1
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("dispatch.spec_verify", cat="adapter",
                        engine=ad.engine_name, rows=ctx.b,
                        pad_to=ctx.padded_batch, width=int(cand.shape[1]),
                        seq_ids=list(ctx.live))
        return out

    def _fetch_verify(self, out, b: int):
        """The ONE blocking sync of a speculative step."""
        ad = self.adapter
        t0 = time.perf_counter()
        toks = np.asarray(out["tokens"])[:b]
        n_emit = np.asarray(out["num_emitted"])[:b]
        t1 = time.perf_counter()
        ad.host_stats["blocking_fetches"] += 1
        ad.host_stats["blocked_s"] += t1 - t0
        rec = _get_recorder()
        if rec.enabled:
            rec.complete("fetch.tokens", t0, cat="adapter", t1=t1,
                         engine=ad.engine_name, rows=b, phase="spec")
        return toks, n_emit
