"""Serving package: the engine adapters (``serving.adapter`` — the
importable continuous-batching contract over both applications) and the
multi-tenant serving engine built on top of the paged adapter
(``serving.engine`` — queue + scheduler + token streams + HTTP/SSE front
door; see README "Serving engine").

Importing ``neuronx_distributed_inference_tpu.serving`` keeps exposing the
adapter surface unchanged (this module used to be ``serving.py``); the
engine layer is imported explicitly from ``.engine``, the fleet layer
above it (replicated-engine router, host-RAM KV spill tier, disaggregated
prefill handoff — README "Fleet") explicitly from ``.fleet``, and the
ragged unified dispatch (one mixed prefill+decode+verify dispatch per
engine step, enabled with ``PagedEngineAdapter(app, ragged=True)`` —
README "Ragged dispatch") explicitly from ``.ragged``.
"""

from .adapter import (ContinuousBatchingAdapter, PagedEngineAdapter,
                      _EngineAdapterBase)
from .lora_pool import LoraAdapterPool

__all__ = ["ContinuousBatchingAdapter", "LoraAdapterPool",
           "PagedEngineAdapter", "_EngineAdapterBase"]
