"""Bounded per-replica LoRA adapter pool — S-LoRA-style paged adapter
serving over the stacked in-graph factors (ROADMAP item 4; reference:
modules/lora_serving/, PAPER.md §L4).

The traced paged graphs gather each row's (A, B) factors from the stacked
``lora_A_<mod>`` / ``lora_B_<mod>`` device arrays by per-row
``adapter_ids`` (modules/lora.py), so ONE ragged dispatch mixes rows from
different adapters at one-dispatch-per-step cost. What was missing is the
RESIDENCY layer: a replica serves K tenants whose adapters do not all fit
the ``max_loras`` device slots at once. :class:`LoraAdapterPool` owns
that layer for one application:

  * **device residency** — slots ``1..max_loras-1`` of the stacked
    arrays (slot 0 is the pinned ZERO adapter: base-model rows gather it
    and stay bit-identical). ``acquire(name)`` returns the adapter's
    resident slot, loading it on miss; residency is LRU with per-slot
    pin counts, so a slot serving live rows is never evicted from under
    them (``release`` unpins — eviction only claims refcount-0 slots).
  * **host-RAM spill/restore** — the same two-tier shape as the KV
    spill tier (serving/fleet/kv_tier.py): an evicted slot's factors are
    copied device→host into a bounded ``OrderedDict`` cache
    (oldest-touched eviction), and a later re-acquire restores from host
    RAM instead of re-reading the checkpoint. Spills are BEST-EFFORT —
    the ``adapter_spill`` fault point fires inside the spill and a trip
    is swallowed and counted (``stats["spill_errors"]``), never failing
    the acquisition that evicted the slot.
  * **transactional swap** — the device write of a swap snapshots every
    stacked leaf it will touch and restores them on ANY failure, so a
    failed swap (the ``adapter_swap`` fault point fires between the
    snapshot and the write) never corrupts a resident slot; the failure
    surfaces as a retry-safe typed
    :class:`~..resilience.errors.StepFailure` (``phase="adapter_swap"``).

Adapters are registered by name, either as a PEFT checkpoint dir
(loaded + GQA-transformed lazily via the application's
``lora_adapter_arrays``) or as pre-transformed host arrays
(``register_arrays`` — tests/bench/chaos need no torch checkpoint).
Loading is keyed off the registration, so the pool never interprets
paths itself.

Observability: ``nxdi_lora_residency_hits_total`` /
``nxdi_lora_swaps_total{adapter}`` / ``nxdi_lora_swap_bytes`` (README
"Observability"), the always-on :attr:`stats` counters (feed
``bench.py --lora-churn``), and ``lora.swap`` / ``lora.spill`` flight-
recorder events.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from ..resilience.errors import (CapacityError, ConfigurationError,
                                 StepFailure)
from ..resilience.faults import FAULTS as _FAULTS
from ..telemetry import get_registry
from ..telemetry import metrics as tmetrics
from ..telemetry.trace import get_recorder as _get_recorder

__all__ = ["LoraAdapterPool"]


class LoraAdapterPool:
    """Bounded device-slot residency for named LoRA adapters over ONE
    paged application's stacked adapter arrays."""

    def __init__(self, app, adapters: Optional[Dict[str, str]] = None,
                 host_cache_adapters: int = 8, telemetry=None):
        if getattr(app.spec, "lora", None) is None:
            raise ConfigurationError(
                "LoraAdapterPool needs an application built with "
                "lora_config (TpuConfig.lora_config) — the stacked "
                "adapter arrays are the pool's backing store")
        if app.spec.lora.max_loras < 2:
            raise ConfigurationError(
                "max_loras must be >= 2 to pool adapters: slot 0 is the "
                "pinned zero adapter (base model)")
        if host_cache_adapters < 1:
            raise ConfigurationError("host_cache_adapters must be >= 1")
        self.app = app
        self._telemetry = telemetry
        self.max_host = host_cache_adapters
        # device slots 1..max_loras-1 (slot 0 = zero adapter, never written)
        self._free: List[int] = list(range(1, app.spec.lora.max_loras))
        self._slots: Dict[str, int] = {}       # resident name -> slot
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self._pins: Dict[str, int] = {}        # resident name -> refcount
        # registration: name -> ("path", dir) | ("arrays", {mod: (A, B)})
        self._sources: Dict[str, Any] = {}
        # host-RAM spill cache: name -> {mod: (A, B)} (bounded, LRU)
        self._host: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "swaps": 0, "swap_bytes": 0,
            "swap_errors": 0, "cold_loads": 0, "restores": 0,
            "spills": 0, "spill_errors": 0, "host_evictions": 0,
            "evictions": 0}
        self.stats["swap_s"] = 0.0
        for name, path in (adapters or {}).items():
            self.register(name, path)

    # -- registration ------------------------------------------------------
    def register(self, name: str, path: str) -> None:
        """Declare ``name`` as a PEFT checkpoint dir, loaded lazily (and
        GQA-transformed) on first acquisition."""
        self._sources[name] = ("path", path)

    def register_arrays(self, name: str, arrays: Dict[str, Any]) -> None:
        """Declare ``name`` from pre-transformed host arrays
        (``{module: (A (L,in,r), B (L,r,out))}`` — the
        ``lora_adapter_arrays`` layout)."""
        self._sources[name] = ("arrays", arrays)

    @property
    def names(self):
        return tuple(self._sources)

    @property
    def n_slots(self) -> int:
        """Usable device slots (slot 0 excluded)."""
        return self.app.spec.lora.max_loras - 1

    def resident(self, name: str) -> bool:
        """Read-only residency probe (no LRU touch) — the router's
        adapter-affinity scoring uses it per queued request."""
        return name in self._slots

    def slot_of(self, name: str) -> Optional[int]:
        return self._slots.get(name)

    # -- the acquire/release lifecycle -------------------------------------
    def acquire(self, name: str) -> int:
        """Pin ``name`` into a device slot and return the slot id. A hit
        touches recency; a miss claims a free slot (evicting the
        least-recently-used UNPINNED resident when none is free, its
        factors spilled host-side best-effort) and swaps the adapter in
        transactionally. Raises :class:`CapacityError` when every slot is
        pinned by live rows, :class:`ConfigurationError` for a name never
        registered."""
        if name in self._slots:
            self._lru.move_to_end(name)
            self._pins[name] += 1
            self.stats["hits"] += 1
            reg = self._registry()
            if reg is not None:
                tmetrics.lora_residency_hits_counter(reg).inc()
            return self._slots[name]
        if name not in self._sources:
            raise ConfigurationError(
                f"unknown adapter {name!r}; registered: "
                f"{sorted(self._sources)}")
        self.stats["misses"] += 1
        slot = self._claim_slot()
        arrays = self._load(name)
        self._swap_in(name, slot, arrays)
        self._slots[name] = slot
        self._lru[name] = None
        self._pins[name] = 1
        return slot

    def release(self, name: str) -> None:
        """Unpin one acquisition. The adapter stays resident (warm for
        the next acquire) until LRU pressure evicts it; releasing a
        non-resident name is a no-op (rollback paths release blindly)."""
        if name in self._pins and self._pins[name] > 0:
            self._pins[name] -= 1

    def pins(self, name: str) -> int:
        return self._pins.get(name, 0)

    # -- internals ---------------------------------------------------------
    def _claim_slot(self) -> int:
        if self._free:
            return self._free.pop()
        for victim in self._lru:               # oldest-touched first
            if self._pins.get(victim, 0) == 0:
                return self._evict(victim)
        raise CapacityError(
            f"all {self.n_slots} adapter slots are pinned by live rows; "
            "release sequences (or raise max_loras) before acquiring "
            "another adapter")

    def _evict(self, name: str) -> int:
        slot = self._slots.pop(name)
        del self._lru[name]
        self._pins.pop(name, None)
        self.stats["evictions"] += 1
        self._spill(name, slot)
        return slot

    def _spill(self, name: str, slot: int) -> None:
        """Best-effort device→host copy of the evicted slot's factors
        into the bounded host cache, so a re-acquire restores from RAM
        instead of the checkpoint. A failure (the ``adapter_spill``
        fault point models one) is swallowed and counted — the eviction
        that triggered the spill must always proceed."""
        try:
            if _FAULTS.active:
                _FAULTS.fire("adapter_spill")
            lw = self.app.params["layers"]
            arrays = {}
            for mod in self.app.spec.lora.target_modules:
                arrays[mod] = (np.asarray(lw[f"lora_A_{mod}"][:, slot]),
                               np.asarray(lw[f"lora_B_{mod}"][:, slot]))
            self._host[name] = arrays
            self._host.move_to_end(name)
            while len(self._host) > self.max_host:
                self._host.popitem(last=False)
                self.stats["host_evictions"] += 1
            self.stats["spills"] += 1
            rec = _get_recorder()
            if rec.enabled:
                rec.instant("lora.spill", cat="lora", adapter=name,
                            slot=slot, host_cached=len(self._host))
        except Exception:
            self.stats["spill_errors"] += 1

    def _load(self, name: str) -> Dict[str, Any]:
        cached = self._host.get(name)
        if cached is not None:
            self._host.move_to_end(name)
            self.stats["restores"] += 1
            return cached
        kind, src = self._sources[name]
        self.stats["cold_loads"] += 1
        if kind == "arrays":
            return src
        return self.app.lora_adapter_arrays(src)

    def _swap_in(self, name: str, slot: int,
                 arrays: Dict[str, Any]) -> None:
        """Transactional device write: snapshot every stacked leaf the
        swap touches, write, and restore the snapshot on ANY failure —
        a failed swap never corrupts a resident slot (the freed slot
        itself holds stale factors, but nothing maps to it)."""
        import time
        lw = self.app.params["layers"]
        snapshot = {}
        for mod in arrays:
            snapshot[f"lora_A_{mod}"] = lw[f"lora_A_{mod}"]
            snapshot[f"lora_B_{mod}"] = lw[f"lora_B_{mod}"]
        t0 = time.perf_counter()
        try:
            if _FAULTS.active:
                _FAULTS.fire("adapter_swap")
            self.app.write_lora_slot(slot, arrays)
        except Exception as e:
            for key, leaf in snapshot.items():
                lw[key] = leaf
            self._free.append(slot)
            self.stats["swap_errors"] += 1
            from .adapter import _trace_error
            raise _trace_error(StepFailure(
                f"adapter swap of {name!r} into slot {slot} failed; the "
                "stacked factors were restored from the pre-swap "
                "snapshot (no resident slot corrupted)",
                phase="adapter_swap", seq_ids=(), retry_safe=True)) from e
        dt = time.perf_counter() - t0
        nbytes = sum(np.asarray(a).nbytes + np.asarray(b).nbytes
                     for a, b in arrays.values())
        self.stats["swaps"] += 1
        self.stats["swap_bytes"] += nbytes
        self.stats["swap_s"] += dt
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("lora.swap", cat="lora", adapter=name, slot=slot,
                        bytes=nbytes, s=round(dt, 6))
        reg = self._registry()
        if reg is not None:
            tmetrics.lora_swaps_counter(reg).inc(adapter=name)
            tmetrics.lora_swap_bytes_counter(reg).inc(nbytes)

    def _registry(self):
        if self._telemetry is not None:
            return self._telemetry if self._telemetry.enabled else None
        reg = get_registry()
        return reg if reg.enabled else None

    # -- introspection -----------------------------------------------------
    def debug_state(self) -> Dict[str, Any]:
        return {
            "resident": {n: {"slot": s, "pins": self._pins.get(n, 0)}
                         for n, s in self._slots.items()},
            "free_slots": list(self._free),
            "host_cached": list(self._host),
            "stats": dict(self.stats),
        }
