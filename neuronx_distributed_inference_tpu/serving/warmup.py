"""Cold-start truth: the fleet precompile plane and the HBM ledger.

:func:`precompile` promotes the observatory's AOT walk into a
server-start warmup that drives the application's OWN jit entry points
(``_run_ragged`` / ``_run_paged_loop`` / ``_run_spec_verify`` /
``_run_paged``; prefill/decode for the contiguous app) across the
UNIFIED ragged row ladder (``autobucketing.ragged_row_buckets``) — not
fresh wrappers, so the serving-path jit caches are actually warm when
the first request lands. Every first-seen graph is timed into
``nxdi_compile_seconds{kind,bucket}`` and classified through jax's
compilation-cache monitoring events: a real XLA build increments
``nxdi_jit_compiles_total``, a persistent-cache load (N replicas share
``jax_compilation_cache_dir`` — models/application.py sets it, the test
suite's conftest has the pattern) counts as ``nxdi_jit_cache_hits_total``
instead. That split is what makes the ROADMAP item-5 pin ("a second
replica compiles nothing") fall out of the counters.

After the walk the application enters **declared steady state**
(:meth:`~..models.application.CausalLMApplication.declare_steady_state`):
any later first-seen signature is a tracked incident — the
``nxdi_steady_state_recompiles_total`` counter, a ``compile.unexpected``
flight-recorder event, attribution onto the triggering request's trace
lane, and exposure in ``/v1/debug/state["warmup"]``.

:func:`memory_ledger` is the live per-replica HBM account: exact model
parameter bytes, the paged KV pool split by block state (used / free /
unwritten, reconciling bit-for-bit with
``PagedEngineAdapter.debug_state()``'s block accounting), host-RAM
spill-tier residency, a fragmentation ratio, and the admission-headroom
estimate the scheduler logs when it rejects. Served as
``GET /v1/debug/memory`` (serving/engine/frontend.py) and aggregated
with per-replica labels through the fleet router.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..modules import autobucketing
from ..telemetry import metrics as tmetrics
from ..telemetry.registry import NULL_REGISTRY
from ..telemetry.trace import get_recorder as _get_recorder

__all__ = ["precompile", "memory_ledger", "WARMUP_SCHEMA", "LEDGER_SCHEMA"]

WARMUP_SCHEMA = "nxdi-warmup-report-v1"
LEDGER_SCHEMA = "nxdi-memory-ledger-v1"


# ---------------------------------------------------------------------------
# compilation-cache monitor: the truth behind compile-vs-load
# ---------------------------------------------------------------------------
class _CompileCacheMonitor:
    """Process-wide listener over jax's compilation-cache monitoring
    events. ``/jax/compilation_cache/cache_hits`` fires when an
    executable was DESERIALIZED from the persistent cache (no XLA
    build); ``cache_misses`` fires when the compiler actually ran. The
    split lets :func:`precompile` count a second replica's walk as cache
    hits rather than misreporting every persistent-cache load as a
    fresh compile."""

    _HIT = "/jax/compilation_cache/cache_hits"
    _MISS = "/jax/compilation_cache/cache_misses"

    def __init__(self):
        self.hits = 0
        self.misses = 0
        self._installed = False
        self._lock = threading.Lock()

    def install(self) -> bool:
        with self._lock:
            if self._installed:
                return True
            try:
                from jax import monitoring
                monitoring.register_event_listener(self._on_event)
            except Exception:
                return False
            self._installed = True
            return True

    def _on_event(self, event: str, *args, **kwargs) -> None:
        if event == self._HIT:
            self.hits += 1
        elif event == self._MISS:
            self.misses += 1

    def snapshot(self):
        return (self.hits, self.misses)


_MONITOR = _CompileCacheMonitor()


# ---------------------------------------------------------------------------
# the precompile plane
# ---------------------------------------------------------------------------
def _paged_plan(app, widths, bt_widths, chunk_tokens, spec_widths):
    """The warm plan of a paged application: the unified ragged row
    ladder across every block-table width bucket, the fused decode loop,
    and the speculative verify widths — the exact shape set the serving
    adapters dispatch (serving/ragged/path.py, serving/adapter.py)."""
    cfg = app.tpu_config
    b = cfg.batch_size
    if widths is None:
        widths = autobucketing.ragged_row_buckets(app.ctx_buckets,
                                                  chunk_tokens)
    if bt_widths is None:
        bt_widths = list(app._bt_buckets)
    chunk = max(cfg.decode_chunk_tokens, 1)
    # a LoRA-built model traces a SECOND graph per shape once any row
    # carries an adapter slot (the adapter_ids kwarg changes the jit
    # signature) — warm both so the first multi-LoRA batch after
    # declare_steady_state() is a cache hit, not a sentinel trip.
    # slot 0 is the pinned zero adapter, so the dummy call writes nothing.
    lora_kw = ({"adapter_ids": np.zeros((b,), np.int32)}
               if app.spec.lora is not None else None)
    plan: List[tuple] = []
    for tw in bt_widths:
        bt = np.zeros((b, tw), np.int32)        # null block only: no writes

        def ragged_thunk(w, bt=bt, **kw):
            # dummy no-write ragged dispatch: every slot negative, widths
            # ones, nothing emitted (mirrors PagedCausalLMApplication.
            # warmup's dummy-call discipline)
            app._run_ragged(np.zeros((b, w), np.int32),
                            np.zeros((b, w), np.int32),
                            np.full((b, w), -1, np.int32), bt,
                            np.ones((b,), np.int32),
                            np.zeros((b,), np.int32), **kw)

        for w in sorted(widths):
            plan.append(("ragged", w, lambda w=w, bt=bt: ragged_thunk(w, bt)))
            if lora_kw is not None:
                plan.append(("ragged_lora", w,
                             lambda w=w, bt=bt: ragged_thunk(w, bt, **lora_kw)))
        if chunk > 1:
            plan.append(("paged_loop", chunk, lambda bt=bt: app._run_paged_loop(
                np.zeros((b,), np.int32), np.zeros((b,), np.int32), bt,
                chunk)))
            if lora_kw is not None:
                plan.append(("paged_loop_lora", chunk,
                             lambda bt=bt: app._run_paged_loop(
                                 np.zeros((b,), np.int32),
                                 np.zeros((b,), np.int32), bt, chunk,
                                 **lora_kw)))
        for w in sorted(spec_widths or ()):
            plan.append(("spec_verify", w, lambda w=w, bt=bt: app._run_spec_verify(
                np.zeros((b, w), np.int32), np.zeros((b, w), np.int32),
                np.full((b, w), -1, np.int32), bt,
                np.ones((b,), np.int32))))
            if lora_kw is not None:
                plan.append(("spec_verify_lora", w,
                             lambda w=w, bt=bt: app._run_spec_verify(
                                 np.zeros((b, w), np.int32),
                                 np.zeros((b, w), np.int32),
                                 np.full((b, w), -1, np.int32), bt,
                                 np.ones((b,), np.int32), **lora_kw)))
    return plan


def _cb_plan(app):
    """Contiguous-app fallback plan: every prefill ctx bucket plus the
    decode step / fused decode loop per batch bucket (the same grid
    ``warmup()`` runs, instrumented per graph)."""
    cfg = app.tpu_config
    b = cfg.ctx_batch_size
    chunk = max(cfg.decode_chunk_tokens, 1)
    plan: List[tuple] = []
    for s in app.ctx_buckets:
        plan.append(("prefill", s, lambda s=s: app._run_prefill(
            np.zeros((b, s), np.int32), np.ones((b,), np.int32))))
    warm_batches = sorted(set(app.batch_buckets)
                          | {cfg.tkg_batch_size or cfg.batch_size})
    for bb in warm_batches:
        if chunk > 1:
            plan.append(("decode_loop", chunk, lambda bb=bb: app._run_decode_loop(
                np.zeros((bb,), np.int32), np.ones((bb,), np.int32),
                chunk)))
        plan.append(("decode", 1, lambda bb=bb: app._run_decode(
            np.zeros((bb, 1), np.int32), np.ones((bb, 1), np.int32))))
    return plan


def precompile(app, *, registry=None, widths: Optional[Sequence[int]] = None,
               bt_widths: Optional[Sequence[int]] = None,
               chunk_tokens: Optional[int] = None,
               spec_widths: Sequence[int] = (),
               declare_steady: bool = True) -> Dict[str, Any]:
    """Server-start precompile: walk the serving graph ladder through the
    application's own jit entry points, time every first-seen graph into
    ``nxdi_compile_seconds{kind,bucket}``, and classify it (XLA build vs
    persistent-cache load vs warm in-memory hit) into the existing
    ``nxdi_jit_compiles_total`` / ``nxdi_jit_cache_hits_total`` counters.

    ``registry``: the replica's metrics registry (defaults to the app's
    resolved telemetry registry). ``widths`` / ``bt_widths`` override the
    default ladders (tests shrink them); ``chunk_tokens`` feeds the
    ragged-row-bucket cap exactly like the adapter's
    ``prefill_chunk_tokens``. ``spec_widths``: speculative verify widths
    (k+1 per attached proposer) to warm. With ``declare_steady`` the app
    enters declared steady state afterwards — any later compile is a
    tracked incident (see the module docstring).

    Returns the ``nxdi-warmup-report-v1`` dict (also stored on the app
    for ``/v1/debug/state["warmup"]``)."""
    if app.params is None:
        app.init_random_weights()
    if app.cache is None:
        app.init_cache()
    reg = registry if registry is not None else app.telemetry
    monitored = _MONITOR.install()
    if hasattr(app, "_run_ragged"):
        plan = _paged_plan(app, widths, bt_widths, chunk_tokens,
                           spec_widths)
    else:
        plan = _cb_plan(app)
    # the entry points' own _note_jit would double-count into the app's
    # registry while this walk does its classified accounting — silence
    # it for the walk (the _jit_seen signature tracking still runs)
    prev_override = app._telemetry_override
    app._telemetry_override = NULL_REGISTRY
    graphs: List[Dict[str, Any]] = []
    n_compiles = n_loads = n_warm = 0
    t_total0 = time.perf_counter()
    try:
        for kind, bucket, thunk in plan:
            n_seen = len(app._jit_seen)
            hits0, misses0 = _MONITOR.snapshot()
            t0 = time.perf_counter()
            thunk()
            dt = time.perf_counter() - t0
            first_seen = len(app._jit_seen) > n_seen
            hits1, misses1 = _MONITOR.snapshot()
            if not first_seen:
                outcome = "warm"
                n_warm += 1
            elif (monitored and hits1 > hits0 and misses1 == misses0):
                outcome = "cache_load"
                n_loads += 1
            else:
                outcome = "compile"
                n_compiles += 1
            if reg.enabled:
                if outcome == "compile":
                    tmetrics.jit_compiles_counter(reg).inc(
                        kind=kind, bucket=str(bucket))
                else:
                    tmetrics.jit_cache_hits_counter(reg).inc(kind=kind)
                if first_seen:
                    tmetrics.compile_seconds_gauge(reg).set(
                        dt, kind=kind, bucket=str(bucket))
            graphs.append({"kind": kind, "bucket": bucket,
                           "seconds": dt, "outcome": outcome})
    finally:
        app._telemetry_override = prev_override
    total = time.perf_counter() - t_total0
    report = {
        "schema": WARMUP_SCHEMA,
        "n_graphs": len(graphs),
        "n_compiles": n_compiles,
        "n_cache_loads": n_loads,
        "n_warm_hits": n_warm,
        "total_seconds": total,
        "cache_monitored": monitored,
        "graphs": graphs,
    }
    app._warmup_report = report
    if declare_steady:
        app.declare_steady_state()
    rec = _get_recorder()
    if rec.enabled:
        rec.instant("compile", cat="app", kind="precompile",
                    bucket=str(len(graphs)),
                    sig=f"compiles={n_compiles} loads={n_loads} "
                        f"warm={n_warm} total_s={total:.3f}")
    return report


# ---------------------------------------------------------------------------
# the HBM ledger
# ---------------------------------------------------------------------------
def _tree_bytes(tree) -> int:
    import jax
    return int(sum(getattr(leaf, "nbytes", 0)
                   for leaf in jax.tree_util.tree_leaves(tree)))


def memory_ledger(adapter, *, registry=None,
                  graph_report: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """One live per-replica HBM account over a
    :class:`~.adapter.PagedEngineAdapter`: exact model parameter bytes,
    the KV pool split by block state (reconciling with
    ``adapter.debug_state()["blocks"]`` exactly), spill-tier residency,
    fragmentation, and the admission-headroom estimate. Sets the
    ``nxdi_hbm_*`` gauges when ``registry`` is live; attaches per-graph
    ``memory_analysis()`` peaks when an observatory ``graph_report``
    (nxdi-graph-report-v1) is supplied."""
    app = adapter.app
    mgr = getattr(app, "kv_mgr", None)
    if mgr is None:
        # contiguous-layout adapter: no block accounting to reconcile —
        # report the static split only
        return {"schema": LEDGER_SCHEMA,
                "model_bytes": _tree_bytes(app.params),
                "kv": {"pool_bytes": _tree_bytes(app.cache)},
                "spill": None,
                "headroom": admission_headroom(adapter)}
    spec = mgr.spec
    pool_bytes = _tree_bytes(app.cache)
    block_bytes = pool_bytes // spec.num_blocks
    usable = spec.num_blocks - 1               # block 0 is the null block
    free = int(mgr.allocator.num_free)
    in_use = usable - free
    unwritten = len(adapter._unwritten)
    live_tokens = sum(int(st.position) for st in adapter.seqs.values())
    live_tokens += sum(int(cst.done)
                       for cst in getattr(adapter, "_chunks", {}).values())
    alloc_slots = in_use * spec.block_size
    frag = (1.0 - live_tokens / alloc_slots) if alloc_slots else 0.0
    frag = min(max(frag, 0.0), 1.0)
    tier = getattr(adapter, "_kv_tier", None)
    spilled_bytes = int(tier.nbytes) if tier is not None else 0
    ledger: Dict[str, Any] = {
        "schema": LEDGER_SCHEMA,
        "model_bytes": _tree_bytes(app.params),
        "kv": {
            "pool_bytes": pool_bytes,
            "block_bytes": block_bytes,
            "block_size": int(spec.block_size),
            "blocks": {"usable": usable, "free": free, "in_use": in_use,
                       "unwritten": unwritten},
            "bytes": {"used": in_use * block_bytes,
                      "free": free * block_bytes,
                      "unwritten": unwritten * block_bytes,
                      "spilled": spilled_bytes},
            "live_tokens": live_tokens,
            "fragmentation_ratio": frag,
        },
        "spill": (None if tier is None else
                  {"blocks": len(tier), "bytes": spilled_bytes,
                   "stats": dict(tier.stats)}),
        "headroom": admission_headroom(adapter),
    }
    if graph_report is not None:
        # static side from the compiled-graph observatory: per-graph
        # memory_analysis() peaks (weights + temps while that graph runs)
        ledger["graphs"] = {
            g["label"]: g.get("memory", {}).get("peak_bytes")
            for g in graph_report.get("graphs", [])}
    reg = registry
    if reg is not None and reg.enabled:
        tmetrics.hbm_model_bytes_gauge(reg).set(ledger["model_bytes"])
        kv_gauge = tmetrics.hbm_kv_bytes_gauge(reg)
        for state, nbytes in ledger["kv"]["bytes"].items():
            kv_gauge.set(nbytes, state=state)
        tmetrics.kv_fragmentation_ratio_gauge(reg).set(frag)
    return ledger


def admission_headroom(adapter) -> Dict[str, int]:
    """The scheduler's capacity-reject log line: free batch slots, free
    KV blocks, and the token headroom they represent."""
    out = {"free_slots": int(getattr(adapter, "free_capacity", 0))}
    mgr = getattr(getattr(adapter, "app", None), "kv_mgr", None)
    if mgr is not None:
        free = int(mgr.allocator.num_free)
        out["free_blocks"] = free
        out["headroom_tokens"] = free * int(mgr.spec.block_size)
    return out
