"""Per-request token streams — the delivery half of the serving engine.

A :class:`TokenStream` is created at submit time and handed to the caller
before any device work happens. The scheduler is the only producer
(:meth:`TokenStream.put` / :meth:`TokenStream.finish`); consumers read
either synchronously (:meth:`TokenStream.drain`, the closed-loop bench and
tests) or asynchronously (``async for tok in stream``, the SSE front end).
Producer and async consumer are expected to share one asyncio event loop
(the front end runs the scheduler as a task on its own loop), so plain
``asyncio.Event`` signalling suffices — no cross-thread machinery.

Backpressure is cooperative: the stream only REPORTS its unread depth
(:attr:`TokenStream.unread`); the scheduler stops stepping a sequence whose
consumer lags past ``max_unread_tokens`` and resumes once the consumer
catches up. Tokens are never dropped.

Cancellation is edge-triggered from either side: the engine's ``cancel()``
(or the front end noticing a dead client socket) finishes the stream with
reason ``"cancelled"`` and a typed :class:`~...resilience.errors.Cancelled`
error, after the engine has released the sequence and reclaimed its KV
blocks. Tokens delivered before the cancel stay valid.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, List, Optional

from ...resilience.errors import Cancelled

__all__ = ["TokenStream"]

#: Stream finish reasons (``TokenStream.finish_reason``):
#:   ``length``    — max_new_tokens generated
#:   ``stop``      — a stop token was generated (it IS delivered)
#:   ``deadline``  — per-request wall-clock budget blew (in queue or running)
#:   ``cancelled`` — explicit cancel or client gone
#:   ``capacity``  — the compiled seq_len cannot hold another token
#:   ``error``     — unrecoverable engine/device failure (see ``error``)
FINISH_REASONS = ("length", "stop", "deadline", "cancelled", "capacity",
                  "error")


class TokenStream:
    """One request's ordered token stream plus terminal status."""

    def __init__(self, request_id: str, tenant: str = ""):
        self.request_id = request_id
        self.tenant = tenant
        self._tokens: List[int] = []
        self._cursor = 0              # consumer position (drain/aiter)
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self._event: Optional[asyncio.Event] = None
        self._cancel_cb: Optional[Callable[[], Any]] = None

    # -- producer side (scheduler only) ------------------------------------
    def put(self, token: int) -> None:
        if self.finish_reason is not None:
            return                    # late token after cancel/expiry: drop
        self._tokens.append(int(token))
        self._wake()

    def finish(self, reason: str,
               error: Optional[BaseException] = None) -> None:
        """Terminal transition; idempotent (first reason wins)."""
        if self.finish_reason is None:
            self.finish_reason = reason
            self.error = error
            self._wake()

    # -- consumer side -----------------------------------------------------
    @property
    def tokens(self) -> List[int]:
        """Every token delivered so far (does not move the cursor)."""
        return list(self._tokens)

    @property
    def n_tokens(self) -> int:
        """Count of delivered tokens — O(1); the scheduler's per-token
        budget checks use this instead of copying ``tokens``."""
        return len(self._tokens)

    def tokens_from(self, start: int) -> List[int]:
        """Tokens from index ``start`` on, without copying the whole
        stream (the fleet router's per-pass pump reads only the new
        tail; does not move the consumer cursor)."""
        return self._tokens[start:]

    @property
    def finished(self) -> bool:
        return self.finish_reason is not None

    @property
    def unread(self) -> int:
        """Delivered tokens the consumer has not drained/iterated yet —
        the scheduler's backpressure signal."""
        return len(self._tokens) - self._cursor

    def drain(self) -> List[int]:
        """Synchronously take every not-yet-consumed token."""
        out = self._tokens[self._cursor:]
        self._cursor = len(self._tokens)
        return out

    def cancel(self) -> None:
        """Ask the engine to cancel this request (release the sequence,
        reclaim blocks). No-op once finished."""
        if self.finish_reason is None and self._cancel_cb is not None:
            self._cancel_cb()

    def cancelled_error(self) -> Cancelled:
        return Cancelled(f"request {self.request_id} was cancelled")

    # -- async iteration ---------------------------------------------------
    def __aiter__(self) -> "TokenStream":
        return self

    async def __anext__(self) -> int:
        while True:
            if self._cursor < len(self._tokens):
                tok = self._tokens[self._cursor]
                self._cursor += 1
                return tok
            if self.finish_reason is not None:
                raise StopAsyncIteration
            await self._wait()

    async def iter_from(self, start: int = 0):
        """Async-iterate tokens from index ``start`` with a PRIVATE
        cursor, then follow the live stream — safe for multiple
        concurrent consumers (replay attaches), unlike ``__anext__``
        whose shared cursor feeds each token to exactly one reader. The
        shared cursor is advanced as a high-water mark so backpressure
        still sees the farthest-ahead consumer."""
        i = start
        while True:
            if i < len(self._tokens):
                tok = self._tokens[i]
                i += 1
                self._cursor = max(self._cursor, i)
                yield tok
                continue
            if self.finish_reason is not None:
                return
            await self._wait()

    async def wait_finished(self) -> str:
        """Block until the stream is terminal; returns the finish reason
        (tokens may still be undrained)."""
        while self.finish_reason is None:
            await self._wait()
        return self.finish_reason

    # -- signalling --------------------------------------------------------
    def _wake(self) -> None:
        if self._event is not None:
            self._event.set()

    async def _wait(self) -> None:
        if self._event is None:
            self._event = asyncio.Event()
        self._event.clear()
        # re-check after clear: a put() between the cursor check and here
        # already set the (fresh) event or appended a token
        if self._cursor < len(self._tokens) or self.finish_reason is not None:
            return
        await self._event.wait()
