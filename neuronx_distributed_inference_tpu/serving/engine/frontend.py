"""Asyncio HTTP/SSE front door for the serving engine — stdlib only.

A deliberately small HTTP/1.1 server (``asyncio.start_server``; no
framework dependencies, mirroring the repo-wide no-deps rule) exposing the
engine's submit / stream / cancel / metrics surface:

  ``POST /v1/generate``
      Body: ``{"prompt": [ints], "max_new_tokens": n, "tenant": "...",
      "priority": 0, "deadline_s": null, "stop_tokens": [],
      "stream": true}``. With ``"stream": true`` (default) the response is
      ``text/event-stream``: one ``data: {"token": t, "index": i}`` event
      per token, then a terminal
      ``data: {"done": true, "reason": "...", "request_id": "..."}``
      event. With ``"stream": false`` the connection blocks and returns
      one JSON body with the full token list.
  ``POST /v1/submit``
      Same body (sans "stream"); returns ``{"request_id": ...}``
      immediately. Attach later via ``GET /v1/stream/<id>``.
  ``GET /v1/stream/<id>``
      SSE attach to a submitted request (replays from token 0, then
      follows live).
  ``POST /v1/cancel/<id>``
      Returns ``{"cancelled": bool}``. Cancelling a queued request costs
      no device work; a running one is released and its blocks reclaimed.
  ``GET /v1/metrics`` (alias ``GET /metrics``)
      Prometheus text exposition: the process-global registry, or —
      with ``fleet=`` and a router aggregator attached — the
      fleet-merged exposition with a ``replica`` label per series. An
      engine SLO tracker exports its gauges at scrape time.
  ``GET /healthz``
      ``{"ok": true, "queue_depth": n, "running": m}``.
  ``GET /v1/debug/state``
      Post-mortem JSON (schema ``nxdi-debug-state-v1``): engine/adapter
      snapshot (per-tenant queue depths, running/pending ids, block
      occupancy, pipeline depth) plus the flight-recorder tail with its
      drop count. Works with the recorder disabled (empty trace).
  ``GET /v1/debug/trace``
      The flight recorder as Chrome trace-event JSON — save the body and
      open it in ``chrome://tracing`` / Perfetto.
  ``GET /v1/debug/trace/<id>``
      ONE request's trace (``<id>`` = request id or trace id): the
      events carrying its ``trace_id`` — queue, admission, dispatch
      rows, requeues, emission — as Chrome trace JSON; 404 with a typed
      JSON body (``"type": "trace_not_found"``) when nothing matches
      (unknown id / recorder disabled).
  ``GET /v1/debug/memory``
      The live HBM ledger (schema ``nxdi-memory-ledger-v1``,
      serving/warmup.py): model parameter bytes, the KV pool split by
      block state (reconciling exactly with the adapter's block
      accounting), spill-tier residency, fragmentation ratio and the
      admission-headroom estimate — per-replica under ``"fleet"`` when
      a router is attached.

Client-gone behaviour: when an SSE write fails (peer reset / closed), the
front end cancels the request through the engine — blocks are reclaimed
and the stream finishes "cancelled" — so a dead client can never pin KV.

Errors map onto the typed taxonomy: QueueOverflow -> 429,
AdmissionError -> 400, unknown ids -> 404, closed engine -> 503.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional, Tuple

from ...resilience.errors import (AdmissionError, ConfigurationError,
                                  QueueOverflow, ServingError)
from ...telemetry import get_registry
from ...telemetry.trace import get_recorder
from .scheduler import ServingEngine
from .streams import TokenStream

__all__ = ["ServingFrontend"]

_MAX_BODY = 1 << 20                      # 1 MiB request-body cap


class _HttpError(Exception):
    """Typed HTTP failure: every error response body is
    ``{"error": <message>, "type": <stable machine tag>, "status": n}``
    so clients can dispatch on ``type`` instead of parsing prose.
    ``type_`` defaults to the status's generic tag (``not_found``,
    ``bad_request``, ...); raisers pass a more specific one when they
    have it (e.g. ``trace_not_found``)."""

    def __init__(self, status: int, message: str,
                 type_: Optional[str] = None):
        super().__init__(message)
        self.status = status
        self.type = type_ or _STATUS_TYPE.get(status, "error")


_STATUS_TEXT = {200: "OK", 400: "Bad Request", 404: "Not Found",
                405: "Method Not Allowed", 413: "Payload Too Large",
                429: "Too Many Requests", 500: "Internal Server Error",
                503: "Service Unavailable"}

_STATUS_TYPE = {400: "bad_request", 404: "not_found",
                405: "method_not_allowed", 413: "payload_too_large",
                429: "queue_overflow", 500: "internal_error",
                503: "unavailable"}


class ServingFrontend:
    """Owns the listener socket, the engine's ``run_forever`` task, and
    the per-connection request handlers.

    ``max_retained_streams`` bounds the ``/v1/submit`` stream registry
    (oldest FINISHED streams beyond it are dropped; default 256 — the
    pre-knob hardcoded bound, pinned by tests). ``fleet`` optionally
    attaches an :class:`~..fleet.router.EngineRouter` whose
    ``debug_state()`` is served as the ``fleet`` section of
    ``GET /v1/debug/state``."""

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0, max_retained_streams: int = 256,
                 fleet=None):
        if max_retained_streams < 1:
            raise ConfigurationError("max_retained_streams must be >= 1")
        self.engine = engine
        self.host = host
        self.port = port
        self.max_retained_streams = max_retained_streams
        self.fleet = fleet
        self._server: Optional[asyncio.base_events.Server] = None
        self._engine_task: Optional[asyncio.Task] = None
        self._streams: Dict[str, TokenStream] = {}   # submitted via HTTP

    async def start(self) -> Tuple[str, int]:
        """Bind, start serving connections and the engine loop; returns
        the bound (host, port) — port 0 resolves to an ephemeral one."""
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._engine_task = asyncio.ensure_future(self.engine.run_forever())
        return self.host, self.port

    async def stop(self) -> None:
        """Close the listener, stop the engine loop (cancelling all
        outstanding requests), and wait for both to wind down."""
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        self.engine.close()
        if self._engine_task is not None:
            await self._engine_task
        self._streams.clear()

    # -- connection handling ----------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                method, path, body = await self._read_request(reader)
                await self._route(method, path, body, writer)
            except _HttpError as e:
                await self._send_json(writer, e.status,
                                      {"error": str(e), "type": e.type,
                                       "status": e.status})
        except (ConnectionError, asyncio.IncompleteReadError):
            pass                      # client went away mid-exchange
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader: asyncio.StreamReader):
        line = await reader.readline()
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            raise _HttpError(400, "malformed request line")
        method, path = parts[0].upper(), parts[1]
        length = 0
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            name, _, value = hline.decode("latin-1").partition(":")
            if name.strip().lower() == "content-length":
                try:
                    length = int(value.strip())
                except ValueError:
                    raise _HttpError(400, "bad Content-Length")
                if length < 0:
                    raise _HttpError(400, "bad Content-Length")
        if length > _MAX_BODY:
            raise _HttpError(413, "request body too large")
        body = await reader.readexactly(length) if length else b""
        return method, path, body

    async def _route(self, method: str, path: str, body: bytes,
                     writer: asyncio.StreamWriter) -> None:
        if path == "/healthz" and method == "GET":
            await self._send_json(writer, 200, {
                "ok": not self.engine._closed,
                "queue_depth": self.engine.queue.depth,
                "running": len(self.engine._active)})
        elif path in ("/metrics", "/v1/metrics") and method == "GET":
            # /v1/metrics is the served exposition surface (the bare
            # /metrics alias predates it and stays for compatibility):
            # Prometheus text of the process registry — or, with a fleet
            # aggregator attached, the N replica registries merged under
            # a `replica` label (serving/fleet/aggregator.py)
            await self._send_raw(writer, 200, self._metrics_text().encode(),
                                 "text/plain; version=0.0.4")
        elif path == "/v1/debug/state" and method == "GET":
            # live post-mortem: engine/adapter snapshot + flight-recorder
            # tail (events empty while the recorder is disabled)
            await self._send_json(writer, 200, self._debug_payload())
        elif path == "/v1/debug/memory" and method == "GET":
            # live HBM ledger (serving/warmup.py): model bytes, KV pool
            # by block state, spill residency, fragmentation, headroom —
            # plus the per-replica fleet account with a router attached
            await self._send_json(writer, 200, self._memory_payload())
        elif path.startswith("/v1/debug/trace/") and method == "GET":
            # per-request trace: <id> is a request id (resolved through
            # the engine/router trace maps) or a raw trace id; returns
            # Chrome trace-event JSON filtered to that one request
            await self._send_json(
                writer, 200,
                self._trace_payload(path[len("/v1/debug/trace/"):]))
        elif path == "/v1/debug/trace" and method == "GET":
            # Chrome trace-event JSON — save the body and load it in
            # chrome://tracing or Perfetto
            await self._send_json(writer, 200, get_recorder().to_chrome())
        elif path == "/v1/generate" and method == "POST":
            spec = self._parse_spec(body)
            stream = self._submit(spec)
            if spec.get("stream", True):
                await self._sse(writer, stream)
            else:
                # consume while waiting (not wait_finished + .tokens):
                # under max_unread_tokens backpressure an unconsumed
                # stream would stall its own decode forever
                toks = [tok async for tok in stream]
                await self._send_json(writer, 200, {
                    "request_id": stream.request_id,
                    "tokens": toks, "reason": stream.finish_reason})
        elif path == "/v1/submit" and method == "POST":
            stream = self._submit(self._parse_spec(body))
            self._prune_streams()
            self._streams[stream.request_id] = stream
            await self._send_json(writer, 200,
                                  {"request_id": stream.request_id})
        elif path.startswith("/v1/stream/") and method == "GET":
            stream = self._streams.get(path[len("/v1/stream/"):])
            if stream is None:
                raise _HttpError(404, "unknown request id")
            await self._sse(writer, stream, replay=True)
        elif path.startswith("/v1/cancel/") and method == "POST":
            rid = path[len("/v1/cancel/"):]
            await self._send_json(writer, 200,
                                  {"cancelled": self.engine.cancel(rid)})
        else:
            raise _HttpError(404 if method in ("GET", "POST") else 405,
                             f"no route for {method} {path}")

    # -- engine glue -------------------------------------------------------
    def _metrics_text(self) -> str:
        """The ``GET /v1/metrics`` body. With a fleet router whose
        ``aggregator`` is set (per-replica registries), the fleet-wide
        merged exposition — each replica engine's SLO tracker exported
        into ITS registry first; otherwise the process-global registry
        with this engine's SLO gauges exported into it. Pull-model
        either way: burn rates are computed when someone looks."""
        agg = getattr(self.fleet, "aggregator", None) \
            if self.fleet is not None else None
        if agg is not None:
            export = getattr(self.fleet, "export_slo", None)
            if export is not None:
                export()
            if self.engine.slo is not None:
                # this frontend's engine may itself be a replica: export
                # its scrape-time SLO gauges into ITS registry (global
                # otherwise, landing under the pseudo-replica below)
                reg_of = getattr(self.fleet, "registry_of",
                                 lambda _e: None)
                self.engine.slo.export(reg_of(self.engine)
                                       or get_registry())
            # the router's OWN series (nxdi_fleet_*, handoffs) live in
            # the process-global registry — merge it in as one more
            # source so enabling fleet exposition never hides them. A
            # series carrying its own `replica` label (the fleet
            # counters) keeps it; everything else from the global
            # registry — including direct HTTP traffic on this
            # frontend's engine, which bypasses the router's registry
            # scoping — is labeled with the pseudo-replica below.
            from ..fleet.aggregator import FleetMetricsAggregator
            sources = dict(agg.sources)
            label = "router"
            while label in sources:
                label = "_" + label
            sources[label] = get_registry()
            return FleetMetricsAggregator(sources).render_prometheus()
        if self.engine.slo is not None:
            self.engine.slo.export(get_registry())
        return get_registry().render_prometheus()

    def _trace_payload(self, key: str) -> Dict[str, Any]:
        """Chrome trace JSON of ONE request's events: ``key`` is a
        request id known to the engine (or the attached fleet router) or
        a literal trace id. 404 when no events match — an unknown id and
        a disabled recorder look the same on purpose (neither has a
        story to tell)."""
        from ...telemetry.request_trace import trace_events
        tid = self.engine.trace_id_of(key)
        if tid is None and self.fleet is not None:
            tid = getattr(self.fleet, "trace_id_of", lambda _k: None)(key)
        tid = tid or key
        rec = get_recorder()
        events = trace_events(rec.events(), tid)
        if not events:
            raise _HttpError(404, f"no trace events for {key!r} (unknown "
                                  "id, aged out, or recorder disabled)",
                             type_="trace_not_found")
        payload = rec.to_chrome(events)
        payload["otherData"]["trace_id"] = tid
        return payload

    def _memory_payload(self) -> Dict[str, Any]:
        """The ``GET /v1/debug/memory`` body: this engine's HBM ledger
        (reconciling exactly with the adapter's block accounting), with
        the gauges refreshed into the scrape registry at read time; a
        fleet router contributes its per-replica ledgers under
        ``"fleet"``."""
        from ..warmup import memory_ledger
        reg_of = getattr(self.fleet, "registry_of", lambda _e: None) \
            if self.fleet is not None else (lambda _e: None)
        payload = memory_ledger(self.engine.adapter,
                                registry=reg_of(self.engine)
                                or get_registry())
        if self.fleet is not None and hasattr(self.fleet, "memory_report"):
            payload["fleet"] = self.fleet.memory_report()
        return payload

    def _debug_payload(self) -> Dict[str, Any]:
        """The ``GET /v1/debug/state`` body: the engine post-mortem dump
        plus — with a fleet router attached — the router's snapshot
        (per-replica health/load, routing stats, in-flight bindings)."""
        payload = self.engine.dump_debug_state()
        if self.fleet is not None:
            payload["fleet"] = self.fleet.debug_state()
        return payload

    def _prune_streams(self) -> None:
        """Bound the /v1/submit registry: drop the oldest FINISHED streams
        beyond the cap (dict preserves insertion order), so a long-lived
        server does not retain one token list per request forever.
        Unfinished streams are never dropped — their requests are live."""
        excess = len(self._streams) - self.max_retained_streams + 1
        if excess <= 0:
            return
        for rid in [r for r, s in self._streams.items()
                    if s.finished][:excess]:
            del self._streams[rid]

    def _parse_spec(self, body: bytes) -> Dict[str, Any]:
        try:
            spec = json.loads(body.decode() or "{}")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise _HttpError(400, f"bad JSON body: {e}")
        if not isinstance(spec, dict):
            raise _HttpError(400, "body must be a JSON object")
        return spec

    def _submit(self, spec: Dict[str, Any]) -> TokenStream:
        try:
            return self.engine.submit(
                spec.get("prompt", ()),
                int(spec.get("max_new_tokens", 16)),
                tenant=str(spec.get("tenant", "default")),
                priority=int(spec.get("priority", 0)),
                deadline_s=spec.get("deadline_s"),
                stop_tokens=spec.get("stop_tokens", ()),
                request_id=spec.get("request_id"))
        except QueueOverflow as e:
            raise _HttpError(429, str(e))
        except AdmissionError as e:
            raise _HttpError(400, str(e))
        except (TypeError, ValueError) as e:
            raise _HttpError(400, f"bad request spec: {e}")
        except ServingError as e:
            raise _HttpError(503, str(e))

    # -- wire formats ------------------------------------------------------
    async def _sse(self, writer: asyncio.StreamWriter, stream: TokenStream,
                   replay: bool = False) -> None:
        """Server-sent events: data-only JSON events, one per token, then
        one terminal done event. A failed write cancels the request."""
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/event-stream\r\n"
                b"Cache-Control: no-store\r\n"
                b"Connection: close\r\n\r\n")
        writer.write(head)
        try:
            await writer.drain()
            idx = 0
            # replay attaches iterate a PRIVATE cursor from token 0, so
            # concurrent consumers of one stream each see the full stream
            source = stream.iter_from(0) if replay else stream
            async for tok in source:
                writer.write(self._sse_event(
                    {"token": tok, "index": idx}))
                idx += 1
                await writer.drain()
            done: Dict[str, Any] = {"done": True,
                                    "reason": stream.finish_reason,
                                    "request_id": stream.request_id}
            if stream.error is not None:
                done["error"] = str(stream.error)
            writer.write(self._sse_event(done))
            await writer.drain()
        except (ConnectionError, OSError):
            # client is gone: reclaim the sequence's blocks
            self.engine.cancel(stream.request_id)

    @staticmethod
    def _sse_event(payload: Dict[str, Any]) -> bytes:
        return b"data: " + json.dumps(payload).encode() + b"\n\n"

    async def _send_json(self, writer: asyncio.StreamWriter, status: int,
                         payload: Dict[str, Any]) -> None:
        await self._send_raw(writer, status, json.dumps(payload).encode(),
                             "application/json")

    async def _send_raw(self, writer: asyncio.StreamWriter, status: int,
                        body: bytes, ctype: str) -> None:
        writer.write(
            f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, '')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n".encode() + body)
        await writer.drain()
