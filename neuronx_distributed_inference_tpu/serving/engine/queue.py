"""Multi-tenant admission queue: priority classes within a tenant,
weighted-fair scheduling across tenants, a starvation bound, and in-queue
deadline expiry.

Scheduling contract (README "Serving engine"):

  * **Across tenants — weighted fair slots.** Each tenant has a weight
    (default 1.0). When the engine has ``k`` free batch slots it fills
    them one at a time, each time picking the tenant whose
    ``occupied_slots / weight`` ratio is lowest (ties broken by oldest
    head request), so steady-state running-slot shares — and therefore
    per-tenant token throughput under continuous batching — converge to
    the weight ratios. Fairness is over SLOTS, not over requests: a
    tenant cannot buy throughput by splitting work into more requests.
  * **Within a tenant — strict priority, then FIFO.** Higher ``priority``
    values run first; equal priorities are served in arrival order.
    Priorities are intra-tenant QoS: a tenant that floods its own
    high-priority lane starves only its own low-priority work.
  * **Starvation bound.** A tenant whose HEAD (next-to-run) request has
    waited longer than ``starvation_bound_s`` jumps the weighted-fair
    order for the next free slot (oldest such head first, across
    tenants), so a low weight or a burst elsewhere can delay but never
    indefinitely starve a tenant's lane. Keying on the head — not the
    tenant's oldest request overall — means a tenant cannot hold one
    stale low-priority request to permanently bypass weighted fairness.
  * **Deadline expiry in queue.** A queued request whose deadline passes
    is removed and typed-expired WITHOUT consuming any device work.
  * **Bounded depth.** ``push`` past ``max_depth`` raises the typed
    :class:`~...resilience.errors.QueueOverflow` before any state change;
    requeues of already-admitted work (preemption victims) bypass the
    bound so eviction can never deadlock against admission control.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ...resilience.errors import ConfigurationError, QueueOverflow
from ...telemetry import get_registry
from ...telemetry import metrics as tmetrics
from .streams import TokenStream

__all__ = ["QueuedRequest", "MultiTenantQueue"]


@dataclass
class QueuedRequest:
    """One submitted request while it waits for (re-)admission.

    ``tokens`` is the CURRENT admission prompt: the original prompt, plus —
    after a preemption — every token generated before eviction (the
    recompute prompt from the :class:`~...resilience.Preempted` record).
    ``orig_prompt_len`` never changes; ``max_new_tokens`` budgets total
    GENERATED tokens across preemptions."""

    request_id: str
    tokens: List[int]
    max_new_tokens: int
    tenant: str
    priority: int
    deadline: Optional[float]          # absolute perf_counter(); None = ∞
    enqueue_t: float
    order: int                         # global arrival index (FIFO tiebreak)
    stream: TokenStream
    orig_prompt_len: int = 0
    stop_tokens: frozenset = frozenset()
    n_preemptions: int = 0
    meta: dict = field(default_factory=dict)
    # SLO-plane anchors (host wall clock; written only when the engine
    # has an SLOTracker attached — see scheduler.py)
    t_first: Optional[float] = None    # first token delivered
    t_last: Optional[float] = None     # latest token delivered
    last_enqueue_t: Optional[float] = None   # most recent (re)queue entry

    def sort_key(self) -> Tuple[int, int]:
        return (-self.priority, self.order)


class MultiTenantQueue:
    """Per-tenant priority heaps + the weighted-fair/starvation pop."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0,
                 max_depth: Optional[int] = 256,
                 starvation_bound_s: float = 2.0):
        self.weights = {t: float(w) for t, w in (weights or {}).items()}
        bad = {t: w for t, w in self.weights.items() if w <= 0}
        if bad or default_weight <= 0:
            # a zero weight reads as "deprioritize" but would divide by
            # zero in the fairness pick; starve-but-don't-kill intent is
            # a small positive weight + the starvation bound
            raise ConfigurationError(
                f"tenant weights must be > 0 (got {bad or default_weight}); "
                "use a small positive weight to deprioritize a tenant")
        self.default_weight = float(default_weight)
        self.max_depth = max_depth
        self.starvation_bound_s = float(starvation_bound_s)
        # degradation overlay (resilience/controller.py): a tenant whose
        # queue-wait SLO is burning gets its EFFECTIVE weight scaled down
        # without touching the configured weights, so releasing the
        # action restores the exact original fairness
        self._weight_scale: Dict[str, float] = {}
        self._heaps: Dict[str, List[Tuple[Tuple[int, int], QueuedRequest]]] \
            = {}
        self._order = itertools.count()

    # -- introspection -----------------------------------------------------
    @property
    def depth(self) -> int:
        return sum(len(h) for h in self._heaps.values())

    def depth_of(self, tenant: str) -> int:
        return len(self._heaps.get(tenant, ()))

    def weight_of(self, tenant: str) -> float:
        """The tenant's EFFECTIVE weight: configured (or default) weight
        times any degradation scale currently applied."""
        return (self.weights.get(tenant, self.default_weight)
                * self._weight_scale.get(tenant, 1.0))

    def set_weight_scale(self, tenant: str, scale: float = 1.0) -> None:
        """Scale a tenant's effective WFQ weight (degradation-controller
        hook — ``tighten_admission``). ``scale=1.0`` removes the overlay;
        the starvation bound still protects a scaled-down tenant."""
        if scale <= 0:
            raise ConfigurationError(
                f"weight scale must be > 0 (got {scale}); use a small "
                "positive scale to deprioritize a tenant")
        if scale == 1.0:
            self._weight_scale.pop(tenant, None)
        else:
            self._weight_scale[tenant] = float(scale)

    def next_order(self) -> int:
        return next(self._order)

    # -- mutation ----------------------------------------------------------
    def push(self, req: QueuedRequest, front: bool = False) -> None:
        """Enqueue. ``front=True`` (preemption requeue) bypasses the depth
        bound and keeps the request's ORIGINAL order/enqueue time, so the
        victim retains its age (and with it the starvation bound's
        protection) instead of going to the back of the line."""
        if (not front and self.max_depth is not None
                and self.depth >= self.max_depth):
            raise QueueOverflow(
                f"serving queue is full ({self.depth}/{self.max_depth}); "
                "shed or retry later")
        heapq.heappush(self._heaps.setdefault(req.tenant, []),
                       (req.sort_key(), req))
        self._tel_depth(req.tenant)

    def remove(self, request_id: str) -> Optional[QueuedRequest]:
        """Drop one queued request by id (cancellation); None if absent."""
        for tenant, heap in self._heaps.items():
            for i, (_, req) in enumerate(heap):
                if req.request_id == request_id:
                    heap[i] = heap[-1]
                    heap.pop()
                    heapq.heapify(heap)
                    self._tel_depth(tenant)
                    return req
        return None

    def expire(self, now: Optional[float] = None) -> List[QueuedRequest]:
        """Remove and return every queued request whose deadline has
        passed — BEFORE it costs any device work."""
        if now is None:
            now = time.perf_counter()
        out: List[QueuedRequest] = []
        for tenant, heap in self._heaps.items():
            live = [(k, r) for k, r in heap
                    if r.deadline is None or now < r.deadline]
            if len(live) != len(heap):
                out.extend(r for _, r in heap
                           if r.deadline is not None and now >= r.deadline)
                heap[:] = live
                heapq.heapify(heap)
                self._tel_depth(tenant)
        return out

    def pop_batch(self, slots: int, occupied: Dict[str, int],
                  now: Optional[float] = None) -> List[QueuedRequest]:
        """Take up to ``slots`` requests in weighted-fair order.

        ``occupied`` maps tenant -> batch slots it currently holds on the
        device (running + pending); each pick increments the local copy so
        one call filling several slots stays proportional."""
        if now is None:
            now = time.perf_counter()
        share = dict(occupied)
        picked: List[QueuedRequest] = []
        while len(picked) < slots:
            tenants = [t for t, h in self._heaps.items() if h]
            if not tenants:
                break
            starving = [t for t in tenants
                        if now - self._oldest(t) > self.starvation_bound_s]
            if starving:
                tenant = min(starving, key=self._oldest)
            else:
                tenant = min(
                    tenants,
                    key=lambda t: (share.get(t, 0) / self.weight_of(t),
                                   self._heaps[t][0][0]))
            _, req = heapq.heappop(self._heaps[tenant])
            self._tel_depth(tenant)
            share[tenant] = share.get(tenant, 0) + 1
            picked.append(req)
        return picked

    # -- helpers -----------------------------------------------------------
    def _oldest(self, tenant: str) -> float:
        """Enqueue time of the tenant's HEAD request — the one the next
        pop would take. Intra-tenant priority stays strict, so a buried
        low-priority request does not age the tenant's lane."""
        return self._heaps[tenant][0][1].enqueue_t

    def _tel_depth(self, tenant: str) -> None:
        reg = get_registry()
        if reg.enabled:
            tmetrics.queue_depth_gauge(reg).set(self.depth_of(tenant),
                                                tenant=tenant)
