"""The closed-loop multi-tenant serving engine over the paged adapter.

:class:`ServingEngine` composes every serving primitive PRs 1-5 landed —
typed transactional admission, recompute preemption, per-request deadlines,
prefix caching with unwritten-block tracking, chunked prefill under
``prefill_budget_tokens``, pipelined ``step_many``/``flush``, and the
telemetry contract — into the engine a load balancer talks to
(ROADMAP item 3; external yardstick: the Gemma-on-Cloud-TPU serving stack,
PAPERS.md arxiv 2605.25645, which reports TTFT/TPOT p50/p99 under
concurrent multi-tenant load).

One :meth:`ServingEngine.run_pass` is the whole closed loop:

  1. **expire** queued requests past their deadline (typed, zero device
     work) and collect adapter preemption records into front-of-queue
     requeues (the :class:`~...resilience.Preempted` ``requeue`` payload —
     tokens, remaining deadline, tenant/priority meta — re-admits without
     side tables; greedy replay is bit-identical, pinned);
  2. **preempt** for priority: when the batch is full and a strictly
     higher-priority request is queued, evict the lowest-priority (then
     most recently admitted) victim via the adapter's public
     :meth:`~..adapter.PagedEngineAdapter.preempt` hook;
  3. **admit** up to ``free_capacity`` requests picked by the queue's
     weighted-fair/priority/starvation-bound order, sorted warm-prefix
     first (:meth:`~..adapter.PagedEngineAdapter.prefix_warmth` peeks the
     block-hash state read-only), as ONE transactional ``add_requests``
     call — chunked prefill under the adapter's budget knob keeps a long
     admission from stalling running decodes;
  4. **dispatch** one decode horizon (``step``/``step_many``) for every
     eligible running row — skipping consumers over their backpressure
     bound — and route tokens to per-request streams.

The engine is synchronous at its core (drive it with :meth:`run_pass` /
:meth:`run_until_drained` from tests and benches); :meth:`run_forever` is
the asyncio wrapper the SSE front door uses.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from ...resilience.errors import (AdmissionError, CapacityError,
                                  ConfigurationError, DeadlineExceeded,
                                  ServingError, StepFailure)
from ...telemetry import get_registry
from ...telemetry import metrics as tmetrics
from ...telemetry.request_trace import new_trace_id, trace_of
from ...telemetry.trace import get_recorder as _get_recorder
from .queue import MultiTenantQueue, QueuedRequest
from .streams import TokenStream

__all__ = ["ServingEngine"]


class ServingEngine:
    """Multi-tenant scheduler + streaming front door over a
    :class:`~..adapter.PagedEngineAdapter`.

    ``tenant_weights`` maps tenant name -> weight (unlisted tenants get
    ``default_weight``); running-slot shares converge to the weight ratios
    under backlog (see ``queue.py`` for the full fairness contract).
    ``decode_steps_per_pass > 1`` fuses that many decode steps per pass
    through ``step_many`` (one dispatch + one fetch), clamped so no row
    can overshoot its token budget or the compiled ``seq_len``.
    ``max_unread_tokens`` bounds how far a stream may run ahead of its
    consumer before the engine stops stepping that sequence (None = no
    backpressure). ``priority_preemption=False`` disables scheduler-driven
    eviction (the adapter's own KV-pressure preemption still applies).
    ``slo`` attaches a :class:`~...telemetry.slo.SLOTracker`: the engine
    feeds it TTFT (submit → first token), per-request mean TPOT and queue
    wait per tenant, host-side only — its report/hint surface is
    read-only (``debug_state()["slo"]``, ``bench.py --slo-report``)."""

    def __init__(self, adapter, *,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0,
                 max_queue_depth: Optional[int] = 256,
                 starvation_bound_s: float = 2.0,
                 max_unread_tokens: Optional[int] = None,
                 decode_steps_per_pass: int = 1,
                 priority_preemption: bool = True,
                 debug_dump_dir: Optional[str] = None,
                 slo=None, degradation=None):
        for hook in ("take_preempted", "preempt", "prefix_warmth",
                     "free_capacity", "pending_prefill_ids"):
            if not hasattr(adapter, hook):
                raise ConfigurationError(
                    "ServingEngine needs the paged adapter surface "
                    f"(missing {hook!r}); build it over a "
                    "PagedEngineAdapter")
        if decode_steps_per_pass < 1:
            raise ConfigurationError("decode_steps_per_pass must be >= 1")
        self.adapter = adapter
        self.queue = MultiTenantQueue(tenant_weights, default_weight,
                                      max_queue_depth, starvation_bound_s)
        self.decode_steps_per_pass = decode_steps_per_pass
        self.max_unread_tokens = max_unread_tokens
        self.priority_preemption = priority_preemption
        # post-mortem artifacts: when set, an unrecoverable StepFailure
        # writes dump_debug_state() here before the engine closes
        self.debug_dump_dir = debug_dump_dir
        # advisory per-tenant SLO plane (telemetry/slo.py); None = no
        # tracking cost at all (every hook is one attribute check)
        self.slo = slo
        # closed-loop degradation (resilience/controller.py): consulted
        # once per pass, acts on the SLO burn index with hysteresis
        if degradation is not None:
            if slo is None:
                raise ConfigurationError(
                    "degradation= needs slo= — the controller acts on "
                    "the SLO tracker's burn index (telemetry/slo.py)")
            if not hasattr(degradation, "update"):
                raise ConfigurationError(
                    "degradation= takes a DegradationController "
                    "(resilience/controller.py) or a compatible "
                    "update(engine) surface")
            if hasattr(degradation, "check_policy"):
                # loud at construction: a defaulted enter threshold that
                # lands at or below exit_burn would flap per pass
                degradation.check_policy(slo.policy)
        self.degradation = degradation
        self._active: Dict[int, QueuedRequest] = {}     # seq_id -> request
        self._sid_of: Dict[str, int] = {}               # request_id -> seq
        self._trace_ids: Dict[str, str] = {}   # request_id -> trace (bounded)
        self._seq_ids = itertools.count()
        self._rid_counter = itertools.count()
        self._reserved: List[str] = []   # rids owed the next freed slots
        self._closed = False
        try:
            self._max_prompt = adapter.app.tpu_config.seq_len
        except AttributeError:
            self._max_prompt = None
        self.stats: Dict[str, int] = {
            "submitted": 0, "completed": 0, "expired_queue": 0,
            "expired_running": 0, "cancelled": 0, "preempt_requeues": 0,
            "priority_preemptions": 0, "admission_retries": 0,
            "capacity_stalls": 0, "step_retries": 0}

    # -- public surface ----------------------------------------------------
    def submit(self, tokens: Sequence[int], max_new_tokens: int, *,
               tenant: str = "default", priority: int = 0,
               deadline_s: Optional[float] = None,
               stop_tokens: Sequence[int] = (),
               request_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               adapter: Optional[str] = None) -> TokenStream:
        """Enqueue one request; returns its :class:`TokenStream`
        immediately (no device work happens here). Raises the typed
        :class:`~...resilience.errors.QueueOverflow` when the queue is at
        ``max_queue_depth`` and :class:`AdmissionError` for malformed
        arguments — both before any state change.

        ``trace_id`` continues an existing request trace (a fleet router
        or handoff continuation passes the original id); None mints a
        fresh one. The id rides ``meta["trace"]`` through the adapter,
        ``Preempted`` records and handoffs, so one trace follows the
        request across preemptions and replicas (see
        telemetry/request_trace.py).

        ``adapter`` names the request's LoRA adapter: it rides
        ``meta["adapter"]`` to the paged adapter, which resolves it to a
        pinned device slot at admission (README "Multi-LoRA serving") —
        no-op for engines without a lora_pool (the key is simply never
        read)."""
        if self._closed:
            raise ServingError("engine is closed")
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise AdmissionError("empty prompt")
        if self._max_prompt is not None and len(tokens) > self._max_prompt:
            # reject here, not at admission time: by then the request is
            # batched with innocent neighbours inside one transactional
            # add_requests call
            raise AdmissionError(
                f"prompt is {len(tokens)} tokens — beyond the compiled "
                f"seq_len {self._max_prompt}")
        if max_new_tokens < 1:
            raise AdmissionError("max_new_tokens must be >= 1")
        rid = (request_id if request_id is not None
               else f"r{next(self._rid_counter)}")
        if rid in self._sid_of or any(
                r.request_id == rid for r in self._queued()):
            raise AdmissionError(f"request_id {rid!r} already in flight")
        now = time.perf_counter()
        tid = trace_id if trace_id is not None else new_trace_id()
        stream = TokenStream(rid, tenant)
        req = QueuedRequest(
            request_id=rid, tokens=tokens, max_new_tokens=max_new_tokens,
            tenant=tenant, priority=priority,
            deadline=None if deadline_s is None else now + deadline_s,
            enqueue_t=now, order=self.queue.next_order(), stream=stream,
            orig_prompt_len=len(tokens),
            stop_tokens=frozenset(int(t) for t in stop_tokens),
            meta={"request_id": rid, "tenant": tenant,
                  "priority": priority, "trace": tid})
        if adapter is not None:
            req.meta["adapter"] = str(adapter)
        self.queue.push(req)         # may raise QueueOverflow
        stream._cancel_cb = lambda: self.cancel(rid)
        self.stats["submitted"] += 1
        self._remember_trace(rid, tid)
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("trace.begin", cat="request", trace=tid,
                        request_id=rid, tenant=tenant,
                        prompt_len=len(tokens), deadline_s=deadline_s,
                        continued=trace_id is not None)
        return req.stream

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued or running request: queued entries are dropped
        with zero device work; running sequences are released and their
        KV blocks reclaimed. Returns False when the id is unknown or
        already finished."""
        req = self.queue.remove(request_id)
        if req is not None:
            self._observe_wait(req, "cancelled")
            req.stream.finish("cancelled", req.stream.cancelled_error())
            self._finalize(req)
            self.stats["cancelled"] += 1
            return True
        sid = self._sid_of.get(request_id)
        if sid is None:
            return False
        req = self._retire(sid)
        self.adapter.release([sid])
        req.stream.finish("cancelled", req.stream.cancelled_error())
        self._finalize(req)
        self.stats["cancelled"] += 1
        return True

    def submit_record(self, rec, max_new_tokens: int, *,
                      stop_tokens: Sequence[int] = (),
                      request_id: Optional[str] = None) -> TokenStream:
        """Submit one :class:`~...resilience.Preempted` record — the
        fleet router's replica-failover path, riding the same
        ``admission_kwargs()`` requeue contract the in-engine preemption
        requeue uses: the record's tokens are the recompute prompt, its
        remaining deadline budget carries over, and tenant/priority come
        from the meta passthrough. ``max_new_tokens`` is the REMAINING
        token budget (the caller already delivered the rest)."""
        kw = rec.admission_kwargs()
        meta = kw["meta"][0] if isinstance(kw["meta"][0], dict) else {}
        stream = self.submit(
            kw["prompts"][0], max_new_tokens,
            tenant=str(meta.get("tenant", "default")),
            priority=int(meta.get("priority", 0)),
            deadline_s=kw["deadline_s"][0], stop_tokens=stop_tokens,
            request_id=request_id, trace_id=trace_of(meta),
            adapter=meta.get("adapter"))
        if self.slo is not None and rec.n_generated > 0:
            # a continuation: the CLIENT saw its first token long ago on
            # the failed replica — this engine's first delivery must not
            # be observed as a fresh (artificially fast) TTFT sample
            now = time.perf_counter()
            for r in self._queued():
                if r.request_id == stream.request_id:
                    r.t_first = r.t_last = now
                    break
        return stream

    @property
    def closed(self) -> bool:
        """True once the engine stopped serving — explicit :meth:`close`
        or an unrecoverable device failure. The fleet router polls this
        to mark replicas dead (see serving/fleet/router.py)."""
        return self._closed

    @property
    def load(self):
        """(queued requests, active requests) — the same numbers
        :meth:`debug_state` reports, without building the full
        post-mortem snapshot. The fleet router's per-submit routing
        tie-break reads this."""
        return (self.queue.depth, len(self._active))

    def seq_id_of(self, request_id: str):
        """The adapter seq id of an ADMITTED request, or None while it
        is still queued / mid-prefill / unknown — the fleet migration
        path (serving/fleet/handoff.py ``migrate``) captures by seq id."""
        return self._sid_of.get(request_id)

    @property
    def has_work(self) -> bool:
        return bool(self._active) or self.queue.depth > 0

    def run_pass(self) -> int:
        """One closed-loop scheduling pass (see the module docstring).
        Returns the number of tokens delivered to streams. With the flight
        recorder enabled each stage lands as a ``pass.*`` complete slice
        on the trace timeline (stable names: ``pass.expire``,
        ``pass.preempt``, ``pass.admit``, ``pass.dispatch``; the adapter
        adds ``dispatch.*``/``fetch.*`` inside the dispatch slice)."""
        now = time.perf_counter()
        rec = _get_recorder()            # disabled: span() is a no-op CM
        if self.degradation is not None:
            # close the loop BEFORE this pass's admission so a tightened
            # weight/shed applies to the work it is about to schedule
            self.degradation.update(self, now=now)
        with rec.span("pass.expire", cat="engine"):
            self._expire_queue(now)
        with rec.span("pass.preempt", cat="engine"):
            self._collect_preempted()
            self._priority_preempt()
        with rec.span("pass.admit", cat="engine"):
            self._admit(now)
            # admission may itself have preempted running victims for
            # blocks (reason="admission"): requeue them before the
            # dispatch stage so their dead seq_ids never reach a step call
            self._collect_preempted()
        with rec.span("pass.dispatch", cat="engine"):
            return self._dispatch_engine_pass()

    def run_until_drained(self, max_passes: int = 100000) -> None:
        """Drive :meth:`run_pass` until no queued or running work remains
        (closed-loop tests and benches). Raises :class:`StepFailure` if
        the device dies unrecoverably mid-drive."""
        passes = 0
        while self.has_work:
            self.run_pass()
            passes += 1
            if passes >= max_passes:
                raise ServingError(
                    f"run_until_drained made no progress in {max_passes} "
                    "passes — scheduler wedged (file a bug with the "
                    "engine stats)", seq_ids=tuple(self._active))

    async def run_forever(self, idle_sleep_s: float = 0.001) -> None:
        """Asyncio driver: run scheduling passes until :meth:`close`,
        yielding to the event loop between passes (and napping while
        idle) so SSE writers and new submits interleave.

        An UNEXPECTED exception (not part of the :class:`ServingError`
        taxonomy — an engine bug, a broken adapter hook) must not kill
        the loop bare with every client stream left hanging: it is
        wrapped into an unrecoverable :class:`StepFailure`, the
        post-mortem is dumped (``debug_dump_dir``) and every stream
        finishes typed ("error") before the wrapper re-raises — pinned
        by tests/test_resilience_control.py."""
        while not self._closed:
            try:
                delivered = self.run_pass() if self.has_work else 0
            except StepFailure:
                raise          # _fatal already ran at the raise site
            except Exception as e:
                # any OTHER exception escaping a pass — a bare bug or an
                # unexpected typed error (SequenceStateError & co never
                # legitimately escape run_pass) — gets the same fatal
                # teardown: no hanging client streams
                err = StepFailure(
                    f"unexpected {type(e).__name__} in the serving loop "
                    "— engine state was dumped and every stream failed "
                    "typed; rebuild the engine before serving",
                    phase="engine", retry_safe=False)
                self._fatal(err)
                raise err from e
            if delivered or self.has_work:
                await asyncio.sleep(0)
            else:
                await asyncio.sleep(idle_sleep_s)

    def close(self) -> None:
        """Stop :meth:`run_forever` and fail over remaining work: queued
        and running requests finish with reason "cancelled"."""
        self._closed = True
        for req in list(self._queued()):
            self.queue.remove(req.request_id)
            req.stream.finish("cancelled", req.stream.cancelled_error())
            self._finalize(req)
        for sid in list(self._active):
            req = self._retire(sid)
            self.adapter.release([sid])
            req.stream.finish("cancelled", req.stream.cancelled_error())
            self._finalize(req)

    # -- pass stages -------------------------------------------------------
    def _expire_queue(self, now: float) -> None:
        rec = _get_recorder()
        for req in self.queue.expire(now):
            self._observe_wait(req, "expired")
            reg = get_registry()
            if reg.enabled:
                tmetrics.deadline_expired_counter(reg).inc(
                    engine="queue", tenant=req.tenant)
            err = DeadlineExceeded(
                f"request {req.request_id} expired after "
                f"{now - req.enqueue_t:.3f}s in queue")
            if rec.enabled:
                rec.error(err, request_id=req.request_id,
                          tenant=req.tenant, where="queue")
            req.stream.finish("deadline", err)
            self._finalize(req)
            self.stats["expired_queue"] += 1

    def _collect_preempted(self) -> None:
        for rec in self.adapter.take_preempted():
            self._requeue(rec)

    def _requeue(self, rec) -> None:
        """Turn one :class:`Preempted` record back into a queued request
        via its requeue payload. Tokens the victim generated before
        eviction are part of the recompute prompt — any not yet delivered
        (sampled while in flight) are delivered now, and the budget
        counts them."""
        meta = rec.meta or {}
        rid = meta.get("request_id")
        req = self._active.get(rec.seq_id)
        if req is None or rid != req.request_id:
            return                   # not engine-owned (foreign caller)
        del self._active[rec.seq_id]
        del self._sid_of[rid]
        generated = list(rec.tokens[req.orig_prompt_len:])
        already = req.stream.n_tokens
        done = False
        delivered = 0
        for tok in generated[already:]:
            req.stream.put(tok)
            delivered += 1
            done = self._hit_limit(req, tok)
            if done:
                break
        self._slo_note_delivery(req, delivered)
        if done:
            self._finalize(req)
            self.stats["completed"] += 1
            return
        req.tokens = list(rec.tokens)
        req.deadline = rec.deadline
        req.n_preemptions += 1
        # the SLO queue-wait clock restarts here: time already spent
        # RUNNING must not count as queue wait after the requeue
        req.last_enqueue_t = time.perf_counter()
        self.queue.push(req, front=True)
        self.stats["preempt_requeues"] += 1
        trec = _get_recorder()
        if trec.enabled:
            trec.instant("trace.requeue", cat="request",
                         trace=trace_of(req.meta),
                         request_id=req.request_id, reason=rec.reason,
                         n_delivered=req.stream.n_tokens)

    def _priority_preempt(self) -> None:
        """When the batch is full and a strictly higher-priority request
        waits, evict the lowest-priority victim (ties: most recently
        submitted) through the adapter hook and requeue it at the front
        of its tenant's lane. The freed slot is RESERVED for the request
        that justified the eviction — without the reservation, weighted
        fairness could hand the slot straight back to the victim and
        livelock in an evict/re-prefill cycle while the high-priority
        request starves."""
        if not self.priority_preemption:
            return
        while self.adapter.free_capacity == 0 and self._active:
            best = max(self._queued(),
                       key=lambda r: (r.priority, -r.order), default=None)
            if best is None:
                return
            victim_sid, victim = min(
                self._active.items(),
                key=lambda kv: (kv[1].priority, -kv[1].order))
            if victim.priority >= best.priority:
                return               # nothing strictly lower-priority
            rec = self.adapter.preempt(victim_sid, reason="scheduler")
            self.stats["priority_preemptions"] += 1
            self._requeue(rec)
            self._reserved.append(best.request_id)

    def _admit(self, now: float) -> None:
        cap = self.adapter.free_capacity
        if cap <= 0 or self.queue.depth == 0:
            self._reserved.clear()
            return
        # slots freed by priority preemption go to the requests that
        # justified the evictions, ahead of the weighted-fair pick
        batch: List[QueuedRequest] = []
        for rid in self._reserved:
            if len(batch) >= cap:
                break
            req = self.queue.remove(rid)   # None: cancelled/expired since
            if req is not None:
                batch.append(req)
        self._reserved.clear()
        if len(batch) < cap:
            occupied: Dict[str, int] = {}
            for req in self._active.values():
                occupied[req.tenant] = occupied.get(req.tenant, 0) + 1
            for req in batch:
                occupied[req.tenant] = occupied.get(req.tenant, 0) + 1
            batch.extend(self.queue.pop_batch(cap - len(batch), occupied,
                                              now))
        if not batch:
            return
        # warm-prefix-first admission ordering: stable sort keeps the
        # fairness pick order among equally-warm requests, and puts warm
        # prompts ahead so intra-call shared prefixes hit originator-first
        batch.sort(key=lambda r: -self.adapter.prefix_warmth(r.tokens))
        try:
            first = self._add_batch(batch, now)
        except DeadlineExceeded:
            # a zero-remaining budget expired inside admission: retry the
            # expiry stage next pass (adapter rolled the call back)
            for r in reversed(batch):
                self.queue.push(r, front=True)
            self.stats["admission_retries"] += 1
            return
        except AdmissionError:
            # one bad request must not sink its innocent batch neighbours
            # (or the serving loop): isolate it by admitting one-by-one
            first = {}
            for r in batch:
                try:
                    first.update(self._add_batch([r], now))
                except AdmissionError as e:
                    r.stream.finish("error", e)
                    self._finalize(r)
                except (DeadlineExceeded, CapacityError, StepFailure) as e:
                    if isinstance(e, StepFailure) and not e.retry_safe:
                        self._fatal(e)
                        raise
                    self.queue.push(r, front=True)
                    self.stats["admission_retries"] += 1
        except (CapacityError, StepFailure) as e:
            if isinstance(e, StepFailure) and not e.retry_safe:
                self._fatal(e)
                raise
            # pool dry even after the adapter's own eviction, or a
            # retry-safe fault: requeue and try again next pass
            for r in reversed(batch):
                self.queue.push(r, front=True)
            self.stats["admission_retries"] += 1
            if isinstance(e, CapacityError):
                self._note_headroom("admit")
            return
        for sid, tok in first.items():   # non-deferred adapters
            self._deliver(sid, [tok])

    def _add_batch(self, batch: List[QueuedRequest],
                   now: float) -> Dict[int, int]:
        """One transactional add_requests call; registers the admitted
        requests and returns the adapter's first-token dict (empty under
        a deferred prefill budget)."""
        sids = [next(self._seq_ids) for _ in batch]
        first = self.adapter.add_requests(
            sids, [r.tokens for r in batch],
            deadline_s=[None if r.deadline is None
                        else max(r.deadline - now, 0.0) for r in batch],
            meta=[r.meta for r in batch])
        rec = _get_recorder()
        for sid, req in zip(sids, batch):
            self._active[sid] = req
            self._sid_of[req.request_id] = sid
            self._observe_wait(req, "admitted")
            if rec.enabled:
                # wait_s measures from the most recent (re)queue entry,
                # matching the SLO queue-wait sample for this admission
                since = (req.last_enqueue_t
                         if req.last_enqueue_t is not None
                         else req.enqueue_t)
                rec.instant("trace.admit", cat="request",
                            trace=trace_of(req.meta),
                            request_id=req.request_id, seq_id=int(sid),
                            wait_s=now - since)
        return first

    def _dispatch_engine_pass(self) -> int:
        """Drive one decode horizon and route tokens to streams. This is
        the engine's dispatch-driving loop: it must stay free of host
        materialization of device values (tier-1 lint region,
        ``scripts/nxdi_lint.py`` host-sync pass) — every token it touches is
        already a host int handed back by the adapter."""
        pending = set(self.adapter.pending_prefill_ids)
        alive = self.adapter.seqs
        eligible: List[int] = []
        horizon = self.decode_steps_per_pass
        # speculative / ragged adapter: the pass budgets by TOKENS-
        # DELIVERED, not steps — each row gets its remaining token budget
        # as a per-row candidate-width clamp (decode_steps_per_pass > 1
        # caps it), and the pass stays one engine step. A ragged adapter
        # routes through the RaggedBatchPlanner: ONE materialized mixed
        # prefill+decode+verify dispatch per pass (serving/ragged/)
        spec = getattr(self.adapter, "_spec", None)
        if spec is None:
            spec = getattr(self.adapter, "_ragged", None)
        room: Dict[int, int] = {}
        for sid, req in self._active.items():
            if sid not in alive and sid not in pending:
                continue             # preempted, record not collected yet
            if sid in pending:
                eligible.append(sid)   # wants prefill progress, no decode
                continue
            if (self.max_unread_tokens is not None
                    and req.stream.unread >= self.max_unread_tokens):
                continue               # backpressure: consumer is behind
            r = self._room(sid, req)
            if spec is not None:
                room[sid] = (min(r, self.decode_steps_per_pass)
                             if self.decode_steps_per_pass > 1 else r)
            else:
                horizon = min(horizon, r)
            eligible.append(sid)
        if not eligible:
            try:
                drained = self.adapter.flush()   # pipelined leftovers
            except StepFailure as e:
                # the deferred fetch of an earlier dispatch can fail here
                # too — same contract as the dispatch below, so the
                # run_forever invariant ("a StepFailure raise site ran
                # _fatal first when unrecoverable") holds on this path
                if e.retry_safe:
                    self.stats["step_retries"] += 1
                    return 0
                self._fatal(e)
                raise
            return self._route(drained if isinstance(drained, dict) else {})
        try:
            if spec is not None:
                res = self.adapter.step(eligible, token_room=room)
            elif horizon > 1:
                res = self.adapter.step_many(horizon, eligible)
            else:
                res = {s: [t] for s, t in
                       self.adapter.step(eligible).items()}
        except DeadlineExceeded as e:
            self._expire_running(e.seq_ids)
            return 0
        except CapacityError as e:
            if e.seq_ids:
                self._finish_capacity(e.seq_ids)
            else:
                self.stats["capacity_stalls"] += 1
            self._note_headroom("step")
            return 0
        except StepFailure as e:
            if e.retry_safe:
                self.stats["step_retries"] += 1
                return 0
            self._fatal(e)
            raise
        return self._route(res)

    # -- token routing -----------------------------------------------------
    def _route(self, res) -> int:
        n = 0
        for sid, toks in res.items():
            toks = toks if isinstance(toks, list) else [toks]
            n += self._deliver(sid, toks)
        if n:
            rec = _get_recorder()
            if rec.enabled:
                rec.instant("stream.deliver", cat="engine", tokens=n,
                            seq_ids=[int(s) for s in res])
        return n

    def _deliver(self, sid: int, toks: List[int]) -> int:
        req = self._active.get(sid)
        if req is None:
            return 0                 # raced with cancel/preempt
        n = 0
        done = False
        for tok in toks:
            req.stream.put(tok)
            n += 1
            if self._hit_limit(req, tok):
                self._retire(sid)
                self.adapter.release([sid])
                self.stats["completed"] += 1
                done = True
                break
        self._slo_note_delivery(req, n)
        if done:
            self._finalize(req)
        return n

    def _slo_note_delivery(self, req: QueuedRequest, n: int) -> None:
        """SLO timestamp bookkeeping shared by every path that puts
        tokens on a stream (normal dispatch AND preempt-replay): first
        delivery anchors TTFT, every delivery advances t_last."""
        if n == 0 or self.slo is None:
            return
        now = time.perf_counter()
        if req.t_first is None:
            req.t_first = now
            # client-observed TTFT: submit -> first delivered token
            # (queue wait included — the number a user feels)
            self.slo.observe(req.tenant, "ttft", now - req.enqueue_t,
                             now=now)
        req.t_last = now

    def _hit_limit(self, req: QueuedRequest, tok: int) -> bool:
        if tok in req.stop_tokens:
            req.stream.finish("stop")
            return True
        if req.stream.n_tokens >= req.max_new_tokens:
            req.stream.finish("length")
            return True
        return False

    def _room(self, sid: int, req: QueuedRequest) -> int:
        """Largest decode horizon this row can take without overshooting
        its token budget or the compiled seq_len."""
        room = req.max_new_tokens - req.stream.n_tokens
        st = self.adapter.seqs.get(sid)
        limit = getattr(self.adapter, "_pos_limit", None)
        if st is not None and limit is not None:
            room = min(room, limit - st.position)
        return max(room, 1)

    # -- terminal paths ----------------------------------------------------
    def _retire(self, sid: int) -> QueuedRequest:
        req = self._active.pop(sid)
        self._sid_of.pop(req.request_id, None)
        return req

    def _expire_running(self, seq_ids: Sequence[int]) -> None:
        for sid in seq_ids:
            if sid not in self._active:
                continue
            req = self._retire(sid)
            self.adapter.release([sid])
            req.stream.finish("deadline", DeadlineExceeded(
                f"request {req.request_id} exceeded its deadline while "
                "running"))
            self._finalize(req)
            self.stats["expired_running"] += 1

    def _finish_capacity(self, seq_ids: Sequence[int]) -> None:
        for sid in seq_ids:
            if sid not in self._active:
                continue
            req = self._retire(sid)
            self.adapter.release([sid])
            req.stream.finish("capacity", CapacityError(
                f"request {req.request_id} reached the compiled seq_len",
                seq_ids=(sid,)))
            self._finalize(req)

    def _fatal(self, err: StepFailure) -> None:
        """Unrecoverable device failure: every stream is failed; the
        adapter (and its application) must be rebuilt before serving.
        With ``debug_dump_dir`` set, the post-mortem (flight-recorder tail
        + engine/adapter snapshot) is written BEFORE the teardown empties
        the state it describes."""
        if self.debug_dump_dir is not None:
            try:
                self.dump_debug_state(
                    os.path.join(self.debug_dump_dir,
                                 f"nxdi_postmortem_{id(err):x}.json"),
                    error=err)
            except Exception:
                # the dump must never mask the error OR abort the stream
                # teardown below (e.g. a non-JSON-able recorded arg)
                pass
        self._closed = True
        for sid in list(self._active):
            req = self._retire(sid)
            req.stream.finish("error", err)
            self._finalize(req)
        for req in list(self._queued()):
            self.queue.remove(req.request_id)
            req.stream.finish("error", err)
            self._finalize(req)

    def _note_headroom(self, where: str) -> None:
        """Flight-record the admission-headroom estimate at the moment a
        capacity reject happens — free batch slots, free KV blocks and
        the token headroom they represent (serving/warmup.py
        ``admission_headroom``), so post-mortems can tell a full pool
        from a fragmented one."""
        rec = _get_recorder()
        if not rec.enabled:
            return
        try:
            from ..warmup import admission_headroom
            rec.instant("admission.headroom", cat="engine", where=where,
                        **admission_headroom(self.adapter))
        except Exception:
            # best-effort observability: a broken estimate must never
            # turn a capacity stall into an engine fault
            pass

    # -- post-mortem surface ----------------------------------------------
    def debug_state(self) -> Dict[str, Any]:
        """Read-only JSON-able snapshot of the scheduler + adapter:
        per-tenant queue depths, active requests (seq_id, tenant,
        priority, delivered tokens), reservation state and the adapter's
        own view (running/pending ids, block occupancy, pipeline depth).
        Served live by ``GET /v1/debug/state``."""
        per_tenant = {t: self.queue.depth_of(t)
                      for t in self.queue._heaps if self.queue.depth_of(t)}
        active = {
            int(sid): {"request_id": req.request_id, "tenant": req.tenant,
                       "priority": req.priority,
                       "n_tokens": req.stream.n_tokens,
                       "max_new_tokens": req.max_new_tokens,
                       "n_preemptions": req.n_preemptions}
            for sid, req in self._active.items()}
        adapter = (self.adapter.debug_state()
                   if hasattr(self.adapter, "debug_state") else {})
        out = {
            "closed": self._closed,
            "stats": dict(self.stats),
            "queue": {"depth": self.queue.depth, "per_tenant": per_tenant},
            "active": active,
            "reserved": list(self._reserved),
            "adapter": adapter,
        }
        app = getattr(self.adapter, "app", None)
        if app is not None and hasattr(app, "warmup_state"):
            # cold-start discipline (serving/warmup.py): the precompile
            # report summary plus every steady-state recompile incident
            out["warmup"] = app.warmup_state()
        if self.slo is not None:
            # read-only SLO plane: per-tenant percentiles, burn rates and
            # the advisory degradation hint (telemetry/slo.py)
            out["slo"] = self.slo.report()
        if self.degradation is not None:
            # the closed-loop actuator's hysteresis state
            # (resilience/controller.py)
            out["degradation"] = self.degradation.state()
        return out

    def dump_debug_state(self, path: Optional[str] = None,
                         error: Optional[BaseException] = None,
                         trace_tail: int = 256) -> Dict[str, Any]:
        """Assemble (and optionally write) one post-mortem artifact: the
        engine/adapter snapshot, the newest ``trace_tail`` flight-recorder
        events with the ring's own drop count (so the artifact states its
        truncation), and the failing error's identity + ``trace_id`` when
        one is given. Returns the JSON-able dict; writes it to ``path``
        when provided (parent directories are created)."""
        rec = _get_recorder()
        dump: Dict[str, Any] = {
            "schema": "nxdi-debug-state-v1",
            "error": None if error is None else {
                "type": type(error).__name__,
                "message": str(error),
                "seq_ids": [int(s) for s in
                            getattr(error, "seq_ids", ()) or ()],
                "phase": getattr(error, "phase", None),
                "retry_safe": getattr(error, "retry_safe", None),
                "trace_id": getattr(error, "trace_id", None),
            },
            "engine": self.debug_state(),
            "trace": {
                "enabled": rec.enabled,
                "events": rec.tail(trace_tail),
                "dropped": rec.dropped,
                "capacity": rec.capacity,
            },
        }
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w") as fh:
                json.dump(dump, fh, indent=1)
            dump["artifact_path"] = path
        return dump

    # -- helpers -----------------------------------------------------------
    def _queued(self):
        for heap in self.queue._heaps.values():
            for _, req in heap:
                yield req

    def _observe_wait(self, req: QueuedRequest, outcome: str) -> None:
        now = time.perf_counter()
        if self.slo is not None and outcome == "admitted":
            # a re-admission measures from its REQUEUE time, not the
            # original submit — time spent running is not queue wait
            since = (req.last_enqueue_t if req.last_enqueue_t is not None
                     else req.enqueue_t)
            self.slo.observe(req.tenant, "queue_wait", now - since,
                             now=now)
        reg = get_registry()
        if reg.enabled:
            tmetrics.queue_wait_histogram(reg).observe(
                now - req.enqueue_t,
                tenant=req.tenant, outcome=outcome)

    # -- request-trace plumbing (telemetry/request_trace.py) ---------------
    def _remember_trace(self, request_id: str, trace_id: str,
                        bound: int = 1024) -> None:
        """Bounded request_id -> trace_id map behind
        ``GET /v1/debug/trace/<id>`` (oldest entries beyond ``bound``
        evicted — dict preserves insertion order)."""
        self._trace_ids[request_id] = trace_id
        while len(self._trace_ids) > bound:
            del self._trace_ids[next(iter(self._trace_ids))]

    def trace_id_of(self, request_id: str) -> Optional[str]:
        """The trace id minted (or continued) for a request submitted to
        THIS engine, None for unknown ids (the map is bounded — very old
        finished requests age out)."""
        return self._trace_ids.get(request_id)

    def _finalize(self, req: QueuedRequest) -> None:
        """Terminal request bookkeeping shared by every finish path:
        the ``trace.emit`` lifecycle event and — with an SLO tracker
        attached and >= 2 tokens delivered over >= 2 delivery passes —
        the per-request mean TPOT observation. A request whose tokens
        all landed in ONE pass (fused horizon, speculation burst,
        preempt replay) has no delivery interval to measure: it
        contributes no TPOT sample rather than a fake-perfect 0.0."""
        if (self.slo is not None and req.t_first is not None
                and req.t_last is not None and req.stream.n_tokens > 1
                and req.t_last > req.t_first):
            self.slo.observe(
                req.tenant, "tpot",
                (req.t_last - req.t_first) / (req.stream.n_tokens - 1),
                now=req.t_last)
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("trace.emit", cat="request",
                        trace=trace_of(req.meta),
                        request_id=req.request_id, tenant=req.tenant,
                        reason=req.stream.finish_reason,
                        n_tokens=req.stream.n_tokens)
