"""Multi-tenant serving engine: request queue with priority classes and
weighted-fair tenant scheduling, prefix-cache-aware admission ordering,
per-request token streams, and an asyncio HTTP/SSE front door — the
closed-loop layer a load balancer talks to, over the paged adapter
(ROADMAP item 3; README "Serving engine" is the contract)."""

from .frontend import ServingFrontend
from .queue import MultiTenantQueue, QueuedRequest
from .scheduler import ServingEngine
from .streams import TokenStream

__all__ = ["ServingEngine", "ServingFrontend", "TokenStream",
           "MultiTenantQueue", "QueuedRequest"]
