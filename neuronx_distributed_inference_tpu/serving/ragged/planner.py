"""Ragged row planning: assemble ALL runnable work into ONE row plan.

The :class:`RaggedBatchPlanner` is the scheduling half of the ragged
unified dispatch (see the package docstring): each engine step it walks
the adapter's state — live decode rows, speculative verify windows, and
pending chunked-prefill admissions — and lays them out as ragged rows of
a single :func:`~...models.model_base.paged_ragged_step` dispatch. A
:class:`RaggedRow` carries everything the packer needs: the row's seq_id,
kind tag (``decode`` / ``verify`` / ``prefill``), absolute token offset,
real-token width, and (prefill rows) whether the chunk completes the
prompt.

Contracts the plan preserves from the two-phase paths it replaces:

  * pending admissions keep their admission order and their deadline
    semantics — a TARGETED expired pending row raises
    :class:`~...resilience.errors.DeadlineExceeded` before any device
    work; an untargeted one is merely skipped from packing;
  * ``prefill_budget_tokens`` survives as a per-step cap on REAL prompt
    tokens packed into the dispatch (the planner subsumes the old
    "at most one chunk dispatch BEFORE the decode dispatch"
    serialization point — prefill rows now ride the same dispatch);
  * total rows never exceed the compiled batch (admission already
    guarantees running + pending <= batch);
  * per-row verify widths are clamped exactly like the standalone
    speculative path: ``k+1`` bounded by seq_len headroom and the
    scheduler's per-row token room, floored at 1.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ...resilience.errors import DeadlineExceeded
from ..adapter import _meta_tenant, _trace_error

__all__ = ["RaggedRow", "RaggedPlan", "RaggedBatchPlanner",
           "KIND_DECODE", "KIND_PREFILL", "KIND_VERIFY"]

KIND_DECODE = "decode"
KIND_PREFILL = "prefill"
KIND_VERIFY = "verify"


@dataclass
class RaggedRow:
    """One row of the unified dispatch: ``width`` real tokens starting at
    absolute position ``offset`` over ``seq_id``'s own block table."""
    seq_id: int
    kind: str                  # KIND_DECODE | KIND_VERIFY | KIND_PREFILL
    offset: int                # absolute position of the row's first token
    width: int                 # real tokens in the row (>= 1)
    final: bool = False        # prefill row completing its prompt
    adapter_id: int = -1       # pinned LoRA slot; -1/0 = base model (the
    #                            dispatch gathers slot 0, the zero adapter)


@dataclass
class RaggedPlan:
    """The per-step row plan: live (decode/verify) rows first — in the
    step call's row order — then pending prefill rows in admission
    order. ``widths`` maps each live row to its candidate width for KV
    growth and rollback."""
    rows: List[RaggedRow]
    widths: Dict[int, int]

    @property
    def live_ids(self) -> List[int]:
        return [r.seq_id for r in self.rows if r.kind != KIND_PREFILL]

    @property
    def prefill_ids(self) -> List[int]:
        return [r.seq_id for r in self.rows if r.kind == KIND_PREFILL]

    def prune(self, adapter) -> None:
        """Drop rows whose sequence left the adapter mid-plan (preempted
        while growing KV for the dispatch)."""
        self.rows = [r for r in self.rows
                     if (r.seq_id in adapter._chunks
                         if r.kind == KIND_PREFILL
                         else r.seq_id in adapter.seqs)]


class RaggedBatchPlanner:
    """Assembles one :class:`RaggedPlan` per engine step from the paged
    adapter's live and pending state."""

    def __init__(self, adapter):
        self.adapter = adapter

    def plan(self, live: Sequence[int], target: Optional[Sequence[int]],
             token_room: Optional[Dict[int, int]],
             max_width: int) -> RaggedPlan:
        """``live``: decode-capable rows (already deadline-checked by the
        caller). ``target``: the step call's explicit seq_ids set (None =
        all) — governs whether an expired PENDING admission raises or is
        skipped. ``max_width``: speculative candidate cap (k+1; 1 =
        no speculation — plain decode rows)."""
        ad = self.adapter
        rows: List[RaggedRow] = []
        widths: Dict[int, int] = {}
        limit = ad._pos_limit
        for s in live:
            w = 1
            if max_width > 1:
                w = min(max_width, limit - ad.seqs[s].position)
                if token_room is not None and s in token_room:
                    w = min(w, token_room[s])
                w = max(1, int(w))
            widths[s] = w
            rows.append(RaggedRow(
                s, KIND_VERIFY if max_width > 1 else KIND_DECODE,
                ad.seqs[s].position, w,
                adapter_id=ad._lora_slots.get(s, -1)))
        self._plan_prefill(rows, target)
        return RaggedPlan(rows, widths)

    def _plan_prefill(self, rows: List[RaggedRow],
                      target: Optional[Sequence[int]]) -> None:
        """Append pending-admission chunk rows (admission order) under the
        ``prefill_budget_tokens`` per-step cap and the compiled-batch row
        cap, enforcing the same deadline semantics as the old standalone
        chunk dispatch."""
        ad = self.adapter
        chunks = ad._chunks
        if not chunks:
            return
        order = sorted(chunks, key=lambda s: chunks[s].admit_idx)
        now = time.perf_counter()
        expired = [s for s in order if chunks[s].deadline is not None
                   and now >= chunks[s].deadline]
        if expired:
            hit = (expired if target is None
                   else [s for s in expired if s in set(target)])
            if hit:
                fresh = [s for s in hit if not chunks[s].expired_reported]
                for s in fresh:
                    chunks[s].expired_reported = True
                ad.telemetry.on_deadline(
                    fresh, [_meta_tenant(chunks[s].meta) for s in fresh])
                raise _trace_error(DeadlineExceeded(
                    f"seq_ids {hit} exceeded their wall-clock deadline "
                    "mid-prefill; release() them (or re-queue with a "
                    "fresh budget) and step again", seq_ids=hit))
            order = [s for s in order if s not in expired]
        budget = ad.prefill_budget_tokens
        left = float("inf") if budget is None else int(budget)
        for s in order:
            if len(rows) == ad.batch or left < 1:
                break
            st = chunks[s]
            n = int(min(len(st.prompt) - st.done,
                        ad.prefill_chunk_tokens, left))
            rows.append(RaggedRow(s, KIND_PREFILL, st.done, n,
                                  final=st.done + n == len(st.prompt),
                                  adapter_id=ad._lora_slots.get(s, -1)))
            left -= n
