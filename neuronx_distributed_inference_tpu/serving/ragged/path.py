"""The ragged unified engine step (see the package docstring).

:class:`RaggedDispatchPath` owns one engine step of the ragged mode of a
:class:`~..adapter.PagedEngineAdapter`:

  1. the :class:`~.planner.RaggedBatchPlanner` lays out ALL runnable work
     — live decode rows (width 1), speculative verify windows (width
     k+1, clamped like the standalone spec path) and pending prefill
     chunks (width n at each row's own suffix offset) — as ragged rows
     of ONE dispatch, padded to the unified
     ``autobucketing.ragged_row_buckets`` ladder;
  2. per-row KV growth for the live rows' candidate windows (preemption-
     aware, exactly like the non-ragged grow);
  3. with speculation attached, the proposer's draft pass (device-
     resident tokens merged into the packed input on device — drafts
     never round-trip through the host);
  4. THE ragged dispatch (``model_base.paged_ragged_step``): in-graph
     per-row sampling for decode rows and final prefill chunks, in-graph
     acceptance for verify windows — greedy exact-match, or gumbel-
     coupled rejection sampling under seeded sampled decode (README
     "Sampled speculation & compressed decode") — nothing emitted for
     intermediate chunks and pad rows;
  5. the ONE blocking fetch of the step, then host bookkeeping: chunk
     cursors advance (final chunks graduate to running rows),
     ``_unwritten`` blocks covered by the now-materialized write chain
     are confirmed, accept cursors advance and KV shrinks to each verify
     row's accepted prefix.

Failure contract: the ``ragged_step`` fault point fires between growth
and the dispatch; any dispatch/fetch failure rolls EVERY packed row back
to its last accepted/delivered token — live rows' KV growth shrunk,
positions untouched, prefill rows aborted exactly like a failed chunk
dispatch (never-written blocks cannot poison the prefix cache) — and
raises a typed :class:`~...resilience.errors.StepFailure` with
``phase="ragged"``. The dispatch helper (``_dispatch_ragged``) must never
materialize device values — tier-1 lint region (the ``host-sync`` pass
of ``scripts/nxdi_lint.py``); the single blocking sync per step is
:meth:`RaggedDispatchPath._fetch_ragged`.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...modules import autobucketing
from ...modules.block_kv_cache import slots_from_table
from ...resilience.errors import (CapacityError, ConfigurationError,
                                  ServingError, StepFailure)
from ...resilience.faults import FAULTS as _FAULTS
from ...telemetry.request_trace import trace_of as _trace_of
from ...telemetry.trace import get_recorder as _get_recorder
from ..adapter import (_async_fetch, _common_tenant, _live_rows,
                       _meta_seed, _meta_tenant, _pre_step_checks,
                       _repeat_row0, _trace_error)
from ..speculation.verifier import validate_spec_sampling
from .planner import (KIND_DECODE, KIND_PREFILL, KIND_VERIFY,
                      RaggedBatchPlanner, RaggedPlan)

__all__ = ["RaggedDispatchPath"]

logger = logging.getLogger("nxdi_tpu")

_EMIT_NONE, _EMIT_LAST, _EMIT_VERIFY = 0, 1, 2


class RaggedDispatchPath:
    """One mixed prefill+decode+verify dispatch per engine step."""

    def __init__(self, adapter, proposer=None):
        cfg = adapter.app.tpu_config
        if adapter._pos_limit is None:
            raise ConfigurationError(
                "the ragged unified dispatch over rolling-window caches "
                "is not supported (row offsets need absolute positions)")
        self.mode = validate_spec_sampling(cfg.on_device_sampling_config,
                                           where="ragged unified dispatch")
        self.adapter = adapter
        self.planner = RaggedBatchPlanner(adapter)
        # ONE warm-shape ladder for every row kind (decode / verify /
        # prefill chunk) — replaces the separate ctx-slice chunk ladder
        # and spec-width ladder, so mixed load never pays a second
        # warm-shape set
        self.row_buckets = autobucketing.ragged_row_buckets(
            adapter.app.ctx_buckets, adapter.prefill_chunk_tokens)
        self.proposer = proposer
        self.spec_path = None
        self.max_width = 1
        if proposer is not None:
            # reuse the speculative path's validation, proposer binding
            # and draft-dispatch lint regions wholesale — only its verify
            # dispatch is replaced by the unified one
            from ..speculation.verifier import SpeculativeDecodePath
            self.spec_path = SpeculativeDecodePath(adapter, proposer)
            self.max_width = min(self.spec_path.max_width,
                                 self.row_buckets[-1])
        stats = adapter.host_stats
        for key in ("ragged_steps", "ragged_dispatches",
                    "ragged_rows_decode", "ragged_rows_prefill",
                    "ragged_rows_verify", "ragged_pad_rows",
                    "ragged_real_tokens", "ragged_padded_tokens"):
            stats.setdefault(key, 0)

    @property
    def wants_hidden(self) -> bool:
        return self.proposer is not None and self.proposer.wants_hidden

    # -- the ragged engine step --------------------------------------------
    def step(self, seq_ids: Optional[Sequence[int]] = None,
             token_room: Optional[Dict[int, int]] = None
             ) -> Dict[int, List[int]]:
        """ONE unified engine step: every runnable row — decode, verify,
        prefill chunk — rides a single materialized dispatch. Returns
        ``{seq_id: [tokens]}`` (1..k+1 tokens per decode/verify row;
        first tokens of prompts whose final chunk landed this step).
        ``token_room`` (scheduler hook) clamps a verify row's candidate
        width so a step never overshoots its remaining token budget."""
        ad = self.adapter
        if ad._inflight is not None:
            ad._stash_flush()          # retire a pre-ragged pipelined step
        pending = ad._pending_ids()
        live = _live_rows(ad.seqs, seq_ids, pending)

        def drain() -> Dict[int, List[int]]:
            return {s: [t] for s, t in ad._drain_ready().items()}

        if not live and not pending:
            return drain()
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        if live:
            _pre_step_checks(ad.seqs, live, ad._pos_limit, ad.telemetry,
                             horizon=1)
        t0 = time.perf_counter()
        # degradation shed: verify windows clamp to width 1 (decode-kind
        # rows, no draft dispatch) — tokens unchanged in both modes
        # (greedy argmax trivially; coupled sampling because the
        # position-keyed draws are path-invariant)
        max_width = 1 if ad._spec_shed else self.max_width
        plan = self.planner.plan(live, seq_ids, token_room, max_width)
        if plan.live_ids:
            self._grow_plan(plan)
            plan.prune(ad)             # rows preempted mid-grow drop out
        if not plan.rows:
            return drain()
        # _ready (graduated first tokens) is drained only after the
        # fallible stages: a StepFailure mid-dispatch leaves them
        # deliverable by the next returning call instead of dropping them
        res = self._execute_plan(plan, t0)
        out = drain()
        for s, row in res.items():
            out.setdefault(s, []).extend(row)
        return out

    # -- internals ---------------------------------------------------------
    def _grow_plan(self, plan: RaggedPlan) -> None:
        """Grow every live row's block list to cover its candidate
        window, evicting victims per the adapter's preemption policy when
        the pool runs dry (rows preempted mid-grow leave the plan via
        :meth:`RaggedPlan.prune`). On an unevictable CapacityError all
        growth from this call is rolled back before the raise."""
        ad = self.adapter
        mgr = ad.app.kv_mgr
        widths = plan.widths
        queue = [s for s in plan.live_ids]
        grown: List[int] = []
        while queue:
            s = queue[0]
            if s not in ad.seqs:       # preempted by an earlier eviction
                queue.pop(0)
                continue
            try:
                mgr.grow(s, widths[s])
            except CapacityError:
                victim = ad._choose_victim()
                if victim is None:
                    for g in grown:
                        mgr.shrink(g, widths[g])
                    raise
                ad._preempt(victim, reason="grow")
                for lst in (queue, grown):
                    if victim in lst:
                        lst.remove(victim)
                continue
            queue.pop(0)
            grown.append(s)

    def _rollback_live(self, plan: RaggedPlan) -> None:
        """Shrink every live row's candidate-window growth back to its
        last accepted/delivered token (positions untouched — a retry
        continues the exact stream)."""
        ad = self.adapter
        for s in plan.live_ids:
            if s in ad.seqs and s in ad.app.kv_mgr.tables:
                ad.app.kv_mgr.shrink(s, plan.widths[s])

    def _rollback_plan(self, plan: RaggedPlan) -> None:
        """Dispatch-failure rollback: live rows shrink to their last
        accepted/delivered token, and every prefill row packed in the
        failed dispatch — its KV writes are suspect — is evicted as a
        PREEMPTION (reverse admission order — never-written blocks leave
        the prefix cache, and the :class:`Preempted` record lets the
        scheduler replay the admission instead of losing the request)."""
        self._rollback_live(plan)
        ad = self.adapter
        for s in reversed(plan.prefill_ids):
            if s in ad._chunks:
                ad._preempt(s, reason="ragged_rollback")

    def _draft(self, plan: RaggedPlan, live_rows) -> Tuple[Any, int, Any]:
        """Run the proposer's draft pass over the live (verify) rows
        through the speculative path's shared preamble
        (:meth:`~..speculation.verifier.SpeculativeDecodePath.run_draft`).
        Returns (drafts device array or None, bucketed spec width, ctx).
        A draft failure rolls back ONLY the live rows' window growth —
        the packed prefill rows saw no device work yet, so their pending
        state stays; a sat-out proposer releases the unused window."""
        import jax.numpy as jnp
        app = self.adapter.app
        live = [r.seq_id for r in live_rows]
        drafts, W, ctx = self.spec_path.run_draft(
            live, plan.widths, lambda: self._rollback_live(plan))
        if drafts is None and W > 1:
            # the proposer sat this step out: release the unused window
            for r in live_rows:
                if r.width > 1:
                    app.kv_mgr.shrink(r.seq_id, r.width - 1)
                    r.width = 1
                    plan.widths[r.seq_id] = 1
            W = 1
            ctx.num_drafts = 0
            ctx.widths = np.ones_like(ctx.widths)
        if drafts is not None:
            drafts = jnp.asarray(drafts)
        return drafts, W, ctx

    def _execute_plan(self, plan: RaggedPlan,
                      t0: float) -> Dict[int, List[int]]:
        import jax.numpy as jnp
        ad = self.adapter
        app = ad.app
        chunks = ad._chunks
        rows = plan.rows
        live_rows = [(i, r) for i, r in enumerate(rows)
                     if r.kind != KIND_PREFILL]
        # draft BEFORE packing: verify widths may degrade to 1 when the
        # proposer sits the step out. The ctx is built even for a fully
        # clamped (width-1) batch so feature-feeding proposers
        # (Medusa/EAGLE) keep seeding from the verify hidden states,
        # exactly like the standalone speculative path
        drafts, spec_W, ctx = (None, 1, None)
        if self.spec_path is not None and live_rows:
            drafts, spec_W, ctx = self._draft(plan,
                                              [r for _, r in live_rows])
        prefill_rows = [(i, r) for i, r in enumerate(rows)
                        if r.kind == KIND_PREFILL]
        b = len(rows)
        W = autobucketing.get_target_bucket(
            self.row_buckets, max(r.width for r in rows), kind="ragged")
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        sids = [r.seq_id for r in rows]
        bs = app.kv_mgr.spec.block_size
        bt = app.kv_mgr.block_table_array(sids, app._bt_width_for(sids))
        ids = np.zeros((b, W), np.int32)
        pos = np.zeros((b, W), np.int32)
        slot_pos = np.full((b, W), -1, np.int32)
        wid = np.zeros((b,), np.int32)
        emit = np.zeros((b,), np.int32)
        cols = np.arange(W, dtype=np.int32)
        for i, r in enumerate(rows):
            wid[i] = r.width
            pos[i] = r.offset + cols
            slot_pos[i, :r.width] = pos[i, :r.width]
            if r.kind == KIND_PREFILL:
                st = chunks[r.seq_id]
                ids[i, :r.width] = st.prompt[r.offset:r.offset + r.width]
                emit[i] = _EMIT_LAST if r.final else _EMIT_NONE
            else:
                ids[i, 0] = ad.seqs[r.seq_id].last_token
                emit[i] = (_EMIT_VERIFY if r.kind == KIND_VERIFY
                           else _EMIT_LAST)
        slots = slots_from_table(bt, slot_pos, bs)
        seeds = np.asarray(
            [_meta_seed(ad.seqs[r.seq_id].meta if r.seq_id in ad.seqs
                        else chunks[r.seq_id].meta) for r in rows],
            np.int32)
        # per-row LoRA slots ride the plan (RaggedRow.adapter_id, pinned
        # at admission): ONE dispatch mixes rows from different adapters;
        # -1 (base model) clamps to slot 0, the zero adapter. None
        # without a pool — the kwarg is never passed, so no-pool graphs
        # stay byte-identical
        aids = None
        if ad._lora_pool is not None:
            aids = np.asarray([max(r.adapter_id, 0) for r in rows],
                              np.int32)
        if pad_to > b:
            ids, pos, slots, bt, wid, emit, seeds = (
                _repeat_row0(x, pad_to)
                for x in (ids, pos, slots, bt, wid, emit, seeds))
            if aids is not None:
                aids = _repeat_row0(aids, pad_to)
        ids_dev = jnp.asarray(ids)
        if drafts is not None and spec_W > 1:
            # merge the device-resident drafts into the packed input —
            # verify rows are the plan's live prefix, candidates never
            # round-trip through the host
            n_live = len(live_rows)
            ids_dev = ids_dev.at[:n_live, 1:spec_W].set(
                drafts[:n_live, :spec_W - 1])
            if pad_to > b:
                # batch-pad rows are clones of row 0 (a verify row when
                # any live row exists) and share its slot mapping — they
                # must carry row 0's DRAFTS too, or their duplicate KV
                # writes would race row 0's with different values
                ids_dev = ids_dev.at[b:, 1:spec_W].set(
                    drafts[0, :spec_W - 1][None])
        if ctx is not None:
            # ctx.cand must honor the spec-context row contract (live
            # rows then ROW-0 CLONES): the ragged grid's rows past the
            # live prefix are prefill/pad rows, so re-pad by gather —
            # EAGLE's draft-cache refresh scatters cand at row-0-cloned
            # positions and duplicate writes must stay value-identical
            n_live = len(live_rows)
            gather = np.concatenate(
                [np.arange(n_live, dtype=np.intp),
                 np.zeros(ctx.padded_batch - n_live, dtype=np.intp)])
            ctx.cand = ids_dev[jnp.asarray(gather), :spec_W]
        # per-tenant failure attribution covers EVERY packed row —
        # pending prefill rows carry their meta in the chunk state
        tenant = _common_tenant(
            [_meta_tenant(ad.seqs[s].meta) for s in sids if s in ad.seqs]
            + [_meta_tenant(chunks[s].meta) for s in sids if s in chunks])
        cache_before = app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("ragged_step")
            out = self._dispatch_ragged(ids_dev, pos, slots, bt, wid,
                                        emit, seeds, rows, aids)
            toks, n_emit = self._fetch_ragged(out, b)
        except ServingError as e:
            self._rollback_plan(plan)
            _trace_error(e)
            raise
        except Exception as e:
            self._rollback_plan(plan)
            ad.telemetry.on_step_failure("ragged", tenant)
            raise _trace_error(StepFailure(
                "ragged unified dispatch failed; every packed row was "
                "rolled back to its last accepted/delivered token",
                phase="ragged", seq_ids=tuple(sids),
                retry_safe=app.cache is cache_before)) from e
        return self._accept(plan, live_rows, prefill_rows, toks, n_emit,
                            out, ctx, spec_W, t0, b, W, pad_to)

    def _accept(self, plan, live_rows, prefill_rows, toks, n_emit, out,
                ctx, spec_W, t0, b, W, pad_to) -> Dict[int, List[int]]:
        """Post-fetch host bookkeeping (the dispatch is materialized)."""
        import jax.numpy as jnp
        ad = self.adapter
        app = ad.app
        chunks = ad._chunks
        bs = app.kv_mgr.spec.block_size
        # 1. chunk cursors advance; the fetch above materialized the
        # dispatch, so every block the donated-cache chain covers up to
        # each pending row's cursor is now confirmed written
        for _, r in prefill_rows:
            chunks[r.seq_id].done += r.width
        for s2, cst in chunks.items():
            ad._unwritten.difference_update(
                app.kv_mgr.tables[s2][:cst.done // bs])
        # 2. final chunks graduate to running rows
        from ..adapter import _SeqState, _meta_tenant
        for i, r in prefill_rows:
            if not r.final:
                continue
            st = chunks.pop(r.seq_id)
            ad._unwritten.difference_update(app.kv_mgr.tables[r.seq_id])
            tok = int(toks[i, 0])
            ad.seqs[r.seq_id] = _SeqState(
                position=len(st.prompt), last_token=tok,
                tokens=list(st.prompt) + [tok],
                prompt_len=len(st.prompt), admit_idx=st.admit_idx,
                deadline=st.deadline, meta=st.meta)
            ad._scratch = None         # live set grew
            ad._ready[r.seq_id] = tok
            ad.telemetry.on_add([r.seq_id], [st.prompt], st.t0, live=1,
                                padded=1, count_rows=False,
                                tenants=[_meta_tenant(st.meta)])
        # 3. live rows: accept cursors advance, KV shrinks to the
        # accepted prefix
        res: Dict[int, List[int]] = {}
        drafted = accepted = 0
        spec_rows = []
        for i, r in live_rows:
            st = ad.seqs[r.seq_id]
            n = int(n_emit[i])
            row = [int(t) for t in toks[i, :n]]
            st.position += n
            for t in row:
                ad._append_token(st, t)
            if r.width > n:
                app.kv_mgr.shrink(r.seq_id, r.width - n)
            res[r.seq_id] = row
            drafted += r.width - 1
            accepted += n - 1
            spec_rows.append((r.seq_id, n))
        # 4. telemetry + always-on host counters
        stats = ad.host_stats
        n_decode = sum(1 for _, r in live_rows if r.kind == KIND_DECODE)
        n_verify = len(live_rows) - n_decode
        real = sum(r.width for r in plan.rows)
        stats["ragged_steps"] += 1
        stats["ragged_rows_decode"] += n_decode
        stats["ragged_rows_verify"] += n_verify
        stats["ragged_rows_prefill"] += len(prefill_rows)
        stats["ragged_pad_rows"] += pad_to - b
        stats["ragged_real_tokens"] += real
        stats["ragged_padded_tokens"] += pad_to * W
        if prefill_rows:
            pre_real = sum(r.width for _, r in prefill_rows)
            stats["prefill_real_tokens"] += pre_real
            stats["prefill_padded_tokens"] += len(prefill_rows) * W
            ad.telemetry.on_prefill_chunk(len(prefill_rows),
                                          len(prefill_rows), pre_real,
                                          len(prefill_rows) * W)
        ad.telemetry.on_ragged_step(
            {KIND_DECODE: n_decode, KIND_VERIFY: n_verify,
             KIND_PREFILL: len(prefill_rows), "pad": pad_to - b},
            real, pad_to * W)
        if self.spec_path is not None and spec_rows:
            stats["spec_steps"] += 1
            stats["spec_drafted_tokens"] += drafted
            stats["spec_accepted_tokens"] += accepted
            ad.telemetry.on_spec_step(spec_rows, t0, padded=pad_to,
                                      width=spec_W, drafted=drafted,
                                      accepted=accepted, mode=self.mode)
        elif spec_rows:
            ad.telemetry.on_step([s for s, _ in spec_rows], t0,
                                 padded=pad_to)
        # 5. proposer feedback (Medusa/EAGLE): hand back the ctx-shaped
        # slice of the unified dispatch's outputs — ctx pad rows must be
        # row-0 clones, so the live prefix is re-padded by gather
        if ctx is not None:
            n_live = len(live_rows)
            hidden = None
            if self.wants_hidden:
                gather = np.concatenate(
                    [np.arange(n_live, dtype=np.intp),
                     np.zeros(ctx.padded_batch - n_live, dtype=np.intp)])
                hidden = out["hidden"][jnp.asarray(gather), :spec_W, :]
            try:
                self.proposer.on_verify(ctx, toks[:n_live, :spec_W],
                                        n_emit[:n_live], hidden)
            except Exception:
                # the step's tokens are already accepted and delivered —
                # a broken proposer must only cost acceptance rate, never
                # the output stream
                logger.warning(
                    "speculative proposer %r failed in on_verify; its "
                    "per-sequence state was dropped (seq_ids=%s)",
                    self.proposer.name, list(ctx.live), exc_info=True)
                self.proposer.forget(ctx.live)
        return res

    # -- dispatch region (nxdi_lint host-sync pass) ------------------------
    def _dispatch_ragged(self, ids_dev, pos, slots, bt, wid, emit, seeds,
                         rows, aids=None):
        """Issue THE unified dispatch (one per engine step) without
        materializing any output; the async copies are started so the
        fetch one call later is cheap."""
        ad = self.adapter
        kw = {"want_hidden": self.wants_hidden, "row_seeds": seeds}
        if aids is not None:
            kw["adapter_ids"] = aids
        if ad.app._steady_state:
            # steady-state compile discipline (serving/warmup.py): carry
            # the packed rows' request trace ids so an unexpected
            # recompile is attributed to its victims' trace lanes
            with ad.app.request_context(
                    self._row_trace(r.seq_id) for r in rows):
                out = ad.app._run_ragged(ids_dev, pos, slots, bt, wid,
                                         emit, **kw)
        else:
            out = ad.app._run_ragged(ids_dev, pos, slots, bt, wid, emit,
                                     **kw)
        _async_fetch(out["tokens"])
        _async_fetch(out["num_emitted"])
        ad.host_stats["dispatches"] += 1
        ad.host_stats["ragged_dispatches"] += 1
        ad.host_stats["device_steps"] += 1
        rec = _get_recorder()
        if rec.enabled:
            rec.instant("dispatch.ragged", cat="adapter",
                        engine=ad.engine_name, rows=len(rows),
                        pad_to=int(wid.shape[0]),
                        width=int(ids_dev.shape[1]),
                        kinds={r.kind: sum(1 for x in rows
                                           if x.kind == r.kind)
                               for r in rows},
                        seq_ids=[int(r.seq_id) for r in rows],
                        # per-row request trace ids (aligned with
                        # seq_ids), so a request's trace lane shows
                        # every ragged dispatch it occupied a row of
                        traces=[self._row_trace(r.seq_id) for r in rows])
        return out

    def _row_trace(self, seq_id: int):
        """The request trace id behind one packed row — live rows carry
        meta on their _SeqState, pending prefill rows on their chunk
        state. Recorder-enabled path only (never called while tracing
        is off)."""
        ad = self.adapter
        st = ad.seqs.get(seq_id)
        meta = st.meta if st is not None else getattr(
            ad._chunks.get(seq_id), "meta", None)
        return _trace_of(meta)

    def _fetch_ragged(self, out, b: int):
        """The ONE blocking sync of a ragged engine step."""
        ad = self.adapter
        t0 = time.perf_counter()
        toks = np.asarray(out["tokens"])[:b]
        n_emit = np.asarray(out["num_emitted"])[:b]
        t1 = time.perf_counter()
        ad.host_stats["blocking_fetches"] += 1
        ad.host_stats["blocked_s"] += t1 - t0
        rec = _get_recorder()
        if rec.enabled:
            rec.complete("fetch.tokens", t0, cat="adapter", t1=t1,
                         engine=ad.engine_name, rows=b, phase="ragged")
        return toks, n_emit
