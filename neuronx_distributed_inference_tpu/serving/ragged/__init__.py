"""Ragged unified dispatch: ONE mixed prefill+decode+verify dispatch per
engine step (ROADMAP item 1; README "Ragged dispatch"; reference shape:
"Ragged Paged Attention", arxiv 2604.15464).

The two-phase schedule this subsystem replaces ran each engine step as
"at most one packed prefill-chunk dispatch, then one decode/verify
dispatch", serialized by the ``prefill_budget_tokens`` point and warmed
over three separate bucket ladders (ctx, prefill-chunk, spec-width).
Ragged mode collapses that: a :class:`~.planner.RaggedBatchPlanner`
assembles ALL runnable work — pending prefill chunks, live decode rows,
speculative verify windows — into one row plan (per-row token offsets,
widths, block tables and kind tags), and a
:class:`~.path.RaggedDispatchPath` executes it as ONE
``model_base.paged_ragged_step`` dispatch over the existing
slot-mapping/block-table graph, padded within the unified
``autobucketing.ragged_row_buckets`` ladder.

Enable with ``PagedEngineAdapter(app, ragged=True)`` (composes with
``speculation=``); ``ServingEngine.run_pass`` routes through the planner
automatically. Every existing contract rides along: transactional
rollback (``ragged_step`` fault point), chunked-prefill ``_unwritten``
block confirmation, preemption/replay, deadlines, token budgets, and the
speculation accept-rate pins — see the path module docstring and
tests/test_ragged_dispatch.py for the pinned guarantees.
"""

from .planner import (KIND_DECODE, KIND_PREFILL, KIND_VERIFY,
                      RaggedBatchPlanner, RaggedPlan, RaggedRow)
from .path import RaggedDispatchPath

__all__ = ["RaggedBatchPlanner", "RaggedDispatchPath", "RaggedPlan",
           "RaggedRow", "KIND_DECODE", "KIND_PREFILL", "KIND_VERIFY"]
