"""Device mesh construction — the TPU-native replacement for the reference's
process-group zoo (reference: neuronx_distributed ``parallel_state``
``initialize_model_parallel`` and
src/neuronx_distributed_inference/modules/attention/attention_process_groups.py).

Instead of materializing TP/CP/DP/EP process groups, we build ONE
``jax.sharding.Mesh`` with named axes and express each parallelism strategy as
a PartitionSpec over those axes:

  axis "dp" — attention data parallel (decode batch sharding,
              reference: attention_process_groups.py:125-163)
  axis "cp" — context parallel (prefill sequence sharding,
              reference: attention_process_groups.py:81-123)
  axis "tp" — tensor parallel (heads / hidden sharding)
  axis "ep" — expert parallel (MoE expert sharding, reference: modules/moe_v2.py:135-161)

The reference's phase asymmetry (CP groups for prefill, DP groups for decode
over the SAME ranks — attention_base.py:183-199) maps here to *reusing* the
``cp`` axis: during prefill activations shard sequence over ("dp","cp"), during
decode the batch shards over ("dp","cp"). The mesh itself never changes, only
the PartitionSpecs, so no KV-head reshuffling between phases is required when
layouts are chosen consistently.

Multi-host: ``jax.distributed.initialize`` over DCN replaces the reference's
MPI + NEURON_RT_ROOT_COMM_ID bootstrap
(reference: scripts/nxdi_distributed_launcher.py:29-85).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("nxdi_tpu")

# Canonical axis order: outermost (slowest-varying, DCN-friendly) first.
AXIS_DP = "dp"
AXIS_CP = "cp"
AXIS_TP = "tp"
AXIS_EP = "ep"
MESH_AXES = (AXIS_DP, AXIS_CP, AXIS_TP, AXIS_EP)


@dataclass(frozen=True)
class MeshConfig:
    tp: int = 1
    cp: int = 1
    dp: int = 1
    ep: int = 1

    @property
    def world_size(self) -> int:
        # cp and dp shard the tp device set during different phases; ep reuses
        # tp devices for MoE. The physical world is dp*cp*tp with ep folded
        # into tp (moe_tp x moe_ep = tp, reference: modules/moe_v2.py:135-161).
        return self.dp * self.cp * self.tp


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap over DCN. Safe no-op for single-process runs.

    Replaces the reference's MPI launcher + gloo host barrier
    (reference: inference_demo.py:788-796, scripts/nxdi_distributed_launcher.py).
    """
    if num_processes is None:
        num_processes = int(os.environ.get("NXDI_TPU_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def build_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the (dp, cp, tp, ep) mesh.

    ep=1 devices-wise: expert parallelism reuses tp-axis devices via a derived
    mesh (see :func:`moe_mesh_axes`); only dp*cp*tp physical devices are laid
    out here. Device order follows jax.devices() which is ICI-contiguous —
    tp innermost so tp collectives ride the fastest links.
    """
    if devices is None:
        devices = jax.devices()
    n = cfg.dp * cfg.cp * cfg.tp
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices (dp={cfg.dp} cp={cfg.cp} "
                         f"tp={cfg.tp}), only {len(devices)} available")
    dev_array = np.array(devices[:n]).reshape(cfg.dp, cfg.cp, cfg.tp, 1)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshConfig())


def mesh_from_config(tpu_config) -> Mesh:
    """Build mesh from a TpuConfig's parallelism degrees."""
    # attention-DP and CP both subdivide the tp rank set in the reference
    # (tp_degree counts ALL ranks; cp/dp are groupings of them:
    # attention_process_groups.py:36-163). Here tp axis = tp/(cp*dp), so the
    # physical world stays tp_degree devices.
    cp = max(tpu_config.cp_degree, 1)
    dp = max(tpu_config.attention_dp_degree, 1)
    shrink = cp * dp
    if tpu_config.tp_degree % shrink != 0:
        raise ValueError(f"tp_degree {tpu_config.tp_degree} not divisible by "
                         f"cp_degree*attention_dp_degree = {shrink}")
    return build_mesh(MeshConfig(tp=tpu_config.tp_degree // shrink, cp=cp, dp=dp,
                                 ep=max(tpu_config.ep_degree, 1)))


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------

def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_spec(mesh: Mesh) -> P:
    """Decode-phase batch sharding over dp (and cp when cp>1 is repurposed,
    reference: DataParallelKVCacheManager)."""
    axes = [a for a, s in zip(mesh.axis_names, mesh.devices.shape) if s > 1
            and a in (AXIS_DP, AXIS_CP)]
    return P(tuple(axes) if axes else None)


def logical_to_physical(rules: dict, logical_axes: Tuple[Optional[str], ...]) -> P:
    """Map logical axis names (e.g. ("batch", "seq", "hidden")) to mesh axes."""
    return P(*[rules.get(a) if a is not None else None for a in logical_axes])


# Default logical->mesh rules for decoder LLMs.
DEFAULT_RULES = {
    "batch": AXIS_DP,
    "seq": None,            # sequence sharded only under SP/CP via explicit specs
    "hidden": None,
    "heads": AXIS_TP,
    "kv_heads": AXIS_TP,
    "mlp": AXIS_TP,
    "vocab": AXIS_TP,
    "expert": AXIS_EP,
    "layer": None,
}
