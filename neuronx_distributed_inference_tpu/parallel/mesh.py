"""Device mesh construction — the TPU-native replacement for the reference's
process-group zoo (reference: neuronx_distributed ``parallel_state``
``initialize_model_parallel`` and
src/neuronx_distributed_inference/modules/attention/attention_process_groups.py).

Instead of materializing TP/CP/DP/EP process groups, we build ONE
``jax.sharding.Mesh`` with named axes and express each parallelism strategy as
a PartitionSpec over those axes:

  axis "dp" — attention data parallel (decode batch sharding,
              reference: attention_process_groups.py:125-163)
  axis "cp" — context parallel (prefill sequence sharding,
              reference: attention_process_groups.py:81-123)
  axis "tp" — tensor parallel (heads / hidden sharding)
  axis "ep" — expert parallel (MoE expert sharding, reference: modules/moe_v2.py:135-161)

The reference's phase asymmetry (CP groups for prefill, DP groups for decode
over the SAME ranks — attention_base.py:183-199) maps here to *reusing* the
``cp`` axis: during prefill activations shard sequence over ("dp","cp"), during
decode the batch shards over ("dp","cp"). The mesh itself never changes, only
the PartitionSpecs, so no KV-head reshuffling between phases is required when
layouts are chosen consistently.

Multi-host: ``jax.distributed.initialize`` over DCN replaces the reference's
MPI + NEURON_RT_ROOT_COMM_ID bootstrap
(reference: scripts/nxdi_distributed_launcher.py:29-85).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("nxdi_tpu")

# Canonical axis order: outermost (slowest-varying, DCN-friendly) first.
# ep sits OUTSIDE tp: the reference's moe_tp_degree x moe_ep_degree factors the
# tp rank set (modules/moe_v2.py:135-161); here "model parallel" dims (heads,
# mlp intermediate, vocab) shard over the COMBINED ("ep","tp") axes while MoE
# expert weights shard experts over "ep" and intermediate over "tp".
AXIS_DP = "dp"
AXIS_CP = "cp"
AXIS_TP = "tp"
AXIS_EP = "ep"
MESH_AXES = (AXIS_DP, AXIS_CP, AXIS_EP, AXIS_TP)

# Composite model-parallel spec entry: full tp_degree sharding of a dim.
AXIS_MP = (AXIS_EP, AXIS_TP)


@dataclass(frozen=True)
class MeshConfig:
    tp: int = 1
    cp: int = 1
    dp: int = 1
    ep: int = 1

    @property
    def world_size(self) -> int:
        # cp/dp/ep all subdivide the model-parallel rank set during different
        # phases/blocks; the physical world is dp*cp*ep*tp
        # (moe_tp x moe_ep = tp, reference: modules/moe_v2.py:135-161).
        return self.dp * self.cp * self.ep * self.tp


@dataclass(frozen=True)
class Topology:
    """Physical placement of the mesh axes: which axes cross the DCN boundary.

    A single slice rides ICI end to end. Scaling out — the 70B-on-v5e-32
    shape is dp4(x)tp8 over four 8-chip hosts — puts the OUTERMOST mesh axes
    on the data-center network, which is ~an order of magnitude slower than
    ICI (priced by the observatory at ``NXDI_TPU_DCN_GBPS`` vs
    ``NXDI_TPU_ICI_GBPS``). MESH_AXES is ordered outermost-first exactly so
    the dp axis is the one that can leave the slice: dp traffic is
    whole-replica independent during decode (no per-step all-reduce), so it
    tolerates DCN latency where tp cannot.
    """

    dcn_axes: Tuple[str, ...] = ()

    def is_dcn(self, comm_axes) -> bool:
        """True when a collective over ``comm_axes`` crosses the DCN."""
        return any(a in self.dcn_axes for a in comm_axes)


#: single-slice default — every axis on ICI
SINGLE_SLICE = Topology()
#: the scale-out shape: dp crosses the DCN boundary, tp/ep/cp stay on ICI
DP_OVER_DCN = Topology(dcn_axes=(AXIS_DP,))


def topology_from_env() -> Topology:
    """Resolve the deployment topology from ``NXDI_TPU_DCN_AXES`` (comma
    separated mesh axis names; default "dp" — the conservative pricing:
    anything dp-attributed is assumed to cross the DCN)."""
    raw = os.environ.get("NXDI_TPU_DCN_AXES", AXIS_DP)
    axes = tuple(a for a in (s.strip() for s in raw.split(",")) if a)
    bad = [a for a in axes if a not in MESH_AXES]
    if bad:
        raise ValueError(f"NXDI_TPU_DCN_AXES names unknown mesh axes {bad}; "
                         f"expected a subset of {MESH_AXES}")
    return Topology(dcn_axes=axes)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap over DCN. Safe no-op for single-process runs.

    Replaces the reference's MPI launcher + gloo host barrier
    (reference: inference_demo.py:788-796, scripts/nxdi_distributed_launcher.py).
    """
    if num_processes is None:
        num_processes = int(os.environ.get("NXDI_TPU_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def build_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the (dp, cp, ep, tp) mesh.

    Device order follows jax.devices() which is ICI-contiguous — tp innermost
    so tp collectives ride the fastest links; ep just outside so MoE expert
    dispatch stays intra-slice.
    """
    if devices is None:
        devices = jax.devices()
    n = cfg.dp * cfg.cp * cfg.ep * cfg.tp
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices (dp={cfg.dp} cp={cfg.cp} "
                         f"ep={cfg.ep} tp={cfg.tp}), only {len(devices)} available")
    dev_array = np.array(devices[:n]).reshape(cfg.dp, cfg.cp, cfg.ep, cfg.tp)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshConfig())


def mesh_from_config(tpu_config) -> Mesh:
    """Build mesh from a TpuConfig's parallelism degrees."""
    # attention-DP / CP / EP all subdivide the tp rank set in the reference
    # (tp_degree counts ALL ranks; cp/dp/ep are groupings of them:
    # attention_process_groups.py:36-163, moe_v2.py:135-161). Here tp axis =
    # tp/(cp*dp*ep), so the physical world stays tp_degree devices.
    cp = max(tpu_config.cp_degree, 1)
    dp = max(tpu_config.attention_dp_degree, 1)
    ep = max(tpu_config.ep_degree, 1)
    shrink = cp * dp * ep
    if tpu_config.tp_degree % shrink != 0:
        raise ValueError(f"tp_degree {tpu_config.tp_degree} not divisible by "
                         f"cp*dp*ep = {shrink}")
    return build_mesh(MeshConfig(tp=tpu_config.tp_degree // shrink, cp=cp, dp=dp,
                                 ep=ep))


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------

def shard_constraint(x, *spec):
    """``with_sharding_constraint`` that no-ops outside a mesh context —
    the shared helper for model code (traced under jit with a mesh active;
    plain-eager tests run without one)."""
    try:
        return jax.lax.with_sharding_constraint(x, P(*spec))
    except (ValueError, RuntimeError):
        return x


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_spec(mesh: Mesh) -> P:
    """Decode-phase batch sharding over dp (and cp when cp>1 is repurposed,
    reference: DataParallelKVCacheManager)."""
    axes = [a for a, s in zip(mesh.axis_names, mesh.devices.shape) if s > 1
            and a in (AXIS_DP, AXIS_CP)]
    return P(tuple(axes) if axes else None)


def logical_to_physical(rules: dict, logical_axes: Tuple[Optional[str], ...]) -> P:
    """Map logical axis names (e.g. ("batch", "seq", "hidden")) to mesh axes."""
    return P(*[rules.get(a) if a is not None else None for a in logical_axes])


# Default logical->mesh rules for decoder LLMs.
DEFAULT_RULES = {
    "batch": AXIS_DP,
    "seq": None,            # sequence sharded only under SP/CP via explicit specs
    "hidden": None,
    "heads": AXIS_MP,
    "kv_heads": AXIS_MP,
    "mlp": AXIS_MP,
    "vocab": AXIS_MP,
    "expert": AXIS_EP,
    "expert_mlp": AXIS_TP,  # intermediate dim inside an expert (moe_tp)
    "layer": None,
}
