"""Sharded layer building blocks — TPU-native replacement for the reference's
NxD parallel layers (reference: neuronx_distributed ``parallel_layers``
ColumnParallelLinear / RowParallelLinear / ParallelEmbedding and the GQA
sharding utilities in
src/neuronx_distributed_inference/modules/attention/gqa.py).

Design: under GSPMD there is no "parallel linear module" — a linear layer is a
weight with a PartitionSpec plus a plain ``jnp.einsum``; XLA inserts the
collectives (all-reduce for row-parallel, etc.). What remains of the
reference's parallel-layer machinery is:

  * declaring weight layouts (column vs row sharding)           -> ParamSpec
  * GQA head padding / replication so kv-heads divide tp
    (reference: gqa.py:32-244)                                  -> here
  * checkpoint-time resharding hooks (reference: gqa.py:679+)   -> shape
    transforms applied by utils/checkpoint.py using these specs
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_EP, AXIS_MP, AXIS_TP


@dataclass(frozen=True)
class ParamSpec:
    """Shape + sharding declaration for one weight tensor."""

    shape: Tuple[int, ...]
    pspec: P
    dtype: jnp.dtype = jnp.bfloat16
    # how to initialize for random-weight tests; loaded checkpoints override
    init: str = "normal"   # "normal" | "zeros" | "ones"

    def initializer(self, key, scale: float = 0.02):
        if self.init == "zeros":
            return jnp.zeros(self.shape, self.dtype)
        if self.init == "ones":
            return jnp.ones(self.shape, self.dtype)
        return (jax.random.normal(key, self.shape, jnp.float32) * scale).astype(self.dtype)


def column_parallel(in_dim: int, out_dim: int, dtype=jnp.bfloat16,
                    layer_stacked: bool = False, num_layers: int = 0) -> ParamSpec:
    """Weight (in, out) with the OUTPUT dim sharded on the full model-parallel
    axis set ("ep","tp") — the analog of ColumnParallelLinear
    (gather_output=False)."""
    if layer_stacked:
        return ParamSpec((num_layers, in_dim, out_dim), P(None, None, AXIS_MP), dtype)
    return ParamSpec((in_dim, out_dim), P(None, AXIS_MP), dtype)


def row_parallel(in_dim: int, out_dim: int, dtype=jnp.bfloat16,
                 layer_stacked: bool = False, num_layers: int = 0) -> ParamSpec:
    """Weight (in, out) with the INPUT dim sharded on ("ep","tp") — the analog
    of RowParallelLinear (input_is_parallel=True); XLA emits the all-reduce."""
    if layer_stacked:
        return ParamSpec((num_layers, in_dim, out_dim), P(None, AXIS_MP, None), dtype)
    return ParamSpec((in_dim, out_dim), P(AXIS_MP, None), dtype)


def row_parallel_output(x, w, *, collective_dtype: Optional[str] = None,
                        collective_block: int = 32):
    """Compute a row-parallel layer's output: ``x`` (B, T, K) with K sharded
    over ("ep","tp"), ``w`` (K, N) per :func:`row_parallel`.

    With ``collective_dtype`` None this is the classic GSPMD form — a plain
    (q)linear whose all-reduce XLA inserts from the sharding constraints.
    With "int8"/"fp8" the reduction is EXPLICIT: a shard_map ring exchange
    with a quantized wire payload (parallel/collectives.py, EQuARX-style).
    """
    if collective_dtype is None:
        from ..modules.quantization import qlinear
        return qlinear(x, w)
    from . import collectives
    return collectives.quantized_row_parallel(
        x, w, dtype=collective_dtype, block=collective_block)


def vocab_parallel_embedding(vocab: int, hidden: int, dtype=jnp.bfloat16) -> ParamSpec:
    """Embedding (V, H) sharded on V (reference: ParallelEmbedding with
    vocab_parallel, models/config.py:142)."""
    return ParamSpec((vocab, hidden), P(AXIS_MP, None), dtype)


def expert_column_parallel(num_experts: int, in_dim: int, out_dim: int,
                           dtype=jnp.bfloat16, layer_stacked: bool = False,
                           num_layers: int = 0) -> ParamSpec:
    """Expert weight (E, in, out): experts sharded on "ep" (moe_ep), the
    output dim on "tp" (moe_tp) — reference: modules/moe_v2.py:135-161
    moe_tp_degree x moe_ep_degree expert sharding."""
    if layer_stacked:
        return ParamSpec((num_layers, num_experts, in_dim, out_dim),
                         P(None, AXIS_EP, None, AXIS_TP), dtype)
    return ParamSpec((num_experts, in_dim, out_dim),
                     P(AXIS_EP, None, AXIS_TP), dtype)


def expert_row_parallel(num_experts: int, in_dim: int, out_dim: int,
                        dtype=jnp.bfloat16, layer_stacked: bool = False,
                        num_layers: int = 0) -> ParamSpec:
    """Expert weight (E, in, out): experts on "ep", input dim on "tp"."""
    if layer_stacked:
        return ParamSpec((num_layers, num_experts, in_dim, out_dim),
                         P(None, AXIS_EP, AXIS_TP, None), dtype)
    return ParamSpec((num_experts, in_dim, out_dim),
                     P(AXIS_EP, AXIS_TP, None), dtype)


def replicated_param(shape: Tuple[int, ...], dtype=jnp.bfloat16, init="ones") -> ParamSpec:
    return ParamSpec(tuple(shape), P(), dtype, init)


# ---------------------------------------------------------------------------
# GQA head sharding (reference: modules/attention/gqa.py)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class GQASharding:
    """Resolved GQA head layout for a given tp degree.

    Strategies (reference: gqa.py:32-101):
      REPLICATE_TO_TP_DEGREE — repeat each KV head so num_kv_heads divides tp
      CONVERT_TO_MHA         — degenerate case rep == q_per_kv
    Plus Q/KV head *padding and reordering* when replication exceeds the
    original q-per-kv ratio (reference: gqa.py:137-244 pads heads and permutes
    Q so each rank holds Q heads together with their KV head).

    Layout invariants used by the attention op (ops/attention.py mha groups
    q heads by ``i // (num_q/num_kv)``):
      * padded KV slot s holds original KV head ``s // kv_replication``
      * original Q head i lives at padded slot ``q_slot_map[i]``; unused
        slots are zero (their o_proj rows are zero too, so they contribute
        nothing).
    """

    num_q_heads: int           # padded logical q heads
    num_kv_heads: int          # padded/replicated logical kv heads
    orig_q_heads: int
    orig_kv_heads: int
    kv_replication: int        # how many times each original kv head is repeated
    tp: int

    @property
    def q_per_kv(self) -> int:
        return self.num_q_heads // self.num_kv_heads

    @property
    def q_slot_map(self) -> Tuple[int, ...]:
        """orig q head i -> padded q slot, preserving kv alignment."""
        orig_qpk = self.orig_q_heads // self.orig_kv_heads
        rep, g = self.kv_replication, self.q_per_kv
        out = []
        for i in range(self.orig_q_heads):
            j, o = divmod(i, orig_qpk)
            out.append(j * rep * g + o)
        return tuple(out)

    @property
    def is_identity(self) -> bool:
        return (self.num_q_heads == self.orig_q_heads
                and self.num_kv_heads == self.orig_kv_heads)


def resolve_gqa_sharding(num_q_heads: int, num_kv_heads: int, tp: int) -> GQASharding:
    """Compute the padded/replicated head layout so kv heads divide tp.

    Mirrors the semantics of gqa.py:62-244. Requires the usual power-of-two
    style divisibility (num_q % num_kv == 0 and tp % num_kv == 0 or
    num_kv % tp == 0) — same constraint set the reference enforces.
    """
    if num_q_heads % num_kv_heads != 0:
        raise ValueError(f"num_q_heads {num_q_heads} must be a multiple of "
                         f"num_kv_heads {num_kv_heads}")
    orig_qpk = num_q_heads // num_kv_heads
    if num_kv_heads % tp == 0:
        rep = 1
        padded_kv = num_kv_heads
        g = orig_qpk
    elif tp % num_kv_heads == 0:
        rep = tp // num_kv_heads
        padded_kv = tp
        g = max(1, -(-orig_qpk // rep))  # ceil
    else:
        raise ValueError(f"unsupported head/tp combination: kv={num_kv_heads} tp={tp}")
    padded_q = padded_kv * g
    return GQASharding(padded_q, padded_kv, num_q_heads, num_kv_heads, rep, tp)


def _to_heads(w: np.ndarray, n_heads: int, head_dim: int, axis: int):
    shape = list(w.shape)
    axis = axis % w.ndim
    assert shape[axis] == n_heads * head_dim, (shape, n_heads, head_dim)
    shape[axis] = n_heads
    shape.insert(axis + 1, head_dim)
    return w.reshape(shape), axis


def _from_heads(w: np.ndarray, axis: int):
    shape = list(w.shape)
    shape[axis] = shape[axis] * shape[axis + 1]
    shape.pop(axis + 1)
    return w.reshape(shape)


def replicate_kv_weight(w: np.ndarray, sharding: GQASharding, head_dim: int,
                        axis: int = -1) -> np.ndarray:
    """Expand a K or V projection weight (..., orig_kv*dh) to the replicated
    layout (..., num_kv*dh): padded slot s = orig head s // rep
    (reference: gqa.py:137-244 ``replicate_kv``)."""
    if sharding.is_identity:
        return w
    w, axis = _to_heads(w, sharding.orig_kv_heads, head_dim, axis)
    w = np.repeat(w, sharding.kv_replication, axis=axis)
    return _from_heads(w, axis)


def place_q_weight(w: np.ndarray, sharding: GQASharding, head_dim: int,
                   axis: int = -1) -> np.ndarray:
    """Scatter original Q heads into their padded slots (zero elsewhere)
    per ``q_slot_map`` (reference: gqa.py head pad + reorder utilities)."""
    if sharding.is_identity:
        return w
    w, axis = _to_heads(w, sharding.orig_q_heads, head_dim, axis)
    out_shape = list(w.shape)
    out_shape[axis] = sharding.num_q_heads
    out = np.zeros(out_shape, dtype=w.dtype)
    idx = [slice(None)] * w.ndim
    src = [slice(None)] * w.ndim
    for i, s in enumerate(sharding.q_slot_map):
        idx[axis] = s
        src[axis] = i
        out[tuple(idx)] = w[tuple(src)]
    return _from_heads(out, axis)
