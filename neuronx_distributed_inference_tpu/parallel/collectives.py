"""Quantized decode collectives — EQuARX-style (PAPERS.md, arxiv 2506.17615)
int8/fp8 ring all-reduce / reduce-scatter for the tp decode collectives.

Decode is comm-bound under the ring model (artifacts/sharding_report_r18.json):
the row-parallel all-reduce after ``o_proj`` / ``down_proj`` moves fp32 wire
bytes every step. This module replaces that implicit GSPMD all-reduce with an
EXPLICIT ``shard_map`` two-phase ring exchange whose per-hop payload is
quantized to int8 (qmax 127) or fp8 e4m3 (qmax 448) with blockwise absmax
scales — the same scale plumbing as :mod:`..modules.quantization`
(``quantize_tensor``'s blockwise layout), applied to activations along the
wire instead of weights in HBM:

  phase 1 (reduce-scatter ring): split the local partial sum into ``g``
    chunks; g-1 hops of quantize -> ``ppermute`` -> dequantize -> accumulate;
    device r ends owning the fully-reduced chunk r.
  phase 2 (all-gather ring): circulate the owned chunk's QUANTIZED form
    (quantize once — the payload never changes, so requantization error does
    not compound) for another g-1 hops.

Wire bytes per device: 2(g-1)/g * N bytes at 1 byte/elem vs the fp32 ring
all-reduce's 2(g-1)/g * N * 4 — a 4x reduction, visible in the observatory
census as ``collective-permute`` ops with s8/f8e4m3fn payloads (plus small
fp32 scale permutes) instead of one f32 ``all-reduce``.

Accumulation stays full precision on-device; only the wire payload is
quantized. The knob lives in :class:`..config.CollectiveConfig` and threads
through ``DecoderSpec`` — when off, model graphs contain no shard_map and are
bit-identical to the fp32-collective stream.
"""

from __future__ import annotations

import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..resilience.errors import ConfigurationError
from .mesh import AXIS_CP, AXIS_DP, AXIS_MP

# dtype knob values -> (wire dtype, symmetric qmax). qmax values match the
# weight-quantization stack (modules/quantization.py quantize_tensor).
WIRE_DTYPES = {
    "int8": (jnp.int8, 127.0),
    "fp8": (jnp.float8_e4m3fn, 448.0),
}
SUPPORTED_DTYPES = tuple(WIRE_DTYPES)

DEFAULT_BLOCK = 32


def require_supported_dtype(dtype: str) -> None:
    """Typed refusal for unsupported wire dtypes (error-paths contract)."""
    if dtype not in WIRE_DTYPES:
        raise ConfigurationError(
            f"unsupported collective dtype {dtype!r}: quantized collectives "
            f"support {sorted(WIRE_DTYPES)} (None disables)")


def _quantize_wire(x: jnp.ndarray, dtype: str, block: int
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Blockwise symmetric quantize along the last dim.

    Mirrors quantize_tensor's BLOCKWISE layout: one fp32 absmax scale per
    ``block`` contiguous elements. Returns (q (..., C), scale (..., C//block)).
    """
    wire_dtype, qmax = WIRE_DTYPES[dtype]
    *lead, c = x.shape
    grouped = x.astype(jnp.float32).reshape(*lead, c // block, block)
    amax = jnp.max(jnp.abs(grouped), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / qmax
    scaled = grouped / scale
    if wire_dtype == jnp.int8:
        q = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(jnp.int8)
    else:
        q = scaled.astype(wire_dtype)
    return q.reshape(*lead, c), scale[..., 0]


def _dequantize_wire(q: jnp.ndarray, scale: jnp.ndarray, block: int,
                     out_dtype) -> jnp.ndarray:
    *lead, c = q.shape
    grouped = q.astype(jnp.float32).reshape(*lead, c // block, block)
    return (grouped * scale[..., None]).reshape(*lead, c).astype(out_dtype)


def _resolve_block(chunk: int, block: int) -> int:
    blk = min(block, chunk)
    if blk < 1 or chunk % blk != 0:
        raise ConfigurationError(
            f"collective block size {block} does not tile the per-shard ring "
            f"chunk of {chunk} elements; pick a block dividing the chunk")
    return blk


def quantized_all_reduce(x: jnp.ndarray, axis_name, group_size: int, *,
                         dtype: str = "int8", block: int = DEFAULT_BLOCK
                         ) -> jnp.ndarray:
    """Two-phase quantized ring all-reduce over ``axis_name``.

    A shard_map collective: call from inside ``jax.shard_map`` where
    ``axis_name`` is live. ``x`` is the local partial sum; the last dim is
    split into ``group_size`` ring chunks (must divide evenly).
    """
    require_supported_dtype(dtype)
    g = int(group_size)
    if g <= 1:
        return x
    n = x.shape[-1]
    if n % g != 0:
        raise ConfigurationError(
            f"quantized all-reduce needs the reduced dim ({n}) divisible by "
            f"the ring group size ({g})")
    blk = _resolve_block(n // g, block)
    fwd = [(i, (i + 1) % g) for i in range(g)]
    r = jax.lax.axis_index(axis_name)
    # (g, ..., chunk): chunk c of the local partial sum at index c
    blocks = jnp.stack(jnp.split(x, g, axis=-1), axis=0)
    # reduce-scatter ring: start from chunk (r-1) so device r ends owning
    # the fully-reduced chunk r after g-1 hops
    cur = jnp.take(blocks, (r - 1) % g, axis=0)
    for step in range(g - 1):
        q, scale = _quantize_wire(cur, dtype, blk)
        q = jax.lax.ppermute(q, axis_name, fwd)
        scale = jax.lax.ppermute(scale, axis_name, fwd)
        recv = _dequantize_wire(q, scale, blk, x.dtype)
        cur = recv + jnp.take(blocks, (r - step - 2) % g, axis=0)
    # all-gather ring: quantize the owned reduced chunk ONCE, forward the
    # quantized payload g-1 hops; own chunk stays full precision locally
    out = jnp.zeros_like(blocks)
    out = out.at[r].set(cur)
    q, scale = _quantize_wire(cur, dtype, blk)
    for step in range(g - 1):
        q = jax.lax.ppermute(q, axis_name, fwd)
        scale = jax.lax.ppermute(scale, axis_name, fwd)
        out = out.at[(r - step - 1) % g].set(
            _dequantize_wire(q, scale, blk, x.dtype))
    return jnp.moveaxis(out, 0, -2).reshape(*x.shape[:-1], n)


def quantized_reduce_scatter(x: jnp.ndarray, axis_name, group_size: int, *,
                             dtype: str = "int8", block: int = DEFAULT_BLOCK
                             ) -> jnp.ndarray:
    """Quantized ring reduce-scatter over the last dim: device r returns the
    fully-reduced chunk r, shape ``(..., n // group_size)``."""
    require_supported_dtype(dtype)
    g = int(group_size)
    n = x.shape[-1]
    if g <= 1:
        return x
    if n % g != 0:
        raise ConfigurationError(
            f"quantized reduce-scatter needs the reduced dim ({n}) divisible "
            f"by the ring group size ({g})")
    blk = _resolve_block(n // g, block)
    fwd = [(i, (i + 1) % g) for i in range(g)]
    r = jax.lax.axis_index(axis_name)
    blocks = jnp.stack(jnp.split(x, g, axis=-1), axis=0)
    cur = jnp.take(blocks, (r - 1) % g, axis=0)
    for step in range(g - 1):
        q, scale = _quantize_wire(cur, dtype, blk)
        q = jax.lax.ppermute(q, axis_name, fwd)
        scale = jax.lax.ppermute(scale, axis_name, fwd)
        recv = _dequantize_wire(q, scale, blk, x.dtype)
        cur = recv + jnp.take(blocks, (r - step - 2) % g, axis=0)
    return cur


# ---------------------------------------------------------------------------
# Row-parallel entry point (called from traced model code)
# ---------------------------------------------------------------------------

def _live_axes(mesh, names) -> Tuple[str, ...]:
    return tuple(a for a in names
                 if a in mesh.axis_names and mesh.shape[a] > 1)


def _weight_leaf(w: Any):
    """Normalize a row-parallel weight into (shard_map arg, spec, matmul fn).

    Returns None when the leaf cannot be sharded along its contraction dim
    (MXFP4's packed nibbles, or blockwise scales that don't tile the shard) —
    caller falls back to the implicit fp32 collective.
    """
    from ..modules.quantization import is_quantized_leaf, qlinear

    if not is_quantized_leaf(w):
        return w, P(AXIS_MP, None), qlinear
    qw, scale = w["qweight"], w["scale"]
    if qw.dtype == jnp.uint8:       # MXFP4: two fp4 values per byte along K
        return None
    if scale.ndim >= 2 and scale.shape[-2] > 1:
        # blockwise: scale rows tile K; sharding both along the contraction
        # axis stays consistent only when the mesh extent divides the rows
        spec = {"qweight": P(AXIS_MP, None), "scale": P(AXIS_MP, None)}
    else:
        spec = {"qweight": P(AXIS_MP, None), "scale": P(None, None)}
    return w, spec, qlinear


def quantized_row_parallel(x: jnp.ndarray, w: Any, *, dtype: str,
                           block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """Row-parallel matmul with a quantized ring all-reduce on the output.

    ``x`` is (B, T, K) with K sharded over the model-parallel axes and B over
    dp; ``w`` is (K, N) row-parallel (fp array or int8/fp8 quantized leaf).
    Falls back to the plain implicit-collective matmul when no model-parallel
    axis is live (single-device graphs stay collective-free) or the weight
    layout cannot shard along K.
    """
    from ..modules.quantization import qlinear

    require_supported_dtype(dtype)
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or not mesh.axis_names:
        return qlinear(x, w)
    mp_axes = _live_axes(mesh, AXIS_MP)
    g = math.prod(mesh.shape[a] for a in mp_axes)
    if g <= 1 or x.ndim != 3:
        return qlinear(x, w)
    leaf = _weight_leaf(w)
    if leaf is None:
        return qlinear(x, w)
    w_arg, w_spec, matmul = leaf
    k = x.shape[-1]
    qw = w["qweight"] if isinstance(w, dict) else w
    n = qw.shape[-1]
    if k % g != 0 or n % g != 0:
        raise ConfigurationError(
            f"quantized collectives need the contraction dim ({k}) and the "
            f"output dim ({n}) divisible by the model-parallel extent ({g})")
    if isinstance(w, dict) and isinstance(w_spec, dict):
        srows = w["scale"].shape[-2]
        if w_spec["scale"][0] is not None and srows % g != 0:
            return qlinear(x, w)     # blockwise scale rows don't tile shards
    _resolve_block(n // g, block)    # refuse un-tileable blocks before tracing
    # decode batch shards over (dp, cp) — mirror shard_batch_spec, but only
    # when the batch extent actually divides (otherwise replicate)
    batch_axes = tuple(a for a in _live_axes(mesh, (AXIS_DP, AXIS_CP))
                       if x.shape[0] % mesh.shape[a] == 0)
    dp_spec = batch_axes if batch_axes else None

    def body(xl, wl):
        partial = matmul(xl, wl)
        return quantized_all_reduce(partial, mp_axes, g,
                                    dtype=dtype, block=block)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(dp_spec, None, mp_axes), w_spec),
        out_specs=P(dp_spec, None, None),
        check_vma=False)(x, w_arg)
