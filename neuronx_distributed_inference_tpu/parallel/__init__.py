"""parallel subpackage."""
