"""Serving integration surface — the importable continuous-batching
contract a vLLM-style engine drives (reference: the vLLM-facing surface of
models/model_wrapper.py — ``vllm_cte_repadding`` :1297-1313 and the
seq_ids-addressed forward :1315-1440; the reference README's north star is
serving through vLLM).

The engine owns scheduling; this adapter owns device state:

  * ``add_requests(seq_ids, prompts)``  — prefill rows into their cache
    lines (cache rows are addressed BY seq_id, so request order is free)
  * ``step(seq_ids=None)``              — one decode step for the given
    (default: all) running rows, repadded to the compiled batch bucket
  * ``release(seq_ids)``                — free rows (and paged blocks)

Works over either application:
  - ``CausalLMApplication`` with ``is_continuous_batching=True`` —
    contiguous cache rows keyed by seq_id;
  - ``PagedCausalLMApplication`` — block tables keyed by seq_id.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .modules import autobucketing
from .telemetry import get_registry
from .telemetry import metrics as tmetrics


@dataclass
class _SeqState:
    position: int                 # position of last_token
    last_token: int
    running: bool = True


class _AdapterTelemetry:
    """Shared engine-adapter instrumentation: TTFT / per-step decode latency
    histograms, live-batch + pad-waste accounting, one request span per
    seq_id. Host-side only (measures at the adapter boundary — the device
    fetch has already happened when these run); every method is a cheap
    no-op while telemetry is disabled."""

    def __init__(self, engine: str, telemetry=None):
        self.engine = engine
        self._telemetry = telemetry
        self._requests: Dict[int, Dict[str, Any]] = {}

    @property
    def registry(self):
        return self._telemetry if self._telemetry is not None \
            else get_registry()

    def on_add(self, seq_ids: Sequence[int], prompts, t0: float,
               live: int, padded: int):
        reg = self.registry
        if not reg.enabled:
            return
        ttft = time.perf_counter() - t0
        hist = tmetrics.ttft_histogram(reg)
        for sid, prompt in zip(seq_ids, prompts):
            span = reg.start_span("request", engine=self.engine, seq_id=sid)
            span.t_start = t0
            span.event("first_token", ttft_s=ttft, prompt_len=len(prompt))
            self._requests[sid] = {"span": span, "steps": 0,
                                   "t_first": t0 + ttft, "t_last": t0 + ttft}
            hist.observe(ttft, engine=self.engine)
        tmetrics.requests_counter(reg).inc(len(seq_ids), engine=self.engine,
                                           event="added")
        tmetrics.generated_tokens_counter(reg).inc(live, engine=self.engine)
        self._rows(reg, "prefill", live, padded)

    def on_step(self, live_ids: Sequence[int], t0: float, padded: int):
        reg = self.registry
        if not reg.enabled:
            return
        now = time.perf_counter()
        tmetrics.decode_step_histogram(reg).observe(now - t0,
                                                    engine=self.engine)
        tmetrics.generated_tokens_counter(reg).inc(len(live_ids),
                                                   engine=self.engine)
        for sid in live_ids:
            info = self._requests.get(sid)
            if info is not None:
                info["steps"] += 1
                info["t_last"] = now
        self._rows(reg, "decode", len(live_ids), padded)

    def on_release(self, seq_ids: Sequence[int]):
        # pop unconditionally: requests admitted while telemetry was live
        # must not leak from _requests if it is disabled before release
        reg = self.registry
        released = 0
        for sid in seq_ids:
            info = self._requests.pop(sid, None)
            if info is None:
                continue
            released += 1
            span, steps = info["span"], info["steps"]
            span.event("released", decode_steps=steps)
            if reg.enabled and steps > 0:
                # first token -> LAST decode step, not -> release: a request
                # parked finished while the engine drains others must not
                # inflate its reported per-token latency
                tmetrics.tpot_histogram(reg).observe(
                    (info["t_last"] - info["t_first"]) / steps,
                    engine=self.engine)
            span.end()
        if released and reg.enabled:
            tmetrics.requests_counter(reg).inc(released, engine=self.engine,
                                               event="released")

    def _rows(self, reg, phase: str, live: int, padded: int):
        tmetrics.live_batch_gauge(reg).set(live, engine=self.engine)
        tmetrics.live_rows_counter(reg).inc(live, engine=self.engine,
                                            phase=phase)
        if padded > live:
            tmetrics.pad_rows_counter(reg).inc(padded - live,
                                               engine=self.engine,
                                               phase=phase)


def _live_rows(seqs: Dict[int, _SeqState],
               seq_ids: Optional[Sequence[int]]) -> List[int]:
    ids = sorted(seqs) if seq_ids is None else list(seq_ids)
    if seq_ids is not None:
        for sid in ids:
            if sid not in seqs:
                raise ValueError(f"seq_id {sid} is not running (released "
                                 "or never added)")
    return [sid for sid in ids if seqs[sid].running]


def _pad_paged_rows(pad_to, ids, pos, slots, bt, last):
    """Repeat row 0 up to the batch bucket; pad rows harmlessly rewrite
    row 0's slots with identical values (reference: vllm_cte_repadding,
    model_wrapper.py:1297-1313)."""
    b = ids.shape[0]
    if b == pad_to:
        return ids, pos, slots, bt, last

    def rep(x):
        return np.concatenate([x, np.repeat(x[:1], pad_to - b, axis=0)])
    return rep(ids), rep(pos), rep(slots), rep(bt), rep(last)


class ContinuousBatchingAdapter:
    """vLLM-style engine adapter over the contiguous app
    (reference: model_wrapper.py:1297-1440)."""

    def __init__(self, app, telemetry=None):
        cfg = app.tpu_config
        if not cfg.is_continuous_batching:
            raise ValueError("app must be built with "
                             "is_continuous_batching=True")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}
        self.telemetry = _AdapterTelemetry("cb", telemetry)

    # -- capacity ---------------------------------------------------------
    @property
    def free_slots(self) -> List[int]:
        used = set(self.seqs)
        return [i for i in range(self.batch) if i not in used]

    # -- lifecycle --------------------------------------------------------
    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]]) -> Dict[int, int]:
        """Prefill ``prompts`` into cache rows ``seq_ids``. Returns
        {seq_id: first generated token}. Rows are padded to the ctx bucket
        (repeat-row-0 batch pad — reference ``vllm_cte_repadding``)."""
        if len(seq_ids) != len(prompts):
            raise ValueError("seq_ids and prompts length mismatch")
        for sid in seq_ids:
            if not 0 <= sid < self.batch:
                raise ValueError(f"seq_id {sid} out of range [0,{self.batch})")
            if sid in self.seqs:
                raise ValueError(f"seq_id {sid} already running")
        t0 = time.perf_counter()
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        width = autobucketing.get_target_bucket(self.app.ctx_buckets,
                                                int(lens.max()), kind="ctx")
        ids = np.zeros((b, width), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        pad_to = self._batch_bucket(b)
        ids_p, sid_p = self._pad_rows(ids, np.asarray(seq_ids, np.int32),
                                      pad_to)
        lens_p = np.concatenate([lens, np.repeat(lens[:1], pad_to - b)])
        out = self.app._run_prefill(ids_p, lens_p, seq_ids=sid_p)
        toks = np.asarray(out["tokens"])[:b]
        res = {}
        for i, sid in enumerate(seq_ids):
            self.seqs[sid] = _SeqState(position=int(lens[i]),
                                       last_token=int(toks[i]))
            res[sid] = int(toks[i])
        self.telemetry.on_add(seq_ids, prompts, t0, live=b, padded=pad_to)
        return res

    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        """One decode step for ``seq_ids`` (default: every running row).
        Returns {seq_id: next token}."""
        live = _live_rows(self.seqs, seq_ids)
        if not live:
            return {}
        t0 = time.perf_counter()
        b = len(live)
        pad_to = self._batch_bucket(b)
        sid = np.asarray(live, np.int32)
        toks = np.asarray([self.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([self.seqs[s].position for s in live], np.int32)
        sid_p = np.concatenate([sid, np.repeat(sid[:1], pad_to - b)])
        toks_p = np.concatenate([toks, np.repeat(toks[:1], pad_to - b)])
        pos_p = np.concatenate([pos, np.repeat(pos[:1], pad_to - b)])
        out = self.app._run_decode(toks_p[:, None], pos_p[:, None],
                                   seq_ids=sid_p)
        new = np.asarray(out["tokens"]).reshape(-1)[:b]
        res = {}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            st.last_token = int(new[i])
            res[s] = int(new[i])
        self.telemetry.on_step(live, t0, padded=pad_to)
        return res

    def release(self, seq_ids: Sequence[int]):
        for sid in seq_ids:
            self.seqs.pop(sid, None)
        self.telemetry.on_release(seq_ids)

    # -- helpers ----------------------------------------------------------
    def _batch_bucket(self, b: int) -> int:
        if b > self.batch:
            raise ValueError(f"live batch {b} exceeds compiled batch "
                             f"{self.batch}")
        return autobucketing.get_target_bucket(self.app.batch_buckets, b,
                                               kind="batch")

    @staticmethod
    def _pad_rows(ids: np.ndarray, seq_ids: np.ndarray, pad_to: int):
        pad = pad_to - ids.shape[0]
        if pad <= 0:
            return ids, seq_ids
        return (np.concatenate([ids, np.repeat(ids[:1], pad, axis=0)]),
                np.concatenate([seq_ids, np.repeat(seq_ids[:1], pad)]))


class PagedEngineAdapter:
    """vLLM-style engine adapter over the PAGED app: block tables keyed by
    seq_id, slot mappings computed from the tables (reference: the
    slot_mapping / active_block_table contract of
    block_kv_cache_manager.py + model_wrapper.py:1297-1313)."""

    def __init__(self, app, telemetry=None):
        cfg = app.tpu_config
        if not cfg.is_block_kv_layout:
            raise ValueError("app must be built with is_block_kv_layout=True")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}
        self.telemetry = _AdapterTelemetry("paged", telemetry)

    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]]) -> Dict[int, int]:
        from .modules.block_kv_cache import slots_from_table
        if len(seq_ids) != len(prompts):
            raise ValueError("seq_ids and prompts length mismatch")
        if len(set(seq_ids)) != len(seq_ids):
            raise ValueError("duplicate seq_ids in one add_requests call")
        for sid in seq_ids:
            if sid in self.seqs:
                raise ValueError(f"seq_id {sid} already running")
        t0 = time.perf_counter()
        app = self.app
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        cached = np.zeros((b,), np.int32)
        for i, sid in enumerate(seq_ids):
            _, c = app.kv_mgr.begin_sequence(sid, list(prompts[i]))
            cached[i] = min(c, lens[i] - 1)
        width = autobucketing.get_target_bucket(
            app.ctx_buckets, int((lens - cached).max()), kind="ctx")
        bt = app.kv_mgr.block_table_array(seq_ids, app._bt_width_for(seq_ids))
        ids_w = np.zeros((b, width), np.int32)
        pos_w = np.zeros((b, width), np.int32)
        for i, p in enumerate(prompts):
            lo = int(cached[i])
            n = int(lens[i] - lo)
            ids_w[i, :n] = np.asarray(p[lo:lo + n])
            pos_w[i] = lo + np.arange(width, dtype=np.int32)
        valid = np.arange(width)[None, :] < (lens - cached)[:, None]
        slots = slots_from_table(bt, np.where(valid, pos_w, -1),
                                 app.kv_mgr.spec.block_size)
        # repad to the compiled batch bucket (repeat row 0 - pad rows
        # rewrite row 0's slots with identical values); without this every
        # distinct live count would jit a fresh graph mid-serving
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        ids_w, pos_w, slots, bt2, last = _pad_paged_rows(
            pad_to, ids_w, pos_w, slots, bt,
            np.maximum(lens - cached - 1, 0))
        out = app._run_paged(ids_w, pos_w, slots, bt2, last)
        toks = np.asarray(out["tokens"]).reshape(-1)
        res = {}
        for i, sid in enumerate(seq_ids):
            self.seqs[sid] = _SeqState(position=int(lens[i]),
                                       last_token=int(toks[i]))
            res[sid] = int(toks[i])
        self.telemetry.on_add(seq_ids, prompts, t0, live=b, padded=pad_to)
        return res

    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        from .modules.block_kv_cache import slots_from_table
        app = self.app
        live = _live_rows(self.seqs, seq_ids)
        if not live:
            return {}
        t0 = time.perf_counter()
        b = len(live)
        toks = np.asarray([self.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([self.seqs[s].position for s in live], np.int32)
        for s in live:
            app.kv_mgr.grow(s, 1)
        bt = app.kv_mgr.block_table_array(live, app._bt_width_for(live))
        slots = slots_from_table(bt, pos[:, None],
                                 app.kv_mgr.spec.block_size)
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        ids_p, pos_p, slots_p, bt_p, last_p = _pad_paged_rows(
            pad_to, toks[:, None], pos[:, None], slots, bt,
            np.zeros((b,), np.int32))
        out = app._run_paged(ids_p, pos_p, slots_p, bt_p, last_p)
        new = np.asarray(out["tokens"]).reshape(-1)[:b]
        res = {}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            st.last_token = int(new[i])
            res[s] = int(new[i])
        self.telemetry.on_step(live, t0, padded=pad_to)
        return res

    def release(self, seq_ids: Sequence[int]):
        for sid in seq_ids:
            if sid in self.seqs:
                self.seqs.pop(sid)
                if sid in self.app.kv_mgr.tables:
                    self.app.kv_mgr.end_sequence(sid)
        self.telemetry.on_release(seq_ids)
