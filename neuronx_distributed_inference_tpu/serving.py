"""Serving integration surface — the importable continuous-batching
contract a vLLM-style engine drives (reference: the vLLM-facing surface of
models/model_wrapper.py — ``vllm_cte_repadding`` :1297-1313 and the
seq_ids-addressed forward :1315-1440; the reference README's north star is
serving through vLLM).

The engine owns scheduling; this adapter owns device state:

  * ``add_requests(seq_ids, prompts)``  — prefill rows into their cache
    lines (cache rows are addressed BY seq_id, so request order is free)
  * ``step(seq_ids=None)``              — one decode step for the given
    (default: all) running rows, repadded to the compiled batch bucket
  * ``release(seq_ids)``                — free rows (and paged blocks)

Works over either application:
  - ``CausalLMApplication`` with ``is_continuous_batching=True`` —
    contiguous cache rows keyed by seq_id;
  - ``PagedCausalLMApplication`` — block tables keyed by seq_id.

Resilience contract (see README "Serving resilience"):

  * every boundary failure is typed (``resilience.errors``) — never a bare
    ``ValueError``/``RuntimeError`` (enforced by
    ``scripts/check_error_paths.py``);
  * ``add_requests`` is **transactional**: it either admits every sequence
    or rolls back all allocations/adapter state from the call and leaves
    device + cache state exactly as before;
  * the paged adapter **preempts** the lowest-priority running sequence
    when the block pool runs dry (``preemption_policy``: "lifo" /
    "fewest_generated" / None), handing back :class:`Preempted` records
    via :meth:`PagedEngineAdapter.take_preempted`;
  * per-request wall-clock deadlines (``deadline_s``) and a
    decode-past-``seq_len`` guard bound each request's budget.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .modules import autobucketing
from .resilience.errors import (AdmissionError, CapacityError,
                                ConfigurationError, DeadlineExceeded,
                                SequenceStateError, ServingError, StepFailure)
from .resilience.faults import FAULTS as _FAULTS
from .resilience.preemption import (PREEMPTION_POLICIES, Preempted,
                                    pick_victim)
from .telemetry import get_registry
from .telemetry import metrics as tmetrics


@dataclass
class _SeqState:
    position: int                 # position of last_token
    last_token: int
    running: bool = True
    tokens: List[int] = field(default_factory=list)  # prompt + generated
    prompt_len: int = 0
    admit_idx: int = 0            # adapter-wide admission counter (LIFO)
    deadline: Optional[float] = None   # absolute perf_counter() deadline
    expired_reported: bool = False     # deadline metric counted once


class _AdapterTelemetry:
    """Shared engine-adapter instrumentation: TTFT / per-step decode latency
    histograms, live-batch + pad-waste accounting, one request span per
    seq_id. Host-side only (measures at the adapter boundary — the device
    fetch has already happened when these run); every method is a cheap
    no-op while telemetry is disabled."""

    def __init__(self, engine: str, telemetry=None):
        self.engine = engine
        self._telemetry = telemetry
        self._requests: Dict[int, Dict[str, Any]] = {}

    @property
    def registry(self):
        return self._telemetry if self._telemetry is not None \
            else get_registry()

    def on_add(self, seq_ids: Sequence[int], prompts, t0: float,
               live: int, padded: int):
        reg = self.registry
        if not reg.enabled:
            return
        ttft = time.perf_counter() - t0
        hist = tmetrics.ttft_histogram(reg)
        for sid, prompt in zip(seq_ids, prompts):
            span = reg.start_span("request", engine=self.engine, seq_id=sid)
            span.t_start = t0
            span.event("first_token", ttft_s=ttft, prompt_len=len(prompt))
            self._requests[sid] = {"span": span, "steps": 0,
                                   "t_first": t0 + ttft, "t_last": t0 + ttft}
            hist.observe(ttft, engine=self.engine)
        tmetrics.requests_counter(reg).inc(len(seq_ids), engine=self.engine,
                                           event="added")
        tmetrics.generated_tokens_counter(reg).inc(live, engine=self.engine)
        self._rows(reg, "prefill", live, padded)

    def on_step(self, live_ids: Sequence[int], t0: float, padded: int):
        reg = self.registry
        if not reg.enabled:
            return
        now = time.perf_counter()
        tmetrics.decode_step_histogram(reg).observe(now - t0,
                                                    engine=self.engine)
        tmetrics.generated_tokens_counter(reg).inc(len(live_ids),
                                                   engine=self.engine)
        for sid in live_ids:
            info = self._requests.get(sid)
            if info is not None:
                info["steps"] += 1
                info["t_last"] = now
        self._rows(reg, "decode", len(live_ids), padded)

    def on_release(self, seq_ids: Sequence[int]):
        # pop unconditionally: requests admitted while telemetry was live
        # must not leak from _requests if it is disabled before release
        reg = self.registry
        released = 0
        for sid in seq_ids:
            info = self._requests.pop(sid, None)
            if info is None:
                continue
            released += 1
            span, steps = info["span"], info["steps"]
            span.event("released", decode_steps=steps)
            if reg.enabled and steps > 0:
                # first token -> LAST decode step, not -> release: a request
                # parked finished while the engine drains others must not
                # inflate its reported per-token latency
                tmetrics.tpot_histogram(reg).observe(
                    (info["t_last"] - info["t_first"]) / steps,
                    engine=self.engine)
            span.end()
        if released and reg.enabled:
            tmetrics.requests_counter(reg).inc(released, engine=self.engine,
                                               event="released")

    def on_preempt(self, seq_id: int, reason: str):
        # like on_release, the span is closed unconditionally so a request
        # preempted after telemetry is disabled cannot leak from _requests
        info = self._requests.pop(seq_id, None)
        if info is not None:
            info["span"].event("preempted", reason=reason)
            info["span"].end()
        reg = self.registry
        if reg.enabled:
            tmetrics.preemptions_counter(reg).inc(engine=self.engine,
                                                  reason=reason)

    def on_deadline(self, seq_ids: Sequence[int]):
        reg = self.registry
        if seq_ids and reg.enabled:
            tmetrics.deadline_expired_counter(reg).inc(len(seq_ids),
                                                       engine=self.engine)

    def on_step_failure(self, phase: str):
        reg = self.registry
        if reg.enabled:
            tmetrics.step_failures_counter(reg).inc(engine=self.engine,
                                                    phase=phase)

    def on_admission_rollback(self):
        reg = self.registry
        if reg.enabled:
            tmetrics.admission_rollbacks_counter(reg).inc(engine=self.engine)

    def _rows(self, reg, phase: str, live: int, padded: int):
        tmetrics.live_batch_gauge(reg).set(live, engine=self.engine)
        tmetrics.live_rows_counter(reg).inc(live, engine=self.engine,
                                            phase=phase)
        if padded > live:
            tmetrics.pad_rows_counter(reg).inc(padded - live,
                                               engine=self.engine,
                                               phase=phase)


def _live_rows(seqs: Dict[int, _SeqState],
               seq_ids: Optional[Sequence[int]]) -> List[int]:
    ids = sorted(seqs) if seq_ids is None else list(seq_ids)
    if seq_ids is not None:
        for sid in ids:
            if sid not in seqs:
                raise SequenceStateError(f"seq_id {sid} is not running "
                                         "(released or never added)")
    return [sid for sid in ids if seqs[sid].running]


def _validate_admission(seq_ids: Sequence[int],
                        prompts: Sequence[Sequence[int]], seq_len: int):
    """Reject malformed admissions BEFORE any state changes — an empty
    batch or a zero-length prompt must fail typed here, not as an opaque
    numpy ``max()`` crash three layers down."""
    if len(seq_ids) == 0:
        raise AdmissionError("add_requests called with empty seq_ids")
    if len(seq_ids) != len(prompts):
        raise AdmissionError("seq_ids and prompts length mismatch "
                             f"({len(seq_ids)} vs {len(prompts)})")
    if len(set(seq_ids)) != len(seq_ids):
        raise AdmissionError("duplicate seq_ids in one add_requests call")
    for sid, p in zip(seq_ids, prompts):
        if len(p) == 0:
            raise AdmissionError(f"zero-length prompt for seq_id {sid}")
        if len(p) > seq_len:
            raise AdmissionError(
                f"prompt for seq_id {sid} is {len(p)} tokens — beyond the "
                f"compiled seq_len {seq_len}")


def _resolve_deadlines(deadline_s, n: int,
                       t0: float) -> List[Optional[float]]:
    """Per-request absolute deadlines from a scalar (shared) or per-seq
    sequence of relative wall-clock budgets in seconds."""
    if deadline_s is None:
        return [None] * n
    if isinstance(deadline_s, (int, float)):
        return [t0 + float(deadline_s)] * n
    if len(deadline_s) != n:
        raise AdmissionError("deadline_s and seq_ids length mismatch")
    return [None if d is None else t0 + float(d) for d in deadline_s]


def _pre_step_checks(seqs: Dict[int, _SeqState], live: Sequence[int],
                     seq_len: Optional[int], telemetry: _AdapterTelemetry):
    """Per-request budget enforcement, BEFORE any device work or cache
    growth: wall-clock deadlines, then the decode-past-seq_len guard (a
    row at position seq_len-1 holds its last representable token — one
    more step would scatter KV out of bounds). ``seq_len`` is None for
    rolling-window caches (slot = pos % window never overflows)."""
    now = time.perf_counter()
    expired = [s for s in live
               if seqs[s].deadline is not None and now >= seqs[s].deadline]
    if expired:
        fresh = [s for s in expired if not seqs[s].expired_reported]
        for s in fresh:
            seqs[s].expired_reported = True
        telemetry.on_deadline(fresh)
        raise DeadlineExceeded(
            f"seq_ids {expired} exceeded their wall-clock deadline; "
            "release() them (or re-queue with a fresh budget) and step "
            "again", seq_ids=expired)
    if seq_len is None:
        return
    over = [s for s in live if seqs[s].position + 1 > seq_len]
    if over:
        raise CapacityError(
            f"decode step for seq_ids {over} would write KV past the "
            f"compiled seq_len {seq_len}; release them or rebuild with a "
            "larger seq_len", seq_ids=over)


def _pad_paged_rows(pad_to, ids, pos, slots, bt, last):
    """Repeat row 0 up to the batch bucket; pad rows harmlessly rewrite
    row 0's slots with identical values (reference: vllm_cte_repadding,
    model_wrapper.py:1297-1313)."""
    b = ids.shape[0]
    if b == pad_to:
        return ids, pos, slots, bt, last

    def rep(x):
        return np.concatenate([x, np.repeat(x[:1], pad_to - b, axis=0)])
    return rep(ids), rep(pos), rep(slots), rep(bt), rep(last)


class ContinuousBatchingAdapter:
    """vLLM-style engine adapter over the contiguous app
    (reference: model_wrapper.py:1297-1440)."""

    def __init__(self, app, telemetry=None):
        cfg = app.tpu_config
        if not cfg.is_continuous_batching:
            raise ConfigurationError("app must be built with "
                                     "is_continuous_batching=True")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}
        self.telemetry = _AdapterTelemetry("cb", telemetry)
        # rolling caches (slot = pos % window) can decode past seq_len
        self._pos_limit = (None if getattr(app.spec, "rolling_window", False)
                           else cfg.seq_len)

    # -- capacity ---------------------------------------------------------
    @property
    def free_slots(self) -> List[int]:
        used = set(self.seqs)
        return [i for i in range(self.batch) if i not in used]

    # -- lifecycle --------------------------------------------------------
    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]],
                     deadline_s: Union[None, float,
                                       Sequence[Optional[float]]] = None
                     ) -> Dict[int, int]:
        """Prefill ``prompts`` into cache rows ``seq_ids``. Returns
        {seq_id: first generated token}. Rows are padded to the ctx bucket
        (repeat-row-0 batch pad — reference ``vllm_cte_repadding``).
        Transactional: a failure admits nothing (cache rows hold garbage
        only for never-admitted seq_ids, which no live row can read)."""
        _validate_admission(seq_ids, prompts, self.app.tpu_config.seq_len)
        for sid in seq_ids:
            if not 0 <= sid < self.batch:
                raise AdmissionError(f"seq_id {sid} out of range "
                                     f"[0,{self.batch})")
            if sid in self.seqs:
                raise AdmissionError(f"seq_id {sid} already running")
        t0 = time.perf_counter()
        deadlines = _resolve_deadlines(deadline_s, len(seq_ids), t0)
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        try:
            width = autobucketing.get_target_bucket(
                self.app.ctx_buckets, int(lens.max()), kind="ctx")
        except ValueError as e:
            raise AdmissionError(f"prompt does not fit any context-encoding "
                                 f"bucket: {e}") from e
        ids = np.zeros((b, width), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        pad_to = self._batch_bucket(b)
        ids_p, sid_p = self._pad_rows(ids, np.asarray(seq_ids, np.int32),
                                      pad_to)
        lens_p = np.concatenate([lens, np.repeat(lens[:1], pad_to - b)])
        cache_before = self.app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("prefill_step")
            out = self.app._run_prefill(ids_p, lens_p, seq_ids=sid_p)
            # materialize INSIDE the try: dispatch is asynchronous, so a
            # genuine device failure only surfaces when the tokens are
            # fetched — it must still be wrapped and rolled back here
            toks = np.asarray(out["tokens"])[:b]
        except ServingError:
            raise
        except Exception as e:
            self.telemetry.on_step_failure("prefill")
            raise StepFailure(
                "prefill device step failed; no sequences were admitted",
                phase="prefill", seq_ids=seq_ids,
                retry_safe=self.app.cache is cache_before) from e
        res = {}
        for i, sid in enumerate(seq_ids):
            # no tokens/admit_idx bookkeeping here: the CB adapter has no
            # preemption path (rows are fixed slots), so the recompute
            # record the paged adapter keeps would be dead state
            self.seqs[sid] = _SeqState(
                position=int(lens[i]), last_token=int(toks[i]),
                prompt_len=int(lens[i]), deadline=deadlines[i])
            res[sid] = int(toks[i])
        self.telemetry.on_add(seq_ids, prompts, t0, live=b, padded=pad_to)
        return res

    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        """One decode step for ``seq_ids`` (default: every running row).
        Returns {seq_id: next token}. Raises :class:`DeadlineExceeded` /
        :class:`CapacityError` before any device work when a row is over
        budget, and :class:`StepFailure` (state untouched, retryable) when
        the device call itself fails."""
        live = _live_rows(self.seqs, seq_ids)
        if not live:
            return {}
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        _pre_step_checks(self.seqs, live, self._pos_limit, self.telemetry)
        t0 = time.perf_counter()
        b = len(live)
        pad_to = self._batch_bucket(b)
        sid = np.asarray(live, np.int32)
        toks = np.asarray([self.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([self.seqs[s].position for s in live], np.int32)
        sid_p = np.concatenate([sid, np.repeat(sid[:1], pad_to - b)])
        toks_p = np.concatenate([toks, np.repeat(toks[:1], pad_to - b)])
        pos_p = np.concatenate([pos, np.repeat(pos[:1], pad_to - b)])
        cache_before = self.app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("decode_step")
            out = self.app._run_decode(toks_p[:, None], pos_p[:, None],
                                       seq_ids=sid_p)
            new = np.asarray(out["tokens"]).reshape(-1)[:b]
        except ServingError:
            raise
        except Exception as e:
            self.telemetry.on_step_failure("decode")
            raise StepFailure(
                "decode device step failed; positions were not advanced",
                phase="decode", seq_ids=tuple(live),
                retry_safe=self.app.cache is cache_before) from e
        res = {}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            st.last_token = int(new[i])
            res[s] = int(new[i])
        self.telemetry.on_step(live, t0, padded=pad_to)
        return res

    def release(self, seq_ids: Sequence[int]):
        for sid in seq_ids:
            self.seqs.pop(sid, None)
        self.telemetry.on_release(seq_ids)

    # -- helpers ----------------------------------------------------------
    def _batch_bucket(self, b: int) -> int:
        if b > self.batch:
            raise CapacityError(f"live batch {b} exceeds compiled batch "
                                f"{self.batch}")
        return autobucketing.get_target_bucket(self.app.batch_buckets, b,
                                               kind="batch")

    @staticmethod
    def _pad_rows(ids: np.ndarray, seq_ids: np.ndarray, pad_to: int):
        pad = pad_to - ids.shape[0]
        if pad <= 0:
            return ids, seq_ids
        return (np.concatenate([ids, np.repeat(ids[:1], pad, axis=0)]),
                np.concatenate([seq_ids, np.repeat(seq_ids[:1], pad)]))


class PagedEngineAdapter:
    """vLLM-style engine adapter over the PAGED app: block tables keyed by
    seq_id, slot mappings computed from the tables (reference: the
    slot_mapping / active_block_table contract of
    block_kv_cache_manager.py + model_wrapper.py:1297-1313).

    ``preemption_policy`` ("lifo" | "fewest_generated" | None) arms
    recompute preemption: when the block pool cannot satisfy an allocation
    the lowest-priority running sequence is evicted, its blocks reclaimed,
    and a :class:`Preempted` record queued for :meth:`take_preempted` —
    the engine re-queues ``record.tokens`` as a fresh prompt. ``None``
    disables eviction (allocation failures then raise
    :class:`CapacityError` after rolling the call back)."""

    def __init__(self, app, telemetry=None,
                 preemption_policy: Optional[str] = "lifo"):
        cfg = app.tpu_config
        if not cfg.is_block_kv_layout:
            raise ConfigurationError("app must be built with "
                                     "is_block_kv_layout=True")
        if (preemption_policy is not None
                and preemption_policy not in PREEMPTION_POLICIES):
            raise ConfigurationError(
                f"unknown preemption_policy {preemption_policy!r}; expected "
                f"one of {PREEMPTION_POLICIES} or None")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}
        self.telemetry = _AdapterTelemetry("paged", telemetry)
        self.preemption_policy = preemption_policy
        self.preempted: List[Preempted] = []
        self._admit_counter = 0
        self._pos_limit = (None if getattr(app.spec, "rolling_window", False)
                           else cfg.seq_len)

    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]],
                     deadline_s: Union[None, float,
                                       Sequence[Optional[float]]] = None
                     ) -> Dict[int, int]:
        """Transactional admission: either every sequence is admitted, or
        every ``begin_sequence`` allocation from this call is rolled back
        and cache state is exactly as before (pool pressure may still
        preempt RUNNING sequences first — that eviction is reported via
        :meth:`take_preempted` and survives a subsequent rollback, since
        the preempted work is handed back to the engine either way)."""
        from .modules.block_kv_cache import slots_from_table
        _validate_admission(seq_ids, prompts, self.app.tpu_config.seq_len)
        for sid in seq_ids:
            if sid in self.seqs:
                raise AdmissionError(f"seq_id {sid} already running")
        t0 = time.perf_counter()
        deadlines = _resolve_deadlines(deadline_s, len(seq_ids), t0)
        app = self.app
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        cached = np.zeros((b,), np.int32)
        begun: List[int] = []
        cache_before = app.cache
        try:
            for i, sid in enumerate(seq_ids):
                while True:
                    try:
                        _, c = app.kv_mgr.begin_sequence(sid,
                                                         list(prompts[i]))
                        begun.append(sid)
                        break
                    except CapacityError:
                        victim = self._choose_victim()
                        if victim is None:
                            raise
                        self._preempt(victim, reason="admission")
                cached[i] = min(c, lens[i] - 1)
            try:
                width = autobucketing.get_target_bucket(
                    app.ctx_buckets, int((lens - cached).max()), kind="ctx")
            except ValueError as e:
                raise AdmissionError(
                    f"prompt does not fit any context-encoding bucket: "
                    f"{e}") from e
            bt = app.kv_mgr.block_table_array(seq_ids,
                                              app._bt_width_for(seq_ids))
            ids_w = np.zeros((b, width), np.int32)
            pos_w = np.zeros((b, width), np.int32)
            for i, p in enumerate(prompts):
                lo = int(cached[i])
                n = int(lens[i] - lo)
                ids_w[i, :n] = np.asarray(p[lo:lo + n])
                pos_w[i] = lo + np.arange(width, dtype=np.int32)
            valid = np.arange(width)[None, :] < (lens - cached)[:, None]
            slots = slots_from_table(bt, np.where(valid, pos_w, -1),
                                     app.kv_mgr.spec.block_size)
            # repad to the compiled batch bucket (repeat row 0 - pad rows
            # rewrite row 0's slots with identical values); without this
            # every distinct live count would jit a fresh graph mid-serving
            pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                     kind="batch")
            ids_w, pos_w, slots, bt2, last = _pad_paged_rows(
                pad_to, ids_w, pos_w, slots, bt,
                np.maximum(lens - cached - 1, 0))
            if _FAULTS.active:
                _FAULTS.fire("prefill_step")
            out = app._run_paged(ids_w, pos_w, slots, bt2, last)
            # materialize INSIDE the try: dispatch is asynchronous, so a
            # genuine device failure only surfaces when the tokens are
            # fetched — it must still be wrapped and rolled back here
            toks = np.asarray(out["tokens"]).reshape(-1)
        except ServingError:
            self._rollback_admission(begun)
            raise
        except Exception as e:
            self._rollback_admission(begun)
            self.telemetry.on_step_failure("prefill")
            raise StepFailure(
                "paged prefill failed; all allocations from this call were "
                "rolled back", phase="prefill", seq_ids=seq_ids,
                retry_safe=app.cache is cache_before) from e
        res = {}
        for i, sid in enumerate(seq_ids):
            self._admit_counter += 1
            self.seqs[sid] = _SeqState(
                position=int(lens[i]), last_token=int(toks[i]),
                tokens=list(prompts[i]) + [int(toks[i])],
                prompt_len=int(lens[i]), admit_idx=self._admit_counter,
                deadline=deadlines[i])
            res[sid] = int(toks[i])
        self.telemetry.on_add(seq_ids, prompts, t0, live=b, padded=pad_to)
        return res

    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        """One decode step for ``seq_ids`` (default: every running row).
        Returns {seq_id: next token}. Under block-pool pressure, running
        sequences may be preempted to make room (absent from the result;
        collect them with :meth:`take_preempted`). A device failure rolls
        host KV growth back and raises :class:`StepFailure` (retryable)."""
        from .modules.block_kv_cache import slots_from_table
        app = self.app
        live = _live_rows(self.seqs, seq_ids)
        if not live:
            return {}
        if _FAULTS.active:
            _FAULTS.fire("slow_step")
        _pre_step_checks(self.seqs, live, self._pos_limit, self.telemetry)
        t0 = time.perf_counter()
        live = self._grow_with_preemption(live)
        if not live:
            return {}
        b = len(live)
        toks = np.asarray([self.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([self.seqs[s].position for s in live], np.int32)
        bt = app.kv_mgr.block_table_array(live, app._bt_width_for(live))
        slots = slots_from_table(bt, pos[:, None],
                                 app.kv_mgr.spec.block_size)
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b,
                                                 kind="batch")
        ids_p, pos_p, slots_p, bt_p, last_p = _pad_paged_rows(
            pad_to, toks[:, None], pos[:, None], slots, bt,
            np.zeros((b,), np.int32))
        cache_before = app.cache
        try:
            if _FAULTS.active:
                _FAULTS.fire("decode_step")
            out = app._run_paged(ids_p, pos_p, slots_p, bt_p, last_p)
            new = np.asarray(out["tokens"]).reshape(-1)[:b]
        except ServingError:
            self._rollback_grow(live)
            raise
        except Exception as e:
            self._rollback_grow(live)
            self.telemetry.on_step_failure("decode")
            raise StepFailure(
                "paged decode step failed; KV growth was rolled back and "
                "positions were not advanced",
                phase="decode", seq_ids=tuple(live),
                retry_safe=app.cache is cache_before) from e
        res = {}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            st.last_token = int(new[i])
            st.tokens.append(int(new[i]))
            res[s] = int(new[i])
        self.telemetry.on_step(live, t0, padded=pad_to)
        return res

    def release(self, seq_ids: Sequence[int]):
        for sid in seq_ids:
            if sid in self.seqs:
                self.seqs.pop(sid)
                if sid in self.app.kv_mgr.tables:
                    self.app.kv_mgr.end_sequence(sid)
        self.telemetry.on_release(seq_ids)

    # -- preemption -------------------------------------------------------
    def take_preempted(self) -> List[Preempted]:
        """Drain :class:`Preempted` records accumulated since the last
        call. The engine re-queues each ``record.tokens`` as a new prompt;
        under greedy sampling the recomputed continuation is bit-identical
        to the uninterrupted run."""
        out, self.preempted = self.preempted, []
        return out

    def _choose_victim(self) -> Optional[int]:
        if self.preemption_policy is None:
            return None
        cands = [(sid, st.admit_idx, len(st.tokens) - st.prompt_len)
                 for sid, st in self.seqs.items() if st.running]
        return pick_victim(self.preemption_policy, cands)

    def _preempt(self, victim: int, reason: str):
        st = self.seqs.pop(victim)
        if victim in self.app.kv_mgr.tables:
            self.app.kv_mgr.end_sequence(victim)
        self.preempted.append(Preempted(
            seq_id=victim, tokens=tuple(st.tokens),
            prompt_len=st.prompt_len,
            n_generated=len(st.tokens) - st.prompt_len, reason=reason))
        self.telemetry.on_preempt(victim, reason)

    def _grow_with_preemption(self, live: Sequence[int]) -> List[int]:
        """Grow every live row's block list by one token, evicting
        victims per the policy when the pool is dry. Returns the rows
        still live (preempted ones removed). If eviction cannot free
        enough, all growth from this call is rolled back and the
        :class:`CapacityError` propagates."""
        app = self.app
        live = list(live)
        queue = list(live)
        grown: List[int] = []
        while queue:
            s = queue[0]
            try:
                app.kv_mgr.grow(s, 1)
            except CapacityError:
                victim = self._choose_victim()
                if victim is None:
                    for g in grown:
                        app.kv_mgr.shrink(g, 1)
                    raise
                self._preempt(victim, reason="grow")
                for lst in (queue, live, grown):
                    if victim in lst:
                        lst.remove(victim)
                continue
            queue.pop(0)
            grown.append(s)
        return live

    def _rollback_grow(self, live: Sequence[int]):
        for s in live:
            self.app.kv_mgr.shrink(s, 1)

    def _rollback_admission(self, begun: Sequence[int]):
        """Abort every sequence begun by the failing add_requests call:
        frees its blocks and purges never-written content hashes from the
        prefix cache (the free count is restored exactly; prefix-HIT
        blocks whose content predates the call stay resident).

        Reverse admission order matters: when prompts within the call
        share a prefix, later sequences prefix-HIT blocks the first one
        allocated (and hashed) moments earlier — unwinding in reverse
        makes the ORIGINATING sequence's abort the last dereference, so
        its invalidate (not a later sibling's plain free) retires the
        never-written hash."""
        for sid in reversed(begun):
            if sid in self.app.kv_mgr.tables:
                self.app.kv_mgr.abort_sequence(sid)
        self.telemetry.on_admission_rollback()
