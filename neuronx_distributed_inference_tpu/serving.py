"""Serving integration surface — the importable continuous-batching
contract a vLLM-style engine drives (reference: the vLLM-facing surface of
models/model_wrapper.py — ``vllm_cte_repadding`` :1297-1313 and the
seq_ids-addressed forward :1315-1440; the reference README's north star is
serving through vLLM).

The engine owns scheduling; this adapter owns device state:

  * ``add_requests(seq_ids, prompts)``  — prefill rows into their cache
    lines (cache rows are addressed BY seq_id, so request order is free)
  * ``step(seq_ids=None)``              — one decode step for the given
    (default: all) running rows, repadded to the compiled batch bucket
  * ``release(seq_ids)``                — free rows (and paged blocks)

Works over either application:
  - ``CausalLMApplication`` with ``is_continuous_batching=True`` —
    contiguous cache rows keyed by seq_id;
  - ``PagedCausalLMApplication`` — block tables keyed by seq_id.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .modules import autobucketing


@dataclass
class _SeqState:
    position: int                 # position of last_token
    last_token: int
    running: bool = True


def _live_rows(seqs: Dict[int, _SeqState],
               seq_ids: Optional[Sequence[int]]) -> List[int]:
    ids = sorted(seqs) if seq_ids is None else list(seq_ids)
    if seq_ids is not None:
        for sid in ids:
            if sid not in seqs:
                raise ValueError(f"seq_id {sid} is not running (released "
                                 "or never added)")
    return [sid for sid in ids if seqs[sid].running]


def _pad_paged_rows(pad_to, ids, pos, slots, bt, last):
    """Repeat row 0 up to the batch bucket; pad rows harmlessly rewrite
    row 0's slots with identical values (reference: vllm_cte_repadding,
    model_wrapper.py:1297-1313)."""
    b = ids.shape[0]
    if b == pad_to:
        return ids, pos, slots, bt, last

    def rep(x):
        return np.concatenate([x, np.repeat(x[:1], pad_to - b, axis=0)])
    return rep(ids), rep(pos), rep(slots), rep(bt), rep(last)


class ContinuousBatchingAdapter:
    """vLLM-style engine adapter over the contiguous app
    (reference: model_wrapper.py:1297-1440)."""

    def __init__(self, app):
        cfg = app.tpu_config
        if not cfg.is_continuous_batching:
            raise ValueError("app must be built with "
                             "is_continuous_batching=True")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}

    # -- capacity ---------------------------------------------------------
    @property
    def free_slots(self) -> List[int]:
        used = set(self.seqs)
        return [i for i in range(self.batch) if i not in used]

    # -- lifecycle --------------------------------------------------------
    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]]) -> Dict[int, int]:
        """Prefill ``prompts`` into cache rows ``seq_ids``. Returns
        {seq_id: first generated token}. Rows are padded to the ctx bucket
        (repeat-row-0 batch pad — reference ``vllm_cte_repadding``)."""
        if len(seq_ids) != len(prompts):
            raise ValueError("seq_ids and prompts length mismatch")
        for sid in seq_ids:
            if not 0 <= sid < self.batch:
                raise ValueError(f"seq_id {sid} out of range [0,{self.batch})")
            if sid in self.seqs:
                raise ValueError(f"seq_id {sid} already running")
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        width = autobucketing.get_target_bucket(self.app.ctx_buckets,
                                                int(lens.max()))
        ids = np.zeros((b, width), np.int32)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        pad_to = self._batch_bucket(b)
        ids_p, sid_p = self._pad_rows(ids, np.asarray(seq_ids, np.int32),
                                      pad_to)
        lens_p = np.concatenate([lens, np.repeat(lens[:1], pad_to - b)])
        out = self.app._run_prefill(ids_p, lens_p, seq_ids=sid_p)
        toks = np.asarray(out["tokens"])[:b]
        res = {}
        for i, sid in enumerate(seq_ids):
            self.seqs[sid] = _SeqState(position=int(lens[i]),
                                       last_token=int(toks[i]))
            res[sid] = int(toks[i])
        return res

    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        """One decode step for ``seq_ids`` (default: every running row).
        Returns {seq_id: next token}."""
        live = _live_rows(self.seqs, seq_ids)
        if not live:
            return {}
        b = len(live)
        pad_to = self._batch_bucket(b)
        sid = np.asarray(live, np.int32)
        toks = np.asarray([self.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([self.seqs[s].position for s in live], np.int32)
        sid_p = np.concatenate([sid, np.repeat(sid[:1], pad_to - b)])
        toks_p = np.concatenate([toks, np.repeat(toks[:1], pad_to - b)])
        pos_p = np.concatenate([pos, np.repeat(pos[:1], pad_to - b)])
        out = self.app._run_decode(toks_p[:, None], pos_p[:, None],
                                   seq_ids=sid_p)
        new = np.asarray(out["tokens"]).reshape(-1)[:b]
        res = {}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            st.last_token = int(new[i])
            res[s] = int(new[i])
        return res

    def release(self, seq_ids: Sequence[int]):
        for sid in seq_ids:
            self.seqs.pop(sid, None)

    # -- helpers ----------------------------------------------------------
    def _batch_bucket(self, b: int) -> int:
        if b > self.batch:
            raise ValueError(f"live batch {b} exceeds compiled batch "
                             f"{self.batch}")
        return autobucketing.get_target_bucket(self.app.batch_buckets, b)

    @staticmethod
    def _pad_rows(ids: np.ndarray, seq_ids: np.ndarray, pad_to: int):
        pad = pad_to - ids.shape[0]
        if pad <= 0:
            return ids, seq_ids
        return (np.concatenate([ids, np.repeat(ids[:1], pad, axis=0)]),
                np.concatenate([seq_ids, np.repeat(seq_ids[:1], pad)]))


class PagedEngineAdapter:
    """vLLM-style engine adapter over the PAGED app: block tables keyed by
    seq_id, slot mappings computed from the tables (reference: the
    slot_mapping / active_block_table contract of
    block_kv_cache_manager.py + model_wrapper.py:1297-1313)."""

    def __init__(self, app):
        cfg = app.tpu_config
        if not cfg.is_block_kv_layout:
            raise ValueError("app must be built with is_block_kv_layout=True")
        self.app = app
        self.batch = cfg.batch_size
        self.seqs: Dict[int, _SeqState] = {}

    def add_requests(self, seq_ids: Sequence[int],
                     prompts: Sequence[Sequence[int]]) -> Dict[int, int]:
        from .modules.block_kv_cache import slots_from_table
        if len(seq_ids) != len(prompts):
            raise ValueError("seq_ids and prompts length mismatch")
        if len(set(seq_ids)) != len(seq_ids):
            raise ValueError("duplicate seq_ids in one add_requests call")
        for sid in seq_ids:
            if sid in self.seqs:
                raise ValueError(f"seq_id {sid} already running")
        app = self.app
        b = len(seq_ids)
        lens = np.asarray([len(p) for p in prompts], np.int32)
        cached = np.zeros((b,), np.int32)
        for i, sid in enumerate(seq_ids):
            _, c = app.kv_mgr.begin_sequence(sid, list(prompts[i]))
            cached[i] = min(c, lens[i] - 1)
        width = autobucketing.get_target_bucket(
            app.ctx_buckets, int((lens - cached).max()))
        bt = app.kv_mgr.block_table_array(seq_ids, app._bt_width_for(seq_ids))
        ids_w = np.zeros((b, width), np.int32)
        pos_w = np.zeros((b, width), np.int32)
        for i, p in enumerate(prompts):
            lo = int(cached[i])
            n = int(lens[i] - lo)
            ids_w[i, :n] = np.asarray(p[lo:lo + n])
            pos_w[i] = lo + np.arange(width, dtype=np.int32)
        valid = np.arange(width)[None, :] < (lens - cached)[:, None]
        slots = slots_from_table(bt, np.where(valid, pos_w, -1),
                                 app.kv_mgr.spec.block_size)
        # repad to the compiled batch bucket (repeat row 0 - pad rows
        # rewrite row 0's slots with identical values); without this every
        # distinct live count would jit a fresh graph mid-serving
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b)
        ids_w, pos_w, slots, bt2, last = _pad_paged_rows(
            pad_to, ids_w, pos_w, slots, bt,
            np.maximum(lens - cached - 1, 0))
        out = app._run_paged(ids_w, pos_w, slots, bt2, last)
        toks = np.asarray(out["tokens"]).reshape(-1)
        res = {}
        for i, sid in enumerate(seq_ids):
            self.seqs[sid] = _SeqState(position=int(lens[i]),
                                       last_token=int(toks[i]))
            res[sid] = int(toks[i])
        return res

    def step(self, seq_ids: Optional[Sequence[int]] = None) -> Dict[int, int]:
        from .modules.block_kv_cache import slots_from_table
        app = self.app
        live = _live_rows(self.seqs, seq_ids)
        if not live:
            return {}
        b = len(live)
        toks = np.asarray([self.seqs[s].last_token for s in live], np.int32)
        pos = np.asarray([self.seqs[s].position for s in live], np.int32)
        for s in live:
            app.kv_mgr.grow(s, 1)
        bt = app.kv_mgr.block_table_array(live, app._bt_width_for(live))
        slots = slots_from_table(bt, pos[:, None],
                                 app.kv_mgr.spec.block_size)
        pad_to = autobucketing.get_target_bucket(app.batch_buckets, b)
        ids_p, pos_p, slots_p, bt_p, last_p = _pad_paged_rows(
            pad_to, toks[:, None], pos[:, None], slots, bt,
            np.zeros((b,), np.int32))
        out = app._run_paged(ids_p, pos_p, slots_p, bt_p, last_p)
        new = np.asarray(out["tokens"]).reshape(-1)[:b]
        res = {}
        for i, s in enumerate(live):
            st = self.seqs[s]
            st.position += 1
            st.last_token = int(new[i])
            res[s] = int(new[i])
        return res

    def release(self, seq_ids: Sequence[int]):
        for sid in seq_ids:
            if sid in self.seqs:
                self.seqs.pop(sid)
                if sid in self.app.kv_mgr.tables:
                    self.app.kv_mgr.end_sequence(sid)
