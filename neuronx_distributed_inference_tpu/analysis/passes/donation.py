"""donation-safety: never read a donated cache binding after the
dispatch that consumed it.

Every device graph in this codebase takes the KV cache with
``jax.jit(..., donate_argnums=...)``: the moment the dispatch call is
issued, the caller's array is CONSUMED — XLA may reuse its buffer for
the output — and any subsequent host read of the old binding observes
garbage (or raises a deleted-buffer error much later, on hardware only).
This is the ``retry_safe=False`` state-loss class: the ``_run_*``
helpers all rebind ``self.cache = out["cache"]`` on the very next line,
and this pass makes that convention a checked contract.

Per-function linear dataflow (statements flattened in source order, the
documented approximation — loop back-edges are not modeled, which is
safe here because every dispatch is followed by its rebind in straight
line code):

  * tracked bindings: attribute chains ending in ``.cache`` /
    ``.draft_cache`` (``self.cache``, ``app.cache``, ...) and local
    aliases assigned from a tracked chain (bare ``cache`` parameters
    are functional values inside traced code, not host bindings);
  * passing a tracked binding as a CALL ARGUMENT marks it consumed
    (over-approximate by design: a helper that takes the cache without
    donating it should be rare enough to earn an inline suppression
    with a reason);
  * a store to the binding (``self.cache = out["cache"]``, tuple
    targets included) cleans it;
  * any read while consumed is a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from ..findings import Finding
from ..registry import LintContext, Pass, register
from ..walker import (assignment_targets, dotted, linear_statements,
                      statement_expressions)

DONATED_ATTRS = ("cache", "draft_cache")

DEFAULT_PATHS = (
    "neuronx_distributed_inference_tpu/models/application.py",
    "neuronx_distributed_inference_tpu/models/speculation.py",
    "neuronx_distributed_inference_tpu/serving/adapter.py",
    "neuronx_distributed_inference_tpu/serving/speculation/proposer.py",
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py",
    "neuronx_distributed_inference_tpu/utils/host_loop.py",
)


def _tracked_chain(node: ast.AST) -> Optional[str]:
    """The tracked binding key for an expression, if any: an ATTRIBUTE
    chain whose last component is a donated attr (``self.cache``,
    ``app.draft_cache``). Bare names are deliberately not tracked — a
    ``cache`` parameter inside a traced/pure function is consumed
    functionally (run_layers takes it and returns the new one), which is
    not the host-layer donation contract; host code holds the donated
    binding on an object, and local aliases of those chains are tracked
    through the alias map."""
    chain = dotted(node)
    if chain is None or "." not in chain:
        return None
    last = chain.rsplit(".", 1)[-1]
    return chain if last in DONATED_ATTRS else None


class _FunctionFlow:
    """Linear consumed/clean tracking for one function scope."""

    def __init__(self, pass_name: str, rel: str, fn: ast.AST):
        self.pass_name = pass_name
        self.rel = rel
        self.fn = fn
        self.consumed: Dict[str, int] = {}     # binding -> consuming line
        self.aliases: Dict[str, str] = {}      # local name -> chain
        self.findings: List[Finding] = []

    def _key(self, node: ast.AST) -> Optional[str]:
        chain = _tracked_chain(node)
        if chain is not None:
            return chain
        name = dotted(node)
        return name if name in self.aliases else None

    def run(self) -> List[Finding]:
        for stmt in linear_statements(self.fn):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            targets = assignment_targets(stmt)
            target_ids = {id(t) for t in targets}
            reads: List[ast.AST] = []
            consumes: List[ast.AST] = []
            for node in statement_expressions(stmt):
                if isinstance(node, ast.Call):
                    for arg in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Starred):
                            arg = arg.value
                        if self._key(arg) is not None:
                            consumes.append(arg)
                key = self._key(node)
                if key is not None and id(node) not in target_ids and \
                        isinstance(getattr(node, "ctx", None), ast.Load):
                    reads.append(node)
            consume_ids = {id(c) for c in consumes}
            # 1) reads first: a read that is itself the consuming
            #    argument is legal when the binding was clean
            for node in reads:
                key = self._key(node)
                dline = self.consumed.get(key)
                if dline is not None and id(node) not in consume_ids:
                    self.findings.append(Finding(
                        self.pass_name, self.rel, node.lineno,
                        f"read of donated binding {key!r} after the "
                        f"dispatch on line {dline} consumed it "
                        "(donate_argnums) — the old buffer is invalid; "
                        "rebind it from the dispatch output first"))
            for node in reads:
                key = self._key(node)
                if self.consumed.get(key) is not None and \
                        id(node) in consume_ids:
                    self.findings.append(Finding(
                        self.pass_name, self.rel, node.lineno,
                        f"donated binding {key!r} passed to another call "
                        f"after the dispatch on line "
                        f"{self.consumed[key]} consumed it — double "
                        "consumption of a dead buffer"))
            # 2) then mark consumption ...
            for node in consumes:
                key = self._key(node)
                self.consumed.setdefault(key, node.lineno)
            # 3) ... and let stores clean / create aliases
            for tgt in targets:
                key = self._key(tgt)
                if key is not None:
                    self.consumed.pop(key, None)
                if isinstance(tgt, ast.Name) and isinstance(stmt, ast.Assign):
                    chain = _tracked_chain(stmt.value)
                    if chain is not None:
                        self.aliases[tgt.id] = chain
                    else:
                        self.aliases.pop(tgt.id, None)
        return self.findings


@register
class DonationSafetyPass(Pass):
    name = "donation-safety"
    description = ("no read of a donated cache binding after the "
                   "dispatch that consumed it (donate_argnums "
                   "state-loss class)")
    default_paths = DEFAULT_PATHS

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        for sf in self._sources(ctx, paths, findings):
            for info in sf.functions():
                findings.extend(
                    _FunctionFlow(self.name, sf.rel, info.node).run())
        return findings
