"""spmd-golden: the SPMD census golden and the compile lint's pinned
graph set cannot drift apart.

The in-process, no-compile slice of ``scripts/check_spmd_sharding.py``
(the full CPU-mesh compile lint stays in that script — it is minutes of
XLA work, not a sub-second AST pass): the committed
``artifacts/spmd_golden.json`` must carry the expected schema, pin
exactly the graphs the script's ``PINNED`` table compiles (both
directions — a graph added to the code but never ``--update-golden``\\ ed,
or left in the golden after being dropped from the code, is the same
stale-pin class the old hardcoded file counts kept hitting), and every
pinned census entry must be well-formed (count/bytes ints).
"""

from __future__ import annotations

import ast
import json
from typing import List, Optional, Sequence

from ..findings import Finding
from ..registry import LintContext, Pass, register

LINT_SCRIPT = "scripts/check_spmd_sharding.py"
GOLDEN_PATH = "artifacts/spmd_golden.json"
GOLDEN_SCHEMA = "nxdi-spmd-golden-v1"


def pinned_graphs(tree: ast.AST):
    """(lineno, names) of the module-level ``PINNED`` dict in the compile
    lint — read via AST so this pass never imports jax."""
    for node in tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == "PINNED" and \
                    isinstance(node.value, ast.Dict):
                names = [k.value for k in node.value.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)]
                return node.lineno, names
    return None, []


@register
class SpmdGoldenPass(Pass):
    name = "spmd-golden"
    description = ("artifacts/spmd_golden.json stays schema-valid and in "
                   "sync with check_spmd_sharding's PINNED graph set")
    default_paths = (LINT_SCRIPT, GOLDEN_PATH)

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        script_rel, golden_rel = (paths if paths is not None
                                  else self.default_paths)
        script_sf = ctx.source_for(script_rel)
        golden_sf = ctx.source_for(golden_rel)
        if script_sf is None:
            return [self.missing(str(script_rel))]
        if golden_sf is None:
            return [Finding(self.name, str(golden_rel), 0,
                            "golden is missing — run scripts/"
                            "check_spmd_sharding.py --update-golden")]
        pin_line, pinned = pinned_graphs(script_sf.tree)
        findings: List[Finding] = []
        if not pinned:
            return [Finding(self.name, script_sf.rel, 1,
                            "no module-level PINNED graph table found — "
                            "the compile lint moved without this pass")]
        try:
            golden = json.loads(golden_sf.text)
        except ValueError as e:
            return [Finding(self.name, golden_sf.rel, 1,
                            f"golden is not valid JSON: {e}")]
        if golden.get("schema") != GOLDEN_SCHEMA:
            findings.append(Finding(
                self.name, golden_sf.rel, 1,
                f"schema {golden.get('schema')!r} != {GOLDEN_SCHEMA!r}"))
            return findings
        graphs = golden.get("graphs")
        if not isinstance(graphs, dict):
            return [Finding(self.name, golden_sf.rel, 1,
                            "golden has no 'graphs' table")]
        for name in sorted(set(pinned) - set(graphs)):
            findings.append(Finding(
                self.name, script_sf.rel, pin_line,
                f"pinned graph {name!r} has no golden census — run "
                "check_spmd_sharding.py --update-golden to pin it"))
        for name in sorted(set(graphs) - set(pinned)):
            findings.append(Finding(
                self.name, golden_sf.rel, 1,
                f"golden pins {name!r} but the compile lint no longer "
                "builds it — stale entry; re-earn the golden with a "
                "full --update-golden run"))
        for name, entry in sorted(graphs.items()):
            coll = entry.get("collectives") if isinstance(entry, dict) \
                else None
            if not isinstance(coll, dict):
                findings.append(Finding(
                    self.name, golden_sf.rel, 1,
                    f"golden graph {name!r} has no 'collectives' table"))
                continue
            for key, c in sorted(coll.items()):
                if not (isinstance(c, dict)
                        and isinstance(c.get("count"), int)
                        and isinstance(c.get("bytes"), int)):
                    findings.append(Finding(
                        self.name, golden_sf.rel, 1,
                        f"golden census {name}/{key} is malformed — "
                        "expected {count: int, bytes: int}"))
        return findings
