"""metric-names: the metric-name contract and the README table cannot
drift.

Port of the PR-7 ``scripts/check_metric_names.py`` checker: every
``nxdi_*`` string constant registered in ``telemetry/metrics.py`` must
appear in the README "Observability" metric table, and every ``nxdi_*``
name in that table must be a registered constant — symmetric, like the
SPMD golden.

Extended (ISSUE 14) with the **helper contract**: every builder helper
in ``telemetry/metrics.py`` (a module-level function taking ``reg`` and
returning ``reg.counter/gauge/histogram(...)``) must name its instrument
through an ``nxdi_``-prefixed module constant (or literal) and pass
non-empty help text — so an instrument can never be registered under an
undocumentable name or with a blank description (rename-red verified by
``tests/test_slo_observability.py``).

Extended (ISSUE 16) with the **label contract**: every label a helper
declares (``labels=("kind", "bucket")``) must appear backticked in the
README table row documenting that metric — so a label added to an
instrument (a new dimension on the scrape surface, a stable contract
like the name itself) cannot ship undocumented, and a documented label
dropped from the code reads as the stale row it is.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set

from ..findings import Finding
from ..registry import LintContext, Pass, register

METRICS_PATH = "neuronx_distributed_inference_tpu/telemetry/metrics.py"
README_PATH = "README.md"

_NAME_RE = re.compile(r"nxdi_[a-z0-9_]+")


def registered_names(tree: ast.AST) -> Set[str]:
    """``nxdi_*`` string constants assigned at module level in
    telemetry/metrics.py — the canonical registration point."""
    return set(constant_map(tree).values())


def constant_map(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``CONSTANT = "nxdi_..."`` assignments, constant name
    -> metric name (the helper contract resolves ``reg.counter(NAME)``
    references through this)."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, str)
                and value.value.startswith("nxdi_")):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = value.value
    return out


_INSTRUMENT_KINDS = ("counter", "gauge", "histogram")


def helper_findings(pass_name: str, rel: str, tree: ast.AST,
                    constants: Dict[str, str]) -> List[Finding]:
    """The helper contract over telemetry/metrics.py: every module-level
    function whose first parameter is ``reg`` must build its instrument
    via ``reg.counter/gauge/histogram(<nxdi_ constant>, <non-empty
    help>, ...)`` — a helper with no instrument call, an unresolvable or
    un-prefixed name, or blank/missing help text is a finding."""
    findings: List[Finding] = []
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        args = node.args.args
        if not args or args[0].arg != "reg":
            continue
        calls = [c for c in ast.walk(node)
                 if isinstance(c, ast.Call)
                 and isinstance(c.func, ast.Attribute)
                 and c.func.attr in _INSTRUMENT_KINDS
                 and isinstance(c.func.value, ast.Name)
                 and c.func.value.id == "reg"]
        if not calls:
            findings.append(Finding(
                pass_name, rel, node.lineno,
                f"helper {node.name}() takes `reg` but never builds an "
                "instrument (reg.counter/gauge/histogram) — dead helper "
                "or a bypass of the canonical registration point"))
            continue
        for call in calls:
            findings.extend(_check_instrument_call(pass_name, rel,
                                                   node.name, call,
                                                   constants))
    return findings


def _check_instrument_call(pass_name: str, rel: str, fn: str,
                           call: ast.Call,
                           constants: Dict[str, str]) -> List[Finding]:
    findings: List[Finding] = []
    name_arg = call.args[0] if call.args else None
    metric_name = None
    if isinstance(name_arg, ast.Constant) and isinstance(name_arg.value,
                                                         str):
        metric_name = name_arg.value
    elif isinstance(name_arg, ast.Name):
        metric_name = constants.get(name_arg.id)
    if metric_name is None:
        findings.append(Finding(
            pass_name, rel, call.lineno,
            f"helper {fn}() builds an instrument whose name is not a "
            "module-level nxdi_* constant (or literal) — the README "
            "table lint cannot see it"))
    elif not metric_name.startswith("nxdi_"):
        findings.append(Finding(
            pass_name, rel, call.lineno,
            f"helper {fn}() registers {metric_name!r} — metric names "
            "must carry the nxdi_ prefix (stable-contract namespace)"))
    # help text: second positional arg or help= keyword
    help_arg = call.args[1] if len(call.args) > 1 else next(
        (kw.value for kw in call.keywords if kw.arg == "help"), None)
    if not (isinstance(help_arg, ast.Constant)
            and isinstance(help_arg.value, str)
            and help_arg.value.strip()):
        findings.append(Finding(
            pass_name, rel, call.lineno,
            f"helper {fn}() registers an instrument without non-empty "
            "help text — every exposed metric must describe itself"))
    return findings


def label_map(tree: ast.AST,
              constants: Dict[str, str]) -> Dict[str, List[str]]:
    """metric name -> declared label names, read from the helpers'
    instrument calls (``labels=("a", "b")`` keyword of
    ``reg.counter/gauge/histogram``). Non-literal label expressions are
    skipped here — the helper contract already flags unresolvable
    registrations."""
    out: Dict[str, List[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        for call in ast.walk(node):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in _INSTRUMENT_KINDS
                    and isinstance(call.func.value, ast.Name)
                    and call.func.value.id == "reg"):
                continue
            name_arg = call.args[0] if call.args else None
            if isinstance(name_arg, ast.Constant) and \
                    isinstance(name_arg.value, str):
                metric = name_arg.value
            elif isinstance(name_arg, ast.Name):
                metric = constants.get(name_arg.id)
            else:
                metric = None
            if metric is None:
                continue
            labels = [elt.value
                      for kw in call.keywords if kw.arg == "labels"
                      and isinstance(kw.value, (ast.Tuple, ast.List))
                      for elt in kw.value.elts
                      if isinstance(elt, ast.Constant)
                      and isinstance(elt.value, str)]
            if labels:
                out.setdefault(metric, [])
                out[metric].extend(l for l in labels
                                   if l not in out[metric])
    return out


def documented_rows(readme_source: str) -> Dict[str, List[str]]:
    """``nxdi_*`` name -> the README Observability table rows mentioning
    it (a name normally has exactly one row of record)."""
    lines = readme_source.splitlines()
    try:
        start = next(i for i, l in enumerate(lines)
                     if l.strip() == "## Observability")
    except StopIteration:
        return {}
    rows: Dict[str, List[str]] = {}
    for line in lines[start + 1:]:
        if line.startswith("## "):
            break
        if line.lstrip().startswith("|"):
            for nm in _NAME_RE.findall(line):
                rows.setdefault(nm, []).append(line)
    return rows


def label_findings(pass_name: str, readme_rel: str,
                   rows: Dict[str, List[str]],
                   labels_by_name: Dict[str, List[str]]) -> List[Finding]:
    """The label contract: every declared label of a documented metric
    must appear backticked in (at least one of) that metric's README
    table rows."""
    findings: List[Finding] = []
    for metric, labels in sorted(labels_by_name.items()):
        metric_rows = rows.get(metric)
        if not metric_rows:
            continue           # undocumented name → the name diff flags it
        missing = [l for l in labels
                   if not any(f"`{l}`" in row for row in metric_rows)]
        for l in missing:
            findings.append(Finding(
                pass_name, readme_rel, 1,
                f"{metric} declares label `{l}` in metrics.py but its "
                "README Observability row never mentions it — labels are "
                "scrape-surface contract; document the dimension"))
    return findings


def documented_names(readme_source: str) -> Set[str]:
    """``nxdi_*`` names in the README Observability metric table (table
    rows only — prose mentions elsewhere are cross-references, not
    documentation of record)."""
    lines = readme_source.splitlines()
    try:
        start = next(i for i, l in enumerate(lines)
                     if l.strip() == "## Observability")
    except StopIteration:
        return set()
    names: Set[str] = set()
    for line in lines[start + 1:]:
        if line.startswith("## "):
            break
        if line.lstrip().startswith("|"):
            names.update(_NAME_RE.findall(line))
    return names


@register
class MetricNamesPass(Pass):
    name = "metric-names"
    description = ("telemetry nxdi_* name constants and the README "
                   "Observability table stay in sync, both directions; "
                   "every metrics.py helper registers an nxdi_-named "
                   "instrument with non-empty help; declared labels are "
                   "documented backticked in the metric's README row")
    default_paths = (METRICS_PATH, README_PATH)

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        metrics_rel, readme_rel = (paths if paths is not None
                                   else self.default_paths)
        findings: List[Finding] = []
        metrics_sf = ctx.source_for(metrics_rel)
        readme_sf = ctx.source_for(readme_rel)
        if metrics_sf is None:
            return [self.missing(str(metrics_rel))]
        if readme_sf is None:
            return [self.missing(str(readme_rel))]
        if metrics_sf.tree is None:
            return [Finding(self.name, metrics_sf.rel, 1,
                            "not parseable as Python — wrong file?")]
        constants = constant_map(metrics_sf.tree)
        registered = set(constants.values())
        documented = documented_names(readme_sf.text)
        findings.extend(helper_findings(self.name, metrics_sf.rel,
                                        metrics_sf.tree, constants))
        if not registered:
            # keep any helper-contract findings already collected: a
            # constants-free metrics file is exactly where helpers go
            # rogue with literals, and those findings are the point
            return findings + [Finding(
                self.name, metrics_sf.rel, 1,
                "no nxdi_* constants found — wrong file?")]
        if not documented:
            return findings + [Finding(
                self.name, readme_sf.rel, 1,
                "no Observability metric table found — wrong file?")]
        for nm in sorted(registered - documented):
            findings.append(Finding(
                self.name, readme_sf.rel, 1,
                f"{nm} is registered in metrics.py but missing from the "
                "README Observability table — document it (names are a "
                "stable contract)"))
        for nm in sorted(documented - registered):
            findings.append(Finding(
                self.name, readme_sf.rel, 1,
                f"{nm} appears in the README Observability table but is "
                "not registered in metrics.py — typo or leftover row"))
        findings.extend(label_findings(
            self.name, readme_sf.rel, documented_rows(readme_sf.text),
            label_map(metrics_sf.tree, constants)))
        return findings
