"""metric-names: the metric-name contract and the README table cannot
drift.

Port of the PR-7 ``scripts/check_metric_names.py`` checker: every
``nxdi_*`` string constant registered in ``telemetry/metrics.py`` must
appear in the README "Observability" metric table, and every ``nxdi_*``
name in that table must be a registered constant — symmetric, like the
SPMD golden.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Set

from ..findings import Finding
from ..registry import LintContext, Pass, register

METRICS_PATH = "neuronx_distributed_inference_tpu/telemetry/metrics.py"
README_PATH = "README.md"

_NAME_RE = re.compile(r"nxdi_[a-z0-9_]+")


def registered_names(tree: ast.AST) -> Set[str]:
    """``nxdi_*`` string constants assigned at module level in
    telemetry/metrics.py — the canonical registration point."""
    names: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if (isinstance(value, ast.Constant) and isinstance(value.value, str)
                and value.value.startswith("nxdi_")):
            names.add(value.value)
    return names


def documented_names(readme_source: str) -> Set[str]:
    """``nxdi_*`` names in the README Observability metric table (table
    rows only — prose mentions elsewhere are cross-references, not
    documentation of record)."""
    lines = readme_source.splitlines()
    try:
        start = next(i for i, l in enumerate(lines)
                     if l.strip() == "## Observability")
    except StopIteration:
        return set()
    names: Set[str] = set()
    for line in lines[start + 1:]:
        if line.startswith("## "):
            break
        if line.lstrip().startswith("|"):
            names.update(_NAME_RE.findall(line))
    return names


@register
class MetricNamesPass(Pass):
    name = "metric-names"
    description = ("telemetry nxdi_* name constants and the README "
                   "Observability table stay in sync, both directions")
    default_paths = (METRICS_PATH, README_PATH)

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        metrics_rel, readme_rel = (paths if paths is not None
                                   else self.default_paths)
        findings: List[Finding] = []
        metrics_sf = ctx.source_for(metrics_rel)
        readme_sf = ctx.source_for(readme_rel)
        if metrics_sf is None:
            return [self.missing(str(metrics_rel))]
        if readme_sf is None:
            return [self.missing(str(readme_rel))]
        if metrics_sf.tree is None:
            return [Finding(self.name, metrics_sf.rel, 1,
                            "not parseable as Python — wrong file?")]
        registered = registered_names(metrics_sf.tree)
        documented = documented_names(readme_sf.text)
        if not registered:
            return [Finding(self.name, metrics_sf.rel, 1,
                            "no nxdi_* constants found — wrong file?")]
        if not documented:
            return [Finding(self.name, readme_sf.rel, 1,
                            "no Observability metric table found — "
                            "wrong file?")]
        for nm in sorted(registered - documented):
            findings.append(Finding(
                self.name, readme_sf.rel, 1,
                f"{nm} is registered in metrics.py but missing from the "
                "README Observability table — document it (names are a "
                "stable contract)"))
        for nm in sorted(documented - registered):
            findings.append(Finding(
                self.name, readme_sf.rel, 1,
                f"{nm} appears in the README Observability table but is "
                "not registered in metrics.py — typo or leftover row"))
        return findings
