"""perf-drift: the committed perf baseline stays well-formed and its
cross-artifact pins stay true.

The in-process, no-measure slice of ``scripts/check_perf_drift.py``
(the live re-measure — a full ragged mixed-load run plus a precompile
walk — stays in that script; it is tens of seconds of jax work, not a
sub-second pass): the committed ``artifacts/perf_baseline_r16.json``
(``bench.py --perf-snapshot``) must carry the expected schema, a
numeric value for every tracked metric, a tolerance entry for every
metric and no orphan tolerances (both directions — a metric added to
the snapshot but never gated, or a tolerance left behind after a
metric was dropped, is the same silent-ungating class the metric-names
pass exists for), the serving-structural metrics must actually be
GATED (a ``None`` tolerance on ``dispatches_per_step`` would turn the
drift gate into folklore), and the baseline's
``golden_collective_bytes`` must equal the sum recomputed from the
committed ``artifacts/spmd_golden.json`` — the two goldens cannot
drift apart.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..findings import Finding
from ..registry import LintContext, Pass, register

BASELINE_PATH = "artifacts/perf_baseline_r16.json"
SPMD_GOLDEN_PATH = "artifacts/spmd_golden.json"
BASELINE_SCHEMA = "nxdi-perf-baseline-v1"

#: metrics whose tolerance must be a number (gated), never None: the
#: serving-path structural proxies the drift gate exists to protect.
MUST_GATE = ("dispatches_per_step", "materialized_per_step",
             "ragged_pad_waste", "precompile_graphs",
             "golden_collective_bytes", "migrations_per_drain",
             "recompute_avoided_tokens", "lora_dispatches_per_step",
             "lora_swap_bytes")


def golden_bytes_total(golden: Dict[str, Any]) -> int:
    """Total collective payload (bytes x count over every pinned graph)
    of an ``nxdi-spmd-golden-v1`` census — the number the baseline pins."""
    return sum(c["bytes"] * c["count"]
               for g in golden.get("graphs", {}).values()
               for c in g.get("collectives", {}).values()
               if isinstance(c, dict))


def validate_baseline(baseline: Any) -> List[Tuple[str, str]]:
    """Structural findings of one parsed baseline payload as
    ``(where, message)`` tuples — shared by the registered pass and
    ``scripts/check_perf_drift.py`` so the two never disagree about
    well-formedness."""
    out: List[Tuple[str, str]] = []
    if not isinstance(baseline, dict):
        return [("baseline", "payload is not a JSON object")]
    if baseline.get("schema") != BASELINE_SCHEMA:
        return [("schema",
                 f"schema {baseline.get('schema')!r} != "
                 f"{BASELINE_SCHEMA!r} — re-run bench.py --perf-snapshot")]
    metrics = baseline.get("metrics")
    tol = baseline.get("tolerances")
    if not isinstance(metrics, dict) or not metrics:
        return [("metrics", "no 'metrics' table — empty baseline")]
    if not isinstance(tol, dict):
        return [("tolerances", "no 'tolerances' table — nothing is gated")]
    for name, v in sorted(metrics.items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            out.append((f"metrics.{name}",
                        f"value {v!r} is not a number"))
    for name in sorted(set(metrics) - set(tol)):
        out.append((f"metrics.{name}",
                    "tracked metric has no tolerance entry — silently "
                    "ungated; add it to 'tolerances' (None = "
                    "informational, on purpose and visible)"))
    for name in sorted(set(tol) - set(metrics)):
        out.append((f"tolerances.{name}",
                    "tolerance for a metric the snapshot no longer "
                    "measures — stale entry"))
    for name, t in sorted(tol.items()):
        if t is None:
            continue
        if not isinstance(t, (int, float)) or isinstance(t, bool) or t < 0:
            out.append((f"tolerances.{name}",
                        f"tolerance {t!r} is not a non-negative number"))
    for name in MUST_GATE:
        if name in metrics and tol.get(name) is None:
            out.append((f"tolerances.{name}",
                        "structural serving metric must be gated — a "
                        "None tolerance here disables the drift gate"))
    return out


@register
class PerfDriftPass(Pass):
    name = "perf-drift"
    description = ("artifacts/perf_baseline_r16.json stays schema-valid, "
                   "fully gated, and byte-consistent with the SPMD golden")
    default_paths = (BASELINE_PATH, SPMD_GOLDEN_PATH)

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        baseline_rel, golden_rel = (paths if paths is not None
                                    else self.default_paths)
        base_sf = ctx.source_for(baseline_rel)
        if base_sf is None:
            return [Finding(self.name, str(baseline_rel), 0,
                            "baseline is missing — run bench.py "
                            "--perf-snapshot to commit one")]
        try:
            baseline = json.loads(base_sf.text)
        except ValueError as e:
            return [Finding(self.name, base_sf.rel, 1,
                            f"baseline is not valid JSON: {e}")]
        findings = [Finding(self.name, base_sf.rel, 1,
                            f"{where}: {msg}")
                    for where, msg in validate_baseline(baseline)]
        if findings:
            return findings
        golden_sf = ctx.source_for(golden_rel)
        if golden_sf is None:
            return findings + [Finding(
                self.name, str(golden_rel), 0,
                "SPMD golden is missing — the baseline's "
                "golden_collective_bytes pin has nothing to check")]
        try:
            golden = json.loads(golden_sf.text)
        except ValueError as e:
            return findings + [Finding(self.name, golden_sf.rel, 1,
                                       f"golden is not valid JSON: {e}")]
        pinned = baseline["metrics"].get("golden_collective_bytes")
        actual = golden_bytes_total(golden)
        if pinned is not None and pinned != actual:
            findings.append(Finding(
                self.name, base_sf.rel, 1,
                f"golden_collective_bytes {pinned} != {actual} summed "
                "from artifacts/spmd_golden.json — the SPMD golden moved "
                "without a deliberate re-baseline (bench.py "
                "--perf-snapshot)"))
        return findings
