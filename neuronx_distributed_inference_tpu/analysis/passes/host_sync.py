"""host-sync: no host-blocking materialization in the dispatch region.

Port of the PR-3 ``scripts/check_host_sync.py`` checker, with the
hand-maintained EXPECTED_REGIONS table replaced by a coverage guard the
shared walker DERIVES (it needed manual updates in PRs 5, 6 and 9):

  * any function whose name starts with ``_dispatch`` must not contain a
    call spelled with a blocking/materializing attribute
    (``asarray``/``array``/``device_get``/``block_until_ready``/
    ``item``/``tolist``) — the blocking fetch belongs in the
    retire/fetch helpers, one async hop behind;
  * **derived-coverage guard** (default file set only): a function that
    issues dispatch work — calls ``_async_fetch``, calls a ``_run_*``
    dispatch primitive on an ``.app`` receiver (alias-tracked:
    ``app = self.app`` counts), or drives ``.step``/``.step_many`` on an
    ``.adapter`` receiver — without also materializing (no ``_fetch*``
    helper call and no blocking attribute of its own) IS a dispatch
    region by construction, and must carry the ``_dispatch`` prefix or
    the region lint silently loses it. A rename now moves coverage
    automatically instead of needing a list edit.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Tuple

from ..findings import Finding
from ..registry import LintContext, Pass, register
from ..walker import SourceFile, dotted, local_aliases, walk_shallow

BANNED_ATTRS = ("asarray", "array", "device_get", "block_until_ready",
                "item", "tolist")
REGION_PREFIX = "_dispatch"
_RUN_PRIMITIVE = re.compile(r"^_run_[a-z0-9_]+$")

DEFAULT_PATHS = (
    "neuronx_distributed_inference_tpu/serving/adapter.py",
    "neuronx_distributed_inference_tpu/serving/engine/scheduler.py",
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py",
    "neuronx_distributed_inference_tpu/serving/ragged/planner.py",
    "neuronx_distributed_inference_tpu/serving/ragged/path.py",
    "neuronx_distributed_inference_tpu/serving/fleet/router.py",
    "neuronx_distributed_inference_tpu/serving/fleet/kv_tier.py",
    "neuronx_distributed_inference_tpu/serving/fleet/handoff.py",
    "neuronx_distributed_inference_tpu/serving/fleet/autoscaler.py",
    "neuronx_distributed_inference_tpu/serving/fleet/loadgen.py",
    "neuronx_distributed_inference_tpu/serving/lora_pool.py",
    "neuronx_distributed_inference_tpu/parallel/collectives.py",
    "neuronx_distributed_inference_tpu/resilience/controller.py",
    "neuronx_distributed_inference_tpu/resilience/chaos.py",
)


def region_functions(sf: SourceFile) -> List[str]:
    """Names of every dispatch-region function in the file."""
    return [info.name for info in sf.functions()
            if info.name.startswith(REGION_PREFIX)]


def blocking_calls(tree: ast.AST) -> List[Tuple[int, str, str]]:
    """(lineno, function, attr) for every banned call inside a dispatch
    region function."""
    bad: List[Tuple[int, str, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith(REGION_PREFIX):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr in BANNED_ATTRS:
                bad.append((sub.lineno, node.name, fn.attr))
    return bad


def dispatch_signal(sf: SourceFile, fn: ast.AST) -> Optional[str]:
    """The derived is-this-a-dispatch-region test: returns a description
    of the dispatch work a NON-materializing function issues, or None.
    Functions that fetch (call a ``_fetch*`` helper or a blocking
    attribute themselves) are the synchronous dispatch+fetch shape —
    exempt, because their materialization is local and visible."""
    app_aliases = local_aliases(fn, ".app")
    signal = None
    for node in walk_shallow(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None:
            continue
        head, _, last = name.rpartition(".")
        if last in BANNED_ATTRS or last.startswith("_fetch"):
            return None                        # it materializes: exempt
        if signal is not None:
            continue
        if name == "_async_fetch":
            signal = "starts an async device fetch (_async_fetch)"
        elif _RUN_PRIMITIVE.match(last) and head and (
                head.endswith(".app") or head in app_aliases):
            signal = f"calls the dispatch primitive {name}"
        elif last in ("step", "step_many") and head.endswith(".adapter"):
            signal = f"drives the adapter decode surface ({name})"
    return signal


@register
class HostSyncPass(Pass):
    name = "host-sync"
    description = ("_dispatch regions never materialize device output; "
                   "dispatch-issuing functions must carry the _dispatch "
                   "prefix (derived coverage, no hand-pinned region list)")
    default_paths = DEFAULT_PATHS

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        guard = paths is None      # derived guard on the default set only
        for sf in self._sources(ctx, paths, findings):
            for lineno, func, attr in blocking_calls(sf.tree):
                findings.append(Finding(
                    self.name, sf.rel, lineno,
                    f".{attr}(...) inside dispatch-region function "
                    f"{func!r} — device output must not be materialized "
                    "before retire/fetch (decode pipeline contract)"))
            if not guard:
                continue
            for info in sf.functions():
                if info.name.startswith(REGION_PREFIX):
                    continue
                signal = dispatch_signal(sf, info.node)
                if signal is not None:
                    findings.append(Finding(
                        self.name, sf.rel, info.node.lineno,
                        f"{info.qualname} {signal} without materializing "
                        "— it is a dispatch region by construction but "
                        "lacks the _dispatch prefix, so the host-sync "
                        "region lint does not cover it; rename it "
                        "_dispatch_* (coverage follows the prefix) or "
                        "move the dispatch into a _dispatch_* helper"))
        return findings
