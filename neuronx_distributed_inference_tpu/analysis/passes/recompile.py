"""recompile-hazard: silent bucket-ladder cache misses inside traced
code.

Cold-start grew 5.7s -> 14.3s across rounds 1-3 (ROADMAP item 5) and
every miss of the jit cache inside the serving hot path is a multi-
second stall a dashboard only sees as tail latency. This pass derives
the TRACED REGION with the shared walker and flags the constructs that
either crash under tracing or silently fork the graph key:

  * **region derivation**: ``jax.jit(...)`` sites in the jit-site files
    (``application.py``, the speculation stacks) name their roots —
    ``partial(model_base.X, ...)`` resolves into ``model_base.py``,
    bare/partial local names resolve to functions defined in the same
    file (e.g. a ``chain`` closure) — then the region closes over every
    module-level function a traced function calls within its own file,
    plus nested ``def``\\ s (scan/loop bodies);
  * ``.item()`` / ``.tolist()`` anywhere in the region: host
    materialization — a crash on a traced value, a baked-in constant
    (= per-value recompile) on a concrete one;
  * ``float(x)`` / ``int(x)`` where ``x`` mentions a traced name
    (parameters minus config-like ones and jit ``static_argnames``,
    plus locals derived from them): concretization that either raises
    ``TracerConversionError`` or bakes a constant;
  * ``np.*(...)`` (real numpy, alias-resolved) over a traced name: same
    class, via host numpy;
  * iteration over a ``set(...)`` / set literal / ``.keys()`` view in
    the region: nondeterministic order feeding shape math or cache-key
    construction makes equal inputs hash to different graphs;
  * a nested traced function capturing a name the enclosing scope
    mutates with ``+=``-style AugAssign: each trace bakes a different
    Python scalar (closure-capture hazard).

Config-like parameters (``spec``/``cfg``/``tpu_cfg``/... and anything
annotated ``DecoderSpec``/``TpuConfig``/``InferenceConfig``) are static
by contract and never tainted.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..findings import Finding
from ..registry import LintContext, Pass, register
from ..walker import SourceFile, dotted, walk_shallow

JIT_SITE_PATHS = (
    "neuronx_distributed_inference_tpu/models/application.py",
    "neuronx_distributed_inference_tpu/models/speculation.py",
    "neuronx_distributed_inference_tpu/serving/speculation/proposer.py",
)
REGION_PATHS = (
    "neuronx_distributed_inference_tpu/models/model_base.py",
    # quantized-collective call chain: model_base._row_parallel_out ->
    # layers.row_parallel_output -> collectives.quantized_row_parallel
    # (the shard_map ring bodies are traced regions too)
    "neuronx_distributed_inference_tpu/parallel/layers.py",
    "neuronx_distributed_inference_tpu/parallel/collectives.py",
    # sampled-verify call chain: model_base.paged_spec_verify /
    # paged_ragged_step -> sampling_ops.coupled_sample / stream_keys
    # (the coupled gumbel draws trace inside every decode graph)
    "neuronx_distributed_inference_tpu/ops/sampling.py",
) + JIT_SITE_PATHS

CONFIG_PARAM_NAMES = {"self", "spec", "cfg", "config", "tpu_cfg",
                      "tpu_config", "tcfg", "draft_cfg", "draft_spec",
                      "kv_view", "input_norm", "phase", "make_mask",
                      "mlp_kind"}
CONFIG_ANNOTATIONS = {"DecoderSpec", "TpuConfig", "InferenceConfig",
                      "SpeculationConfig", "bool", "int", "str", "float"}


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                return {kw.value.value}
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                return {e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)}
    return set()


def _partial_root(call: ast.Call) -> Optional[Tuple[str, Set[str]]]:
    """``partial(X, ..., kw=...)`` → (dotted X, baked kwarg names)."""
    if not (isinstance(call, ast.Call)
            and (dotted(call.func) or "").rsplit(".", 1)[-1] == "partial"
            and call.args):
        return None
    name = dotted(call.args[0])
    if name is None:
        return None
    return name, {kw.arg for kw in call.keywords if kw.arg}


def jit_roots(sf: SourceFile) -> List[Tuple[str, Optional[str], Set[str]]]:
    """(root name, module hint or None, static argnames) for every
    ``jax.jit(X, ...)`` site in the file. ``X`` may be a bare name
    (resolved through a same-scope ``fn = partial(...)`` binding — the
    idiom every ``_jit_*`` helper uses), an attribute
    (``model_base.decode_loop``) or an inline ``partial(...)``. Keyword
    arguments baked into the partial count as static (they are bound at
    jit-construction time, not traced)."""
    roots: List[Tuple[str, Optional[str], Set[str]]] = []
    scopes: List[ast.AST] = [sf.tree] + [i.node for i in sf.functions()]
    for scope in scopes:
        partials: dict = {}
        for node in walk_shallow(scope):
            if isinstance(node, ast.Assign):
                pr = _partial_root(node.value) \
                    if isinstance(node.value, ast.Call) else None
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        if pr is not None:
                            partials[t.id] = pr
                        else:
                            partials.pop(t.id, None)
        for node in walk_shallow(scope):
            if not isinstance(node, ast.Call):
                continue
            if (dotted(node.func) or "").rsplit(".", 1)[-1] != "jit":
                continue
            if not node.args:
                continue
            target = node.args[0]
            statics = _static_argnames(node)
            pr = _partial_root(target) if isinstance(target, ast.Call) \
                else None
            if pr is not None:
                name, baked = pr
                statics |= baked
            else:
                name = dotted(target)
                if name in partials:
                    name, baked = partials[name]
                    statics = statics | baked
            if name is None:
                continue
            head, _, last = name.rpartition(".")
            roots.append((last, head or None, statics))
    return roots


def _tainted_params(fn: ast.AST, statics: Set[str]) -> Set[str]:
    tainted: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs +
              ([args.vararg] if args.vararg else []) +
              ([args.kwarg] if args.kwarg else [])):
        ann = ""
        if a.annotation is not None:
            ann = (dotted(a.annotation) or "").rsplit(".", 1)[-1]
        if a.arg in CONFIG_PARAM_NAMES or a.arg in statics or \
                ann in CONFIG_ANNOTATIONS:
            continue
        tainted.add(a.arg)
    return tainted


def _mentions(node: ast.AST, names: Set[str]) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return sub.id
    return None


class _RegionScan:
    """Hazard scan of one traced function (nested defs included, with
    their params tainted too)."""

    def __init__(self, pass_name: str, rel: str, fn: ast.AST,
                 np_names: Set[str], statics: Set[str]):
        self.pass_name = pass_name
        self.rel = rel
        self.fn = fn
        self.np_names = np_names
        self.statics = statics
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        self._scan_scope(self.fn, _tainted_params(self.fn, self.statics),
                         outer_aug=set())
        return self.findings

    def _scan_scope(self, fn: ast.AST, tainted: Set[str],
                    outer_aug: Set[str]):
        tainted = set(tainted)
        aug_here: Set[str] = set()
        assigned_here: Set[str] = set()
        nested: List[ast.AST] = []
        for node in sorted(walk_shallow(fn),
                           key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0))):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                continue
            if isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                aug_here.add(node.target.id)
                assigned_here.add(node.target.id)
            if isinstance(node, ast.Assign):
                has_taint = _mentions(node.value, tainted) is not None
                for tgt in node.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            assigned_here.add(sub.id)
                            if has_taint:
                                tainted.add(sub.id)
            self._hazards(node, tainted)
            # closure-capture hazard: loads of outer AugAssign'd names
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in outer_aug and node.id not in assigned_here:
                self.findings.append(Finding(
                    self.pass_name, self.rel, node.lineno,
                    f"traced closure reads {node.id!r}, a Python value "
                    "the enclosing scope mutates with augmented "
                    "assignment — each trace bakes a different constant "
                    "into the graph (closure-capture recompile hazard); "
                    "pass it as a traced argument instead"))
        for sub in nested:
            sub_tainted = tainted | _tainted_params(sub, set())
            self._scan_scope(sub, sub_tainted, outer_aug | aug_here)

    def _hazards(self, node: ast.AST, tainted: Set[str]):
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            head, _, last = name.rpartition(".")
            if last in ("item", "tolist") and not node.args:
                self.findings.append(Finding(
                    self.pass_name, self.rel, node.lineno,
                    f".{last}() inside a traced region — host "
                    "materialization: crashes on a traced value, bakes "
                    "a per-value constant (one graph per value) on a "
                    "concrete one"))
            elif name in ("float", "int") and node.args and \
                    _mentions(node.args[0], tainted):
                self.findings.append(Finding(
                    self.pass_name, self.rel, node.lineno,
                    f"{name}(...) over traced value "
                    f"{_mentions(node.args[0], tainted)!r} inside a "
                    "traced region — concretization raises under "
                    "tracing or bakes a per-value constant (bucket-"
                    "ladder cache miss)"))
            elif head in self.np_names and \
                    any(_mentions(a, tainted) for a in node.args):
                self.findings.append(Finding(
                    self.pass_name, self.rel, node.lineno,
                    f"np.{last}(...) over traced value "
                    f"{next(filter(None, (_mentions(a, tainted) for a in node.args)))!r}"
                    " inside a traced region — host numpy forces a "
                    "sync/concretization; use jnp"))
        iters: List[ast.AST] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iters.extend(g.iter for g in node.generators)
        for it in iters:
            bad = None
            if isinstance(it, ast.Set):
                bad = "a set literal"
            elif isinstance(it, ast.Call):
                cal = dotted(it.func) or ""
                if cal == "set":
                    bad = "set(...)"
                elif cal.endswith(".keys"):
                    bad = f"{cal}() (unsorted dict view)"
            if bad is not None:
                self.findings.append(Finding(
                    self.pass_name, self.rel, it.lineno,
                    f"iteration over {bad} inside a traced region — "
                    "nondeterministic order feeding graph construction "
                    "makes equal inputs trace different graphs (silent "
                    "jit-cache miss); iterate sorted(...) or a tuple"))


@register
class RecompileHazardPass(Pass):
    name = "recompile-hazard"
    description = ("no host concretization, unordered iteration or "
                   "mutated-closure capture inside jitted/traced regions "
                   "(bucket-ladder jit-cache contract)")
    default_paths = REGION_PATHS

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        region_paths = list(paths) if paths is not None \
            else list(REGION_PATHS)
        sources = self._sources(ctx, region_paths, findings)
        by_rel = {sf.rel: sf for sf in sources}
        by_stem = {rel.rsplit("/", 1)[-1][:-3]: sf
                   for rel, sf in by_rel.items()}
        # 1) roots from every jit site in the scanned set
        region: Dict[Tuple[str, str], Set[str]] = {}   # (rel, fn) -> statics
        work: List[Tuple[str, str]] = []
        for sf in sources:
            for name, module_hint, statics in jit_roots(sf):
                site = self._resolve(name, module_hint, sf, by_stem)
                if site is None:
                    continue
                key = (site.rel, name)
                if key not in region:
                    region[key] = set()
                    work.append(key)
                region[key] |= statics
        # 2) close over callees ACROSS the scanned set: bare names
        #    (same file / imported-from), and module-attribute calls
        #    whose module stem is a scanned file (model_base.X)
        while work:
            rel, name = work.pop()
            sf = by_rel[rel]
            fn = sf.toplevel_functions().get(name) or \
                sf.function_index().get(name)
            if fn is None:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cal = dotted(node.func)
                if cal is None:
                    continue
                head, _, last = cal.rpartition(".")
                target = self._resolve(last, head or None, sf, by_stem)
                if target is None:
                    continue
                key = (target.rel, last)
                if key not in region:
                    region[key] = set()
                    work.append(key)
        # 3) hazard-scan every region function once
        for (rel, name), statics in sorted(region.items()):
            sf = by_rel[rel]
            fn = sf.toplevel_functions().get(name) or \
                sf.function_index().get(name)
            if fn is None:
                continue
            findings.extend(_RegionScan(
                self.name, sf.rel, fn, sf.module_aliases("numpy"),
                statics).run())
        return findings

    def _resolve(self, name: str, module_hint: Optional[str],
                 site_sf: SourceFile, by_stem: Dict[str, SourceFile]
                 ) -> Optional[SourceFile]:
        """Which scanned file defines function ``name``: an explicit
        module attribute (``model_base.X``) resolves by file stem, a
        bare name by same-file definition or imported-from lookup
        against the scanned stems."""
        if module_hint:
            sf = by_stem.get(module_hint.rsplit(".", 1)[-1])
            if sf is not None and name in sf.toplevel_functions():
                return sf
            return None
        if name in site_sf.function_index():
            return site_sf
        for stem, sf in by_stem.items():
            if sf is not site_sf and name in site_sf.imported_names(stem) \
                    and name in sf.toplevel_functions():
                return sf
        return None
