"""aliasing-safety: the PR-3 zero-copy scratch race, as a lint.

jax's CPU backend may alias a suitably-aligned numpy array ZERO-COPY
into the running computation: refilling a host scratch buffer that a
still-in-flight async dispatch aliases corrupts that dispatch's input
mid-execution. PR 3 hit exactly this (flaky under the 8-device test
env) and fixed it by double-buffering the scratch fills ping-pong: each
``fill()`` first REBINDS the buffer attributes to the other buffer set,
then writes in place — the set a still-in-flight dispatch aliases is
never rewritten.

This pass encodes that contract structurally, per class in the serving
dispatch layer:

  * **scratch buffer attributes** are derived by the walker: attributes
    assigned from a numpy array constructor (``np.empty/zeros/...``)
    anywhere in the class, or rebound from a buffer container subscript
    (``self.ids, ... = self._bufs[self._cur]`` — the ping-pong flip);
  * in any method other than ``__init__``, an **in-place mutation** of a
    buffer attribute — a subscript store ``self.X[...] = ...`` (via a
    local alias too), or ``self.X`` passed to an in-place filler
    (``*_into(...)``, ``np.copyto``, ``fill_block_table``) — is a
    finding UNLESS the attribute was rebound (plain store to
    ``self.X``) EARLIER in the same method, i.e. the ping-pong swap ran
    first. ``__init__`` is exempt: a buffer that has never been
    dispatched cannot be aliased.

Verified red on a doctored revert of the PR-3 double-buffering fix and
green on the current tree (tests/test_nxdi_lint.py). A fill that is
provably never live across a dispatch can suppress with a reason:
``# nxdi-lint: disable=aliasing-safety``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set

from ..findings import Finding
from ..registry import LintContext, Pass, register
from ..walker import dotted, walk_shallow

NP_CTORS = ("empty", "zeros", "ones", "full", "arange", "asarray", "array",
            "concatenate", "empty_like", "zeros_like", "ones_like",
            "full_like", "copy")
_INPLACE_SINK = re.compile(r"(_into$|^copyto$|^fill_block_table$)")

DEFAULT_PATHS = (
    "neuronx_distributed_inference_tpu/serving/adapter.py",
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py",
    "neuronx_distributed_inference_tpu/serving/speculation/proposer.py",
    "neuronx_distributed_inference_tpu/serving/engine/scheduler.py",
)


def _np_aliases(sf) -> Set[str]:
    return sf.module_aliases("numpy") or {"np"}


def buffer_attrs(cls: ast.ClassDef, np_names: Set[str]) -> Set[str]:
    """Attribute names of ``cls`` that hold host numpy scratch buffers:
    assigned from a numpy constructor, or rebound (possibly as a tuple)
    from a subscript of another attribute — the double-buffer container
    pattern ``self.a, self.b = self._bufs[i]``."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value_is_np = _is_np_ctor(node.value, np_names)
        value_is_container = (isinstance(node.value, ast.Subscript)
                              and dotted(node.value.value) is not None
                              and "." in (dotted(node.value.value) or ""))
        if not (value_is_np or value_is_container):
            continue
        stack = list(node.targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                attrs.add(t.attr)
    return attrs


def _is_np_ctor(node: ast.AST, np_names: Set[str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    return (isinstance(fn, ast.Attribute) and fn.attr in NP_CTORS
            and isinstance(fn.value, ast.Name) and fn.value.id in np_names)


def _self_attr(node: ast.AST, attrs: Set[str],
               aliases: Dict[str, str]) -> Optional[str]:
    """Resolve an expression to a tracked buffer attr name: ``self.X``,
    a subscript/slice of it, or a local alias of it."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self" and node.attr in attrs:
        return node.attr
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    return None


@register
class AliasingSafetyPass(Pass):
    name = "aliasing-safety"
    description = ("in-place scratch-buffer mutation requires a fresh-"
                   "buffer rebind first (ping-pong double-buffering; "
                   "jax CPU zero-copy aliasing race)")
    default_paths = DEFAULT_PATHS

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        for sf in self._sources(ctx, paths, findings):
            np_names = _np_aliases(sf)
            for cls in sf.classes():
                attrs = buffer_attrs(cls, np_names)
                if not attrs:
                    continue
                for item in cls.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            item.name != "__init__":
                        findings.extend(self._check_method(
                            sf.rel, cls.name, item, attrs))
        return findings

    def _check_method(self, rel: str, cls_name: str, fn: ast.AST,
                      attrs: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        rebound: Dict[str, int] = {}       # attr -> rebind line
        aliases: Dict[str, str] = {}       # local name -> attr
        reported: Set[str] = set()
        for node in sorted(walk_shallow(fn),
                           key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0))):
            if isinstance(node, ast.Assign):
                stack = list(node.targets)
                plain_targets: List[ast.expr] = []
                while stack:
                    t = stack.pop()
                    if isinstance(t, (ast.Tuple, ast.List)):
                        stack.extend(t.elts)
                    else:
                        plain_targets.append(t)
                for t in plain_targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self" and t.attr in attrs:
                        rebound.setdefault(t.attr, t.lineno)
                    elif isinstance(t, ast.Name):
                        # a subscript of a buffer is a VIEW — it shares
                        # the memory, so it aliases the buffer too
                        src = _self_attr(node.value, attrs, aliases)
                        if src is not None:
                            aliases[t.id] = src
                        else:
                            aliases.pop(t.id, None)
            writes = self._inplace_writes(node, attrs, aliases)
            for attr, lineno in writes:
                if attr in reported:
                    continue
                hit = rebound.get(attr)
                if hit is None or hit > lineno:
                    reported.add(attr)
                    findings.append(Finding(
                        self.name, rel, lineno,
                        f"{cls_name}.{fn.name} mutates scratch buffer "
                        f"'self.{attr}' in place without first rebinding "
                        "it to a fresh buffer (ping-pong swap) — a "
                        "still-in-flight async dispatch may zero-copy-"
                        "alias the old buffer (jax CPU), so refilling it "
                        "races the device read; double-buffer like "
                        "_CbScratch/_PagedScratch.fill"))
        return findings

    def _inplace_writes(self, node: ast.AST, attrs: Set[str],
                        aliases: Dict[str, str]):
        """(attr, line) in-place mutations at this node: subscript
        stores and in-place-filler call arguments."""
        out = []
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            stack = list(targets)
            while stack:
                t = stack.pop()
                if isinstance(t, (ast.Tuple, ast.List)):
                    stack.extend(t.elts)
                elif isinstance(t, ast.Subscript):
                    attr = _self_attr(t, attrs, aliases)
                    if attr is not None:
                        out.append((attr, t.lineno))
        elif isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            last = name.rsplit(".", 1)[-1]
            if _INPLACE_SINK.search(last):
                for arg in node.args:
                    attr = _self_attr(arg, attrs, aliases)
                    if attr is not None:
                        out.append((attr, node.lineno))
        return out
