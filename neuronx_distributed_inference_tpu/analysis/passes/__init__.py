"""Pass registration: importing this package registers every built-in
pass with the :mod:`..registry`."""

from . import aliasing  # noqa: F401
from . import donation  # noqa: F401
from . import error_paths  # noqa: F401
from . import fault_points  # noqa: F401
from . import host_sync  # noqa: F401
from . import metric_names  # noqa: F401
from . import perf_drift  # noqa: F401
from . import recompile  # noqa: F401
from . import spmd_golden  # noqa: F401
