"""fault-points: the fault registry and its call sites cannot drift.

``FAULTS.inject()`` already rejects unknown names at ARMING time, but
nothing checked the other direction: a ``FAULTS.fire("typo")`` call
site in the library silently never fires (the injector looks the name
up and finds nothing armed), and a registered point whose last call
site was refactored away silently stops being testable — the chaos
campaign would sweep a point that can never trip. Symmetric, like the
metric-names pass:

  * every ``*.fire("<name>")`` / ``*.inject("<name>")`` call on a
    FAULTS-named receiver in the scanned tree must use a name in
    ``resilience/faults.py``'s ``FAULT_POINTS`` (a non-constant name
    argument is flagged too — it cannot be statically checked and the
    registry is a stable contract, so call sites spell names
    literally);
  * every registered point must have >= 1 ``fire`` call site (orphaned
    points are findings).

Default file set: discovered — every ``.py`` under ``serving/``,
``modules/`` and ``resilience/`` (where fault points live by design)
plus the registry file itself. An explicit ``paths`` override (tests,
doctored copies) uses exactly the given files, reading ``FAULT_POINTS``
from whichever of them defines it (falling back to the repo registry).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..findings import Finding
from ..registry import LintContext, Pass, register

REGISTRY_PATH = "neuronx_distributed_inference_tpu/resilience/faults.py"

_SCAN_ROOTS = (
    "neuronx_distributed_inference_tpu/serving",
    "neuronx_distributed_inference_tpu/modules",
    "neuronx_distributed_inference_tpu/resilience",
)

_CALLS = ("fire", "inject")


def registered_points(tree: ast.AST) -> Optional[Tuple[str, ...]]:
    """The ``FAULT_POINTS`` tuple of string constants, or None when the
    file does not define one."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
                   for t in node.targets):
            continue
        value = node.value
        if isinstance(value, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in value.elts):
            return tuple(e.value for e in value.elts)
    return None


def fault_calls(tree: ast.AST) -> List[Tuple[int, str, Optional[str]]]:
    """(lineno, method, point-name-or-None) for every ``fire``/``inject``
    call whose receiver name mentions FAULTS (``FAULTS.fire``,
    ``_FAULTS.fire``, ``self.faults.inject`` do; unrelated ``x.fire``
    does not). ``None`` marks a non-constant name argument."""
    out: List[Tuple[int, str, Optional[str]]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in _CALLS):
            continue
        recv = fn.value
        recv_name = (recv.id if isinstance(recv, ast.Name)
                     else recv.attr if isinstance(recv, ast.Attribute)
                     else "")
        if "FAULTS" not in recv_name.upper():
            continue
        if not node.args:
            continue
        arg = node.args[0]
        name = (arg.value if isinstance(arg, ast.Constant)
                and isinstance(arg.value, str) else None)
        out.append((node.lineno, fn.attr, name))
    return out


@register
class FaultPointsPass(Pass):
    name = "fault-points"
    description = ("every FAULTS.fire()/inject() call site names a "
                   "registered fault point and every registered point "
                   "has >= 1 fire call site (symmetric, like "
                   "metric-names)")
    default_paths = (REGISTRY_PATH,)

    def effective_paths(self, ctx: LintContext) -> List[str]:
        # discovered coverage: the per-pass `files` stat in the report
        # must state the scanned set, not the 1-file default anchor
        return self._discover(ctx)

    def _discover(self, ctx: LintContext) -> List[str]:
        rels: Set[str] = {REGISTRY_PATH}
        for root in _SCAN_ROOTS:
            base = ctx.repo_root / root
            if base.is_dir():
                rels.update(
                    p.relative_to(ctx.repo_root).as_posix()
                    for p in base.rglob("*.py"))
        return sorted(rels)

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        rels = (list(paths) if paths is not None
                else self._discover(ctx))
        sources = self._sources(ctx, rels, findings)
        # the registry: the first scanned file defining FAULT_POINTS
        # (doctored-copy override), else the repo's canonical one
        points: Optional[Tuple[str, ...]] = None
        reg_rel = REGISTRY_PATH
        for sf in sources:
            pts = registered_points(sf.tree)
            if pts is not None:
                points, reg_rel = pts, sf.rel
                break
        if points is None:
            reg = ctx.source(REGISTRY_PATH)
            if reg is None or reg.tree is None or \
                    (points := registered_points(reg.tree)) is None:
                findings.append(Finding(
                    self.name, REGISTRY_PATH, 0,
                    "FAULT_POINTS tuple of string constants not found — "
                    "the fault registry moved or lost its literal form"))
                return findings
        fired: Set[str] = set()
        for sf in sources:
            for lineno, method, point in fault_calls(sf.tree):
                if point is None:
                    # a parameterized inject() (the chaos campaign's
                    # schedule driver) validates at arming time; a
                    # parameterized FIRE would dodge both checks
                    if method == "fire":
                        findings.append(Finding(
                            self.name, sf.rel, lineno,
                            "FAULTS.fire() with a non-literal point "
                            "name — the registry is a stable contract; "
                            "spell the point as a string literal so "
                            "this pass can check it"))
                    continue
                if point not in points:
                    findings.append(Finding(
                        self.name, sf.rel, lineno,
                        f"FAULTS.{method}({point!r}) is not a "
                        f"registered fault point ({reg_rel}) — a typo'd "
                        "point silently never fires; known: "
                        f"{list(points)}"))
                elif method == "fire":
                    fired.add(point)
        for point in points:
            if point not in fired:
                findings.append(Finding(
                    self.name, reg_rel, 0,
                    f"registered fault point {point!r} has no "
                    "FAULTS.fire() call site in the scanned tree — an "
                    "orphaned point can never trip, so every recovery "
                    "path claiming to test it is vacuous"))
        return findings
