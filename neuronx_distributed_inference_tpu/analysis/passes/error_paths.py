"""error-paths: the serving surface raises ONLY the typed taxonomy.

Port of the PR-2 ``scripts/check_error_paths.py`` checker: any
``raise ValueError(...)`` / ``raise RuntimeError(...)`` in the serving
files must be one of the ``resilience.errors`` types instead, so an
engine can branch on exception type to pick a recovery path. Bare
re-raises and every other exception class are allowed.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Tuple

from ..findings import Finding
from ..registry import LintContext, Pass, register

BANNED = ("ValueError", "RuntimeError")

DEFAULT_PATHS = (
    "neuronx_distributed_inference_tpu/serving/adapter.py",
    "neuronx_distributed_inference_tpu/serving/engine/queue.py",
    "neuronx_distributed_inference_tpu/serving/engine/scheduler.py",
    "neuronx_distributed_inference_tpu/serving/engine/streams.py",
    "neuronx_distributed_inference_tpu/serving/engine/frontend.py",
    "neuronx_distributed_inference_tpu/serving/speculation/__init__.py",
    "neuronx_distributed_inference_tpu/serving/speculation/proposer.py",
    "neuronx_distributed_inference_tpu/serving/speculation/verifier.py",
    "neuronx_distributed_inference_tpu/serving/ragged/__init__.py",
    "neuronx_distributed_inference_tpu/serving/ragged/planner.py",
    "neuronx_distributed_inference_tpu/serving/ragged/path.py",
    "neuronx_distributed_inference_tpu/serving/fleet/__init__.py",
    "neuronx_distributed_inference_tpu/serving/fleet/router.py",
    "neuronx_distributed_inference_tpu/serving/fleet/kv_tier.py",
    "neuronx_distributed_inference_tpu/serving/fleet/handoff.py",
    "neuronx_distributed_inference_tpu/serving/fleet/aggregator.py",
    "neuronx_distributed_inference_tpu/serving/fleet/autoscaler.py",
    "neuronx_distributed_inference_tpu/serving/fleet/loadgen.py",
    "neuronx_distributed_inference_tpu/serving/lora_pool.py",
    "neuronx_distributed_inference_tpu/modules/block_kv_cache.py",
    "neuronx_distributed_inference_tpu/modules/low_rank.py",
    "neuronx_distributed_inference_tpu/parallel/collectives.py",
    "neuronx_distributed_inference_tpu/resilience/controller.py",
    "neuronx_distributed_inference_tpu/resilience/chaos.py",
)


def banned_raises(tree: ast.AST) -> List[Tuple[int, str]]:
    """(lineno, exception name) for every ``raise`` of a banned builtin."""
    bad: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        target = node.exc
        if isinstance(target, ast.Call):
            target = target.func
        if isinstance(target, ast.Name) and target.id in BANNED:
            bad.append((node.lineno, target.id))
    return bad


@register
class ErrorPathsPass(Pass):
    name = "error-paths"
    description = ("serving surface raises only the typed resilience "
                   "taxonomy (no bare ValueError/RuntimeError)")
    default_paths = DEFAULT_PATHS

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        for sf in self._sources(ctx, paths, findings):
            for lineno, name in banned_raises(sf.tree):
                findings.append(Finding(
                    self.name, sf.rel, lineno,
                    f"raise {name}(...) — use the typed taxonomy in "
                    "neuronx_distributed_inference_tpu/resilience/"
                    "errors.py"))
        return findings
