"""Shared AST walker for nxdi-lint passes.

One :class:`SourceFile` per linted file: the module is parsed ONCE and
every pass reads the same tree through the helpers here — function/class
indexing with qualified names, dotted attribute-chain rendering, local
alias tracking (``app = self.app`` making ``app._run_paged`` count as an
``.app`` dispatch), numpy-import alias resolution, statement
linearization for order-sensitive dataflow (donation/aliasing), and
per-line ``# nxdi-lint: disable=<pass>`` suppression parsing.

Everything in this package is STDLIB-ONLY by contract: the driver
(``scripts/nxdi_lint.py``) and the back-compat ``check_*.py`` shims load
it without importing the parent package (whose ``__init__`` pulls jax),
so a lint subprocess costs milliseconds, not a jax import — that is what
lets the whole suite run in-process inside the tier-1 budget.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(r"#\s*nxdi-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Suppression:
    """One ``# nxdi-lint: disable=a,b`` comment. ``covers`` is the line
    set it applies to: its own line, plus — when the comment stands on a
    line of its own — the next code line below it."""
    line: int
    covers: Tuple[int, ...]
    passes: Tuple[str, ...]


@dataclass(frozen=True)
class FunctionInfo:
    """One (possibly nested) function with its context."""
    qualname: str
    node: ast.AST                    # FunctionDef | AsyncFunctionDef
    class_name: Optional[str]        # nearest enclosing class, if any
    parent: Optional[ast.AST]        # nearest enclosing function, if any

    @property
    def name(self) -> str:
        return self.node.name


def dotted(node: ast.AST) -> Optional[str]:
    """Render an attribute/name chain as ``"self.app.cache"``; None for
    anything that is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call target (``"self.app._run_paged"``)."""
    return dotted(call.func)


def linear_statements(node: ast.AST) -> Iterator[ast.stmt]:
    """Every statement under ``node`` in source order, with compound
    statements (if/for/while/with/try) flattened — the linear
    approximation the dataflow passes document. Nested function/class
    bodies are NOT descended into (they are separate scopes, analyzed on
    their own)."""
    body: List[ast.stmt] = getattr(node, "body", [])
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for attr in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, attr, []):
                yield from _linear_one(sub)
        for handler in getattr(stmt, "handlers", []):
            for sub in handler.body:
                yield from _linear_one(sub)


def _linear_one(stmt: ast.stmt) -> Iterator[ast.stmt]:
    yield stmt
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    for attr in ("body", "orelse", "finalbody"):
        for sub in getattr(stmt, attr, []):
            yield from _linear_one(sub)
    for handler in getattr(stmt, "handlers", []):
        for sub in handler.body:
            yield from _linear_one(sub)


def statement_expressions(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Expression nodes belonging to ONE statement, without descending
    into child statements (compound statements contribute only their
    header: an ``if`` its test, a ``for`` its target+iter, a ``with``
    its items). Pair with :func:`linear_statements`, which yields the
    child statements separately — walking the whole compound node would
    process every nested expression twice."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    if isinstance(stmt, (ast.If, ast.While)):
        roots: List[ast.AST] = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.target, stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [it.context_expr for it in stmt.items] + \
                [it.optional_vars for it in stmt.items
                 if it.optional_vars is not None]
    elif isinstance(stmt, ast.Try):
        roots = [h.type for h in stmt.handlers if h.type is not None]
    else:
        roots = list(ast.iter_child_nodes(stmt))
    for root in roots:
        yield root
        yield from walk_shallow(root)


def walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function/class
    definitions — expression-level traversal of ONE scope."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(child))


class SourceFile:
    """One parsed source file shared by every pass."""

    def __init__(self, text: str, rel: str):
        self.text = text
        self.rel = rel                       # repo-relative posix path
        self.lines = text.splitlines()
        # Parse ANYTHING that parses as Python — the old check_*.py CLIs
        # accepted arbitrary user paths (a metrics file copied to .txt),
        # and the extension is not the contract. Non-Python inputs
        # (README.md) carry no tree; AST passes emit a finding for a
        # treeless file instead of dereferencing it.
        try:
            self.tree: Optional[ast.Module] = ast.parse(text)
        except SyntaxError:
            self.tree = None
        self.suppressions: List[Suppression] = (
            self._parse_suppressions() if self.tree is not None else [])
        # memo caches — the recompile closure calls these per call site
        self._toplevel: Optional[Dict[str, ast.AST]] = None
        self._fn_index: Optional[Dict[str, ast.AST]] = None
        self._mod_aliases: Dict[str, Set[str]] = {}
        self._imported: Dict[str, Set[str]] = {}

    # -- suppressions ------------------------------------------------------
    def _parse_suppressions(self) -> List[Suppression]:
        sups: List[Suppression] = []
        for i, line in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(line)
            if m is None:
                continue
            passes = tuple(sorted({p.strip() for p in m.group(1).split(",")
                                   if p.strip()}))
            covers = [i]
            if line.lstrip().startswith("#"):
                # a standalone comment also covers the next code line
                for j in range(i, len(self.lines)):
                    nxt = self.lines[j].strip()
                    if nxt and not nxt.startswith("#"):
                        covers.append(j + 1)
                        break
            sups.append(Suppression(i, tuple(covers), passes))
        return sups

    # -- indexes -----------------------------------------------------------
    def functions(self) -> Iterator[FunctionInfo]:
        """Every function (nested included), with qualname/class/parent."""
        yield from self._walk_functions(self.tree, prefix="",
                                        class_name=None, parent=None)

    def _walk_functions(self, node, prefix, class_name, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield FunctionInfo(qual, child, class_name, parent)
                yield from self._walk_functions(
                    child, prefix=qual + ".", class_name=class_name,
                    parent=child)
            elif isinstance(child, ast.ClassDef):
                yield from self._walk_functions(
                    child, prefix=f"{prefix}{child.name}.",
                    class_name=child.name, parent=parent)
            else:
                yield from self._walk_functions(child, prefix, class_name,
                                                parent)

    def classes(self) -> Iterator[ast.ClassDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                yield node

    def toplevel_functions(self) -> Dict[str, ast.AST]:
        """Module-level ``def`` index (call-graph closure roots)."""
        if self._toplevel is None:
            self._toplevel = {
                n.name: n for n in self.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        return self._toplevel

    def function_index(self) -> Dict[str, ast.AST]:
        """EVERY function in the file by bare name (nested included;
        later definitions win). Used to resolve locally-defined traced
        roots like a ``chain`` closure handed to ``jax.jit``."""
        if self._fn_index is None:
            self._fn_index = {info.name: info.node
                              for info in self.functions()}
        return self._fn_index

    def module_aliases(self, module: str) -> Set[str]:
        """Names this file binds to ``module`` (``import numpy as np`` →
        {"np"} for module="numpy")."""
        if module not in self._mod_aliases:
            names: Set[str] = set()
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name == module:
                            names.add(alias.asname
                                      or alias.name.split(".")[0])
            self._mod_aliases[module] = names
        return self._mod_aliases[module]

    def imported_names(self, module_suffix: str) -> Set[str]:
        """Names imported ``from <...module_suffix> import X`` (suffix
        match tolerates relative-import spellings)."""
        if module_suffix not in self._imported:
            names: Set[str] = set()
            for node in ast.walk(self.tree):
                if isinstance(node, ast.ImportFrom) and node.module and \
                        node.module.endswith(module_suffix):
                    names.update(a.asname or a.name for a in node.names)
            self._imported[module_suffix] = names
        return self._imported[module_suffix]


def local_aliases(fn: ast.AST, chain_suffix: str) -> Set[str]:
    """Local names assigned (anywhere in ``fn``, one level) from an
    attribute chain ending in ``chain_suffix`` — e.g. suffix ``".app"``
    catches ``app = self.app`` and ``app = ad.app``."""
    names: Set[str] = set()
    for node in walk_shallow(fn):
        if not isinstance(node, ast.Assign):
            continue
        src = dotted(node.value)
        if src is None or not src.endswith(chain_suffix):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
    return names


def assignment_targets(stmt: ast.stmt) -> List[ast.expr]:
    """Flattened store targets of an assignment statement (tuple/list
    targets unpacked); [] for non-assignments."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        raw = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        raw = [stmt.target]
    else:
        return targets
    while raw:
        t = raw.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            raw.extend(t.elts)
        else:
            targets.append(t)
    return targets
