"""Pass registry + the lint context every pass runs against.

A pass subclasses :class:`Pass`, names itself (the name is the
suppression token: ``# nxdi-lint: disable=<name>``), declares its
default repo-relative file set and implements ``run(ctx, paths=None)``.
Registration is a decorator; the driver discovers passes by importing
:mod:`.passes`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .findings import Finding
from .walker import SourceFile

_REGISTRY: Dict[str, "Pass"] = {}


class LintContext:
    """Parse-once source cache over one repo root. ``source()`` returns
    None for a missing file (passes emit their own missing-file
    finding, mirroring the old checkers)."""

    def __init__(self, repo_root: Path):
        self.repo_root = Path(repo_root)
        self._cache: Dict[str, Optional[SourceFile]] = {}

    def source(self, rel: str) -> Optional[SourceFile]:
        rel = Path(rel).as_posix()
        if rel not in self._cache:
            path = self.repo_root / rel
            self._cache[rel] = (SourceFile(path.read_text(), rel)
                                if path.exists() else None)
        return self._cache[rel]

    def source_for(self, path: Path) -> Optional[SourceFile]:
        """Absolute or repo-relative path → SourceFile (repo-relative
        when under the root, else keyed by its absolute posix path so
        shims can lint arbitrary files)."""
        p = Path(path)
        if not p.is_absolute():
            return self.source(p.as_posix())
        try:
            return self.source(p.relative_to(self.repo_root).as_posix())
        except ValueError:
            key = p.as_posix()
            if key not in self._cache:
                self._cache[key] = (SourceFile(p.read_text(), key)
                                    if p.exists() else None)
            return self._cache[key]

    def scanned(self) -> List[SourceFile]:
        return [sf for sf in self._cache.values() if sf is not None]


class Pass:
    """Base class: one static-analysis pass."""

    name: str = ""
    description: str = ""
    default_paths: Sequence[str] = ()

    def run(self, ctx: LintContext,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        raise NotImplementedError

    def effective_paths(self, ctx: LintContext) -> Sequence[str]:
        """The file set a default-paths run actually covers — passes
        with DISCOVERED coverage (fault-points) override this so the
        report's per-pass ``files`` stat states the truth."""
        return self.default_paths

    # shared helper: resolve the file list, emitting missing-file findings
    def _sources(self, ctx: LintContext, paths: Optional[Sequence[str]],
                 findings: List[Finding]):
        out = []
        for rel in (paths if paths is not None else self.default_paths):
            sf = ctx.source_for(Path(rel))
            if sf is None:
                findings.append(Finding(self.name, str(rel), 0,
                                        "file is missing"))
            elif sf.tree is None:
                findings.append(Finding(
                    self.name, sf.rel, 1,
                    "not parseable as Python — this pass needs an AST"))
            else:
                out.append(sf)
        return out

    def missing(self, rel: str) -> Finding:
        return Finding(self.name, rel, 0, "file is missing")


def register(cls):
    inst = cls()
    assert inst.name and inst.name not in _REGISTRY, inst.name
    _REGISTRY[inst.name] = inst
    return cls


def all_passes() -> Dict[str, Pass]:
    from . import passes as _passes  # noqa: F401  (registration side effect)
    return dict(sorted(_REGISTRY.items()))


def get_pass(name: str) -> Pass:
    return all_passes()[name]
