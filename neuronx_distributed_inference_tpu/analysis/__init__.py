"""nxdi-lint: the unified static-analysis framework.

One shared AST walker (:mod:`.walker`), a :class:`~.registry.Pass`
registry, per-line ``# nxdi-lint: disable=<pass>`` suppressions with an
unused-suppression check, and a unified findings model with a
``--json`` artifact (:mod:`.findings`, schema ``nxdi-lint-v1``). The
passes encode the serving stack's hard-won invariants — typed error
paths, host-sync dispatch regions, the metric-name contract, the SPMD
golden pin, donation safety, scratch-buffer aliasing safety and
recompile hazards — see README "Static analysis" for the catalog and
the red-then-green methodology for adding one.

STDLIB-ONLY by contract, and loadable WITHOUT the parent package: the
driver (``scripts/nxdi_lint.py``) and the ``check_*.py`` back-compat
shims import it via :data:`scripts.nxdi_lint.load_analysis` so a lint
subprocess never pays the package's jax import.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .findings import SCHEMA, Finding, PassStats, Report  # noqa: F401
from .registry import (LintContext, Pass, all_passes,  # noqa: F401
                       get_pass)

UNUSED_PASS = "unused-suppression"


def _apply_suppressions(ctx: LintContext, findings: List[Finding],
                        used) -> (list, list):
    """Split findings into (surviving, suppressed), recording which
    suppression comments fired in ``used`` (a set of
    (rel, suppression-line) pairs)."""
    by_rel = {sf.rel: sf for sf in ctx.scanned()}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        sf = by_rel.get(f.path)
        hit = None
        if sf is not None:
            for sup in sf.suppressions:
                if f.line in sup.covers and (f.pass_name in sup.passes
                                             or "all" in sup.passes):
                    hit = sup
                    break
        if hit is None:
            kept.append(f)
        else:
            used.add((f.path, hit.line))
            suppressed.append(f)
    return kept, suppressed


def run_single(ctx: LintContext, name: str,
               paths: Optional[Sequence[str]] = None) -> List[Finding]:
    """One pass, suppressions applied — the back-compat ``check_*.py``
    shims route through this so a suppression honored by the driver is
    honored by the legacy CLI too."""
    raw = get_pass(name).run(ctx, paths=paths)
    kept, _ = _apply_suppressions(ctx, raw, set())
    return kept


def run_passes(repo_root, names: Optional[Sequence[str]] = None,
               ctx: Optional[LintContext] = None,
               overrides: Optional[Dict[str, Sequence[str]]] = None
               ) -> Report:
    """Run the selected passes (default: all) in-process over one repo
    root and return the unified :class:`Report` — suppressions applied,
    unused suppressions reported as findings of the virtual
    ``unused-suppression`` pass. ``overrides`` maps a pass name to an
    explicit file list (tests / partial runs); unlisted passes keep
    their default paths."""
    registry = all_passes()
    if names is None:
        names = list(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise KeyError(f"unknown pass(es) {unknown}; "
                       f"available: {list(registry)}")
    ctx = ctx or LintContext(Path(repo_root))
    report = Report()
    used = set()
    for name in names:
        p = registry[name]
        t0 = time.perf_counter()
        pass_paths = (overrides or {}).get(name)
        raw = p.run(ctx, paths=pass_paths)
        kept, suppressed = _apply_suppressions(ctx, raw, used)
        report.findings.extend(kept)
        report.suppressed.extend(suppressed)
        report.passes.append(PassStats(
            name=p.name, description=p.description,
            files=len(pass_paths if pass_paths is not None
                      else p.effective_paths(ctx)),
            findings=len(kept), suppressed=len(suppressed),
            duration_s=time.perf_counter() - t0))
    # unused-suppression check: every disable comment in a scanned file
    # must have absorbed at least one finding of a named pass that ran
    ran = set(names)
    unused: List[Finding] = []
    for sf in ctx.scanned():
        for sup in sf.suppressions:
            if (sf.rel, sup.line) in used:
                continue
            if not (set(sup.passes) & (ran | {"all"})):
                continue           # suppresses only passes that didn't run
            unused.append(Finding(
                UNUSED_PASS, sf.rel, sup.line,
                f"suppression for {', '.join(sup.passes)} did not match "
                "any finding — stale comment (the code was fixed, or the "
                "pass name is misspelled); remove it"))
    report.findings.extend(unused)
    report.passes.append(PassStats(
        name=UNUSED_PASS,
        description="every nxdi-lint disable comment still absorbs a "
                    "finding",
        files=len(ctx.scanned()), findings=len(unused)))
    report.files = sorted(sf.rel for sf in ctx.scanned())
    return report
