"""Unified findings model + the ``nxdi-lint-v1`` JSON artifact schema.

Every pass returns a flat list of :class:`Finding`; the driver applies
suppressions, runs the unused-suppression check, and renders one
:class:`Report` — the same object behind the console output, the ``rc``
and the ``--json`` artifact that ``bench.py --lint-report`` commits per
round (so lint findings trend like bench numbers).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

SCHEMA = "nxdi-lint-v1"


@dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a repo-relative path + line."""
    pass_name: str
    path: str                        # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return {"pass": self.pass_name, "path": self.path,
                "line": self.line, "message": self.message}


@dataclass
class PassStats:
    name: str
    description: str
    files: int = 0
    findings: int = 0
    suppressed: int = 0
    duration_s: float = 0.0


@dataclass
class Report:
    """One driver run: surviving findings, what suppressions absorbed,
    and per-pass accounting."""
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    passes: List[PassStats] = field(default_factory=list)
    files: List[str] = field(default_factory=list)      # union, sorted

    @property
    def rc(self) -> int:
        return 1 if self.findings else 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "schema": SCHEMA,
            "passes": {
                p.name: {"description": p.description, "files": p.files,
                         "findings": p.findings, "suppressed": p.suppressed,
                         "duration_s": round(p.duration_s, 4)}
                for p in self.passes},
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "files": list(self.files),
            "totals": {"files": len(self.files),
                       "findings": len(self.findings),
                       "suppressed": len(self.suppressed)},
        }
