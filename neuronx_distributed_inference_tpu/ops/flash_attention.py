"""Pallas flash attention for context encoding — TPU-native replacement for
the reference's NKI flash kernel ``nkilib.core.attention.attention_cte``
(reference: modules/attention/attention_base.py:72-85, kernel dispatch
:565-770, strategy selection :985-1034).

Online-softmax tiling over K/V blocks with causal block skipping; supports
sliding-window masking and logit soft-cap. GQA is handled by mapping each Q
head's grid row to its KV head in the BlockSpec index map (no KV head
materialization, unlike repeat_kv).

Layouts: q/k/v (B, H, S, D) inside the kernel; the public wrapper takes the
model's (B, S, H, D) and transposes. All softmax math fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.3819763e38  # close to f32 min; matches jax flash impls


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, block_q: int, block_k: int, causal: bool,
                  window: int, soft_cap: Optional[float]):
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    # causal block skip: block contributes only if its first key pos can be
    # attended by the last query pos of this q block
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1
    if window > 0:
        # skip blocks entirely left of every query's window
        run = jnp.logical_and(run, k_start + block_k > q_start - window + 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)          # (bq, D)
        k = k_ref[0, 0].astype(jnp.float32)          # (bk, D)
        v = v_ref[0, 0].astype(jnp.float32)          # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (bq, bk)
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = jnp.ones((block_q, block_k), jnp.bool_)
        if causal:
            mask = kpos <= qpos
        if window > 0:
            mask = jnp.logical_and(mask, qpos - kpos < window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0:1]                       # (bq, 1)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                       # (bq, bk)
        l_ref[:, 0:1] = l_ref[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_ref[:, 0:1] = m_cur

    @pl.when(ik == nk - 1)
    def _finalize():
        # causal guarantees l > 0 (each query attends at least itself)
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, 0:1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "causal", "window", "soft_cap", "block_q",
                     "block_k", "interpret"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    scale: float, causal: bool = True, window: int = 0,
                    soft_cap: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False) -> jnp.ndarray:
    """q (B, S, Hq, D); k/v (B, S, Hkv, D) -> (B, S, Hq, D).

    S must be a multiple of the block sizes (callers pad to bucket sizes that
    are powers of two >= 128, so this holds; see supports()).
    """
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    block_q = min(block_q, s)
    block_k = min(block_k, s)

    qt = jnp.transpose(q, (0, 2, 1, 3))      # (B, Hq, S, D)
    kt = jnp.transpose(k, (0, 2, 1, 3))      # (B, Hkv, S, D)
    vt = jnp.transpose(v, (0, 2, 1, 3))

    def _kv_block(i, j):
        # DMA elision (same trick as ops/decode_attention.py): clamp the
        # k-block index into this q-block's causal/window-valid range —
        # consecutive identical indices skip the DMA, so the causal upper
        # triangle and out-of-window blocks cost nothing
        jc = j
        if causal:
            jc = jnp.minimum(jc, (i * block_q + block_q - 1) // block_k)
        if window > 0:
            lo = jnp.maximum((i * block_q - window + 1) // block_k, 0)
            jc = jnp.maximum(jc, lo)
        return jc

    grid = (b, hq, s // block_q, s // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, soft_cap=soft_cap)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, i, j: (bi, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, h, i, j, g=g: (bi, h // g,
                                                   _kv_block(i, j), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, h, i, j, g=g: (bi, h // g,
                                                   _kv_block(i, j), 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, h, i, j: (bi, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3))


def dispatch_prefill(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                     scale: float, causal: bool = True, window: int = 0,
                     soft_cap: Optional[float] = None,
                     interpret: bool = False) -> Optional[jnp.ndarray]:
    """Mesh-aware prefill entry: shard_map the flash kernel over the
    model-parallel axes (q AND kv heads split — GQA sharding already
    pads/replicates kv heads to a multiple of tp) so tp>1 runs the kernel
    per-shard instead of all-gathering under GSPMD (the tp=1-only
    restriction the round-3 review flagged). Returns None when the heads
    cannot be sharded."""
    mesh = jax.sharding.get_abstract_mesh()
    hq, hkv = q.shape[2], k.shape[2]
    mp_axes = tuple(a for a in ("ep", "tp")
                    if mesh is not None and a in mesh.axis_names
                    and mesh.shape[a] > 1)
    mp = 1
    for a in mp_axes:
        mp *= mesh.shape[a]
    # batch over dp too (the decode dispatch does the same) — omitting it
    # would all-gather the dp-sharded prefill activations and compute the
    # kernel dp-times redundantly
    dp_axes = tuple(a for a in ("dp",)
                    if mesh is not None and a in mesh.axis_names
                    and mesh.shape[a] > 1 and q.shape[0] % mesh.shape[a] == 0)
    if mp == 1 and not dp_axes:
        return flash_attention(q, k, v, scale=scale, causal=causal,
                               window=window, soft_cap=soft_cap,
                               interpret=interpret)
    if mp > 1 and (hq % mp or hkv % mp or (hq // mp) % (hkv // mp)):
        return None
    from jax.sharding import PartitionSpec as P
    spec = P(dp_axes if dp_axes else None, None,
             mp_axes if mp_axes else None, None)

    def body(qs, ks, vs):
        return flash_attention(qs, ks, vs, scale=scale, causal=causal,
                               window=window, soft_cap=soft_cap,
                               interpret=interpret)

    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def supports(seq_len: int, head_dim: int, has_sink: bool, chunk: int,
             block: int = 128) -> bool:
    """Strategy gate (reference analog: FlashAttentionStrategy selection,
    attention_base.py:985-1034). The XLA path remains the fallback."""
    return (seq_len % block == 0 and seq_len >= block
            and head_dim % 64 == 0 and not has_sink and chunk == 0)
