"""Rotary position embeddings (reference: modules/attention/utils.py RoPE
helpers + Llama3 scaled RoPE at models/llama/modeling_llama.py:805).

TPU-first: cos/sin are computed on the fly from position_ids inside the traced
graph (cheap VPU work, avoids an S×D table in HBM) in fp32 for accuracy.
Supports: default RoPE, linear scaling, dynamic NTK, llama3 frequency scaling,
and partial rotary (rotary_dim < head_dim)."""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


def np_one_hot(idx: Sequence[int], depth: int) -> np.ndarray:
    """(len(idx), depth) fp32 one-hot built at trace time."""
    out = np.zeros((len(idx), depth), np.float32)
    out[np.arange(len(idx)), np.asarray(idx)] = 1.0
    return out


@dataclass(frozen=True)
class RopeConfig:
    head_dim: int
    rope_theta: float = 10000.0
    rotary_dim: Optional[int] = None     # partial rotary support
    scaling_type: Optional[str] = None   # None | "linear" | "llama3" | "yarn"
    scaling_factor: float = 1.0
    # llama3 scaling params (reference: modeling_llama.py:805 Llama3RotaryEmbedding)
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192
    # yarn params (deepseek / gpt-oss; reference: deepseek rope_util +
    # HF _compute_yarn_parameters semantics)
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    mscale: float = 1.0
    mscale_all_dim: float = 0.0
    attention_factor: Optional[float] = None  # cos/sin multiplier; None=derive
    truncate: bool = True
    # M-RoPE (Qwen2-VL / Qwen2.5-VL — reference: models/qwen2_vl/
    # modeling_qwen2_vl_text.py:52 ``apply_multimodal_rotary_pos_emb``):
    # positions are 3-axis (temporal, height, width); freq slot i takes its
    # angle from the axis owning it — slots [0,s0) temporal, [s0,s0+s1)
    # height, [s0+s1,s0+s1+s2) width. sum(mrope_section) == dim/2.
    mrope_section: Optional[Tuple[int, ...]] = None
    # Qwen3-VL interleaved M-RoPE (reference: models/qwen3_vl/ — HF
    # apply_interleaved_mrope): slots cycle T,H,W,T,H,W,... up to 3*sec_h /
    # 3*sec_w for H/W, preserving frequency continuity; the tail stays T
    mrope_interleaved: bool = False
    # longrope (phi-3 / minicpm4 — HF _compute_longrope_parameters): one
    # rescale factor per frequency slot; the long list applies when the
    # deployed max_position exceeds the original pretraining length
    short_factor: Optional[Tuple[float, ...]] = None
    long_factor: Optional[Tuple[float, ...]] = None
    max_position: int = 0            # deployed max_position_embeddings

    @property
    def dim(self) -> int:
        return self.rotary_dim or self.head_dim


def _base_inv_freq(cfg: RopeConfig) -> jnp.ndarray:
    d = cfg.dim
    return 1.0 / (cfg.rope_theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))


SUPPORTED_SCALING = (None, "default", "linear", "llama3", "yarn",
                     "longrope")


def yarn_attention_factor(cfg: RopeConfig) -> float:
    """Post-scale on cos/sin (YaRN attention temperature; HF
    _compute_yarn_parameters semantics)."""
    if cfg.attention_factor is not None:
        return float(cfg.attention_factor)

    def get_mscale(scale: float, m: float = 1.0) -> float:
        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

    if cfg.mscale and cfg.mscale_all_dim:
        return get_mscale(cfg.scaling_factor, cfg.mscale) / get_mscale(
            cfg.scaling_factor, cfg.mscale_all_dim)
    return get_mscale(cfg.scaling_factor)


def _yarn_inv_freq(cfg: RopeConfig) -> jnp.ndarray:
    d = cfg.dim
    pos_freqs = cfg.rope_theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    inv_extrap = 1.0 / pos_freqs
    inv_interp = 1.0 / (cfg.scaling_factor * pos_freqs)

    def corr_dim(n_rot: float) -> float:
        return (d * math.log(cfg.original_max_position / (n_rot * 2 * math.pi))
                ) / (2 * math.log(cfg.rope_theta))

    low, high = corr_dim(cfg.beta_fast), corr_dim(cfg.beta_slow)
    if cfg.truncate:
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, d - 1)
    if low == high:
        high += 0.001
    ramp = jnp.clip((jnp.arange(d // 2, dtype=jnp.float32) - low)
                    / (high - low), 0, 1)
    extrap_factor = 1.0 - ramp
    return inv_interp * (1 - extrap_factor) + inv_extrap * extrap_factor


def compute_inv_freq(cfg: RopeConfig) -> jnp.ndarray:
    if cfg.scaling_type not in SUPPORTED_SCALING:
        raise NotImplementedError(
            f"rope scaling type {cfg.scaling_type!r} not implemented yet "
            f"(supported: {SUPPORTED_SCALING})")
    if cfg.scaling_type == "yarn":
        return _yarn_inv_freq(cfg)
    if cfg.scaling_type == "longrope":
        use_long = (cfg.max_position > cfg.original_max_position
                    and cfg.long_factor is not None)
        ext = jnp.asarray(cfg.long_factor if use_long else cfg.short_factor,
                          jnp.float32)
        return _base_inv_freq(cfg) / ext
    inv_freq = _base_inv_freq(cfg)
    if cfg.scaling_type == "linear":
        inv_freq = inv_freq / cfg.scaling_factor
    elif cfg.scaling_type == "llama3":
        # Llama-3.1 frequency-dependent scaling (reference: modeling_llama.py:805-840)
        low_wavelen = cfg.original_max_position / cfg.low_freq_factor
        high_wavelen = cfg.original_max_position / cfg.high_freq_factor
        wavelen = 2 * math.pi / inv_freq
        scaled = inv_freq / cfg.scaling_factor
        smooth = (cfg.original_max_position / wavelen - cfg.low_freq_factor) / (
            cfg.high_freq_factor - cfg.low_freq_factor)
        mid = (1 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(wavelen < high_wavelen, inv_freq,
                             jnp.where(wavelen > low_wavelen, scaled, mid))
    return inv_freq


def rope_cos_sin(position_ids: jnp.ndarray, cfg: RopeConfig
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, S) int positions -> cos/sin of shape (B, S, dim/2), fp32.

    M-RoPE: (B, S, 3) positions + cfg.mrope_section -> each freq slot takes
    its angle from its owning axis (text tokens pass t == h == w, recovering
    plain RoPE)."""
    inv_freq = compute_inv_freq(cfg)
    if cfg.mrope_section is not None and position_ids.ndim == 3:
        angles3 = (position_ids.astype(jnp.float32)[..., None]
                   * inv_freq)                     # (B, S, 3, d/2)
        if cfg.mrope_interleaved:
            sec = cfg.mrope_section
            axis_of_slot = []
            for i in range(sum(sec)):
                if i % 3 == 1 and i < 3 * sec[1]:
                    axis_of_slot.append(1)
                elif i % 3 == 2 and i < 3 * sec[2]:
                    axis_of_slot.append(2)
                else:
                    axis_of_slot.append(0)
        else:
            axis_of_slot = sum(([ax] * n for ax, n in
                                enumerate(cfg.mrope_section)), [])
        sel = jnp.asarray(np_one_hot(axis_of_slot, angles3.shape[2]))
        angles = jnp.einsum("bsad,da->bsd", angles3, sel)
    else:
        if position_ids.ndim == 3:
            position_ids = position_ids[..., 0]
        angles = position_ids.astype(jnp.float32)[..., None] * inv_freq
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    if cfg.scaling_type == "yarn":
        f = yarn_attention_factor(cfg)
        cos, sin = cos * f, sin * f
    elif cfg.scaling_type == "longrope":
        if cfg.attention_factor is not None:
            f = float(cfg.attention_factor)
        else:
            factor = max(cfg.max_position / cfg.original_max_position, 1.0)
            f = (1.0 if factor <= 1.0 else math.sqrt(
                1.0 + math.log(factor) / math.log(cfg.original_max_position)))
        cos, sin = cos * f, sin * f
    return cos, sin


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray,
               interleaved: bool = False) -> jnp.ndarray:
    """Apply rotary embedding.

    x: (B, S, H, D); cos/sin: (B, S, d/2) where d = rotary dim (may be < D).
    Default is the HF "half" convention (rotate_half); ``interleaved`` selects
    the GPT-NeoX interleaved pairing.
    """
    d2 = cos.shape[-1]
    d = 2 * d2
    dtype = x.dtype
    x_rot, x_pass = x[..., :d], x[..., d:]
    xf = x_rot.astype(jnp.float32)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    if interleaved:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        out = jnp.stack([o1, o2], axis=-1).reshape(xf.shape)
    else:
        x1, x2 = xf[..., :d2], xf[..., d2:]
        out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    out = out.astype(dtype)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out
