"""On-device sampling (reference: modules/generation/sampling.py ``Sampler``).

Everything runs inside the decode graph: greedy argmax, or
top-k / top-p / temperature multinomial with **per-request** sampling params
(reference: prepare_sampling_params :183 — a (B, 3) tensor of
[top_k, top_p, temperature]).

The reference implements a multi-stage hierarchical top-k because Neuron lacks
a fast full-vocab sort (:285-335). On TPU, ``jax.lax.top_k`` with a static
``global_topk`` bound (default 256) plays the same role: one top_k over the
vocab shard, then per-request masking down to the dynamic k.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..config import OnDeviceSamplingConfig


def prepare_sampling_params(batch_size: int, top_k=1, top_p=1.0, temperature=1.0):
    """Host helper -> (B, 3) fp32 [top_k, top_p, temperature]
    (reference: sampling.py:183 ``prepare_sampling_params``)."""
    import numpy as np

    def _bcast(v):
        a = np.asarray(v, dtype=np.float32).reshape(-1)
        if a.size == 1:
            a = np.full((batch_size,), a[0], dtype=np.float32)
        if a.size != batch_size:
            raise ValueError(f"sampling param batch {a.size} != {batch_size}")
        return a

    return np.stack([_bcast(top_k), _bcast(top_p), _bcast(temperature)], axis=1)


def mask_padded_logits(logits: jnp.ndarray, pad_size: int) -> jnp.ndarray:
    """Mask vocab-padding columns added for tp divisibility
    (reference: sampling.py:24 ``mask_padded_logits``)."""
    if pad_size == 0:
        return logits
    v = logits.shape[-1]
    col = jnp.arange(v) >= (v - pad_size)
    return jnp.where(col, jnp.finfo(logits.dtype).min, logits)


def greedy_sample(logits: jnp.ndarray) -> jnp.ndarray:
    """(…, V) -> (…,) int32 argmax (reference: nxd argmax op path)."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def truncated_probs(logits: jnp.ndarray, sampling_params: jnp.ndarray,
                    global_topk: int = 256):
    """The shared top-k/top-p/temperature truncation: logits (B, V) +
    sampling_params (B, 3) -> (probs, top_idx), both (B, k) with
    k = min(global_topk, V), probs renormalized over the kept prefix.
    top_k <= 0 or >= global_topk means "no k truncation beyond
    global_topk"."""
    b, v = logits.shape
    k = min(global_topk, v)
    lf = logits.astype(jnp.float32)
    top_vals, top_idx = jax.lax.top_k(lf, k)  # (B, k) sorted desc

    req_k = sampling_params[:, 0]
    req_p = sampling_params[:, 1]
    temp = jnp.maximum(sampling_params[:, 2], 1e-6)

    ranks = jnp.arange(k, dtype=jnp.float32)[None, :]
    kmask = jnp.where(req_k[:, None] > 0, ranks < req_k[:, None], True)

    scaled = top_vals / temp[:, None]
    probs = jax.nn.softmax(jnp.where(kmask, scaled, -jnp.inf), axis=-1)
    # top-p: keep the smallest prefix of sorted probs with cumsum >= p,
    # always keeping the top token.
    cum = jnp.cumsum(probs, axis=-1)
    pmask = (cum - probs) < req_p[:, None]
    probs = jnp.where(pmask & kmask, probs, 0.0)
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return probs, top_idx


def topk_topp_sample(logits: jnp.ndarray, sampling_params: jnp.ndarray,
                     key: jax.Array, global_topk: int = 256,
                     deterministic: bool = False) -> jnp.ndarray:
    """Per-request top-k/top-p/temperature sampling.

    logits (B, V); sampling_params (B, 3) = [top_k, top_p, temperature].
    """
    probs, top_idx = truncated_probs(logits, sampling_params, global_topk)
    if deterministic:
        choice = jnp.argmax(probs, axis=-1)
    else:
        # gumbel-max over the truncated distribution
        g = jax.random.gumbel(key, probs.shape, dtype=jnp.float32)
        choice = jnp.argmax(jnp.where(probs > 0, jnp.log(probs) + g, -jnp.inf), axis=-1)
    return jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)


def stream_keys(stream_seed: int, seeds: jnp.ndarray,
                positions: jnp.ndarray) -> jax.Array:
    """Per-draw PRNG keys for the positionally coupled stream: row i's
    key is ``fold_in(fold_in(PRNGKey(stream_seed), seeds[i]),
    positions[i])`` — a pure function of (engine stream seed, request
    seed, absolute position of the token whose logits are sampled), so
    the same draw falls out of ANY graph that samples that position:
    eager decode, the fused decode loop, the prefill tail, a draft-loop
    step, a verify column, or a ragged verify row."""
    base = jax.random.PRNGKey(stream_seed)
    return jax.vmap(lambda s, p: jax.random.fold_in(
        jax.random.fold_in(base, s), p))(
        seeds.astype(jnp.int32), positions.astype(jnp.int32))


def coupled_sample(logits: jnp.ndarray,
                   config: OnDeviceSamplingConfig,
                   sampling_params: Optional[jnp.ndarray],
                   seeds: jnp.ndarray,
                   positions: jnp.ndarray) -> jnp.ndarray:
    """Positionally coupled top-k/top-p/temperature sampling.

    Unlike :func:`sample` (one gumbel block per dispatch, so streams
    depend on scheduling), every draw here is keyed by
    :func:`stream_keys` and the per-row gumbel noise has a fixed shape
    (k,), making the sampled token a pure function of (stream_seed,
    request seed, position, logits). That invariance is what makes
    gumbel-coupled rejection sampling exact: the verify graph's coupled
    draw at position p IS the token eager decode would have sampled at
    p, so accept-by-exact-match preserves both the output distribution
    and the stream (see README "Sampled speculation & compressed
    decode").

    logits (B, V) with seeds (B,) / positions (B,), or (B, T, V) with
    positions (B, T); sampling_params (B, 3) or None (config-static).
    """
    squeeze = False
    if logits.ndim == 3:
        b, t, v = logits.shape
        logits = logits.reshape(b * t, v)
        seeds = jnp.broadcast_to(seeds[:, None], (b, t)).reshape(-1)
        positions = positions.reshape(-1)
        if sampling_params is not None and sampling_params.shape[0] == b:
            sampling_params = jnp.repeat(sampling_params, t, axis=0)
        squeeze = (b, t)
    if sampling_params is None:
        sampling_params = jnp.broadcast_to(
            jnp.array([[config.top_k, config.top_p, config.temperature]],
                      jnp.float32), (logits.shape[0], 3))
    probs, top_idx = truncated_probs(logits, sampling_params,
                                     config.global_topk)
    if config.deterministic:
        choice = jnp.argmax(probs, axis=-1)
    else:
        keys = stream_keys(config.stream_seed or 0, seeds, positions)
        kwidth = probs.shape[-1]
        g = jax.vmap(lambda k: jax.random.gumbel(k, (kwidth,),
                                                 jnp.float32))(keys)
        choice = jnp.argmax(jnp.where(probs > 0, jnp.log(probs) + g,
                                      -jnp.inf), axis=-1)
    toks = jnp.take_along_axis(top_idx, choice[:, None],
                               axis=-1)[:, 0].astype(jnp.int32)
    if squeeze:
        toks = toks.reshape(squeeze)
    return toks


def sample(logits: jnp.ndarray, config: Optional[OnDeviceSamplingConfig],
           sampling_params: Optional[jnp.ndarray] = None,
           key: Optional[jax.Array] = None) -> jnp.ndarray:
    """Dispatch greedy vs multinomial; (B, V) or (B, T, V) logits -> tokens."""
    squeeze = False
    if logits.ndim == 3:
        b, t, v = logits.shape
        logits = logits.reshape(b * t, v)
        squeeze = (b, t)
    if sampling_params is None and (config is None or not config.do_sample):
        toks = greedy_sample(logits)
    elif sampling_params is None:
        sp = jnp.broadcast_to(
            jnp.array([[config.top_k, config.top_p, config.temperature]],
                      jnp.float32), (logits.shape[0], 3))
        toks = topk_topp_sample(logits, sp, key, config.global_topk,
                                config.deterministic)
    else:
        if sampling_params.shape[0] != logits.shape[0]:
            sampling_params = jnp.repeat(
                sampling_params, logits.shape[0] // sampling_params.shape[0], axis=0)
        toks = topk_topp_sample(logits, sampling_params, key,
                                config.global_topk if config else 256,
                                config.deterministic if config else False)
    if squeeze:
        toks = toks.reshape(squeeze)
    return toks


def sample_dp(logits: jnp.ndarray, config, sampling_params, key,
              mesh=None) -> jnp.ndarray:
    """Batch-sharded sampling (reference: modules/generation/sampling.py
    :467-578 ``DataParallelSampler``): shard_map :func:`sample` over the
    mesh "dp" axis so each shard runs top-k on its own batch slice —
    the (B, V) logits are never gathered. Falls back to the global
    :func:`sample` when no dp axis is active or B doesn't divide."""
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
    b = logits.shape[0]
    if (mesh is None or "dp" not in getattr(mesh, "axis_names", ())
            or mesh.shape["dp"] <= 1 or b % mesh.shape["dp"] != 0):
        return sample(logits, config, sampling_params, key)
    from jax.sharding import PartitionSpec as P
    dp = mesh.shape["dp"]
    specs = [P("dp")]
    args = [logits]
    if sampling_params is not None:
        specs.append(P("dp") if sampling_params.shape[0] == b else P())
        args.append(sampling_params)
    if key is not None:
        # fold the shard index into the key so shards draw independent noise
        specs.append(P())
        args.append(key)

    def body(lg, *rest):
        sp = rest[0] if sampling_params is not None else None
        k = rest[-1] if key is not None else None
        if k is not None:
            k = jax.random.fold_in(k, jax.lax.axis_index("dp"))
        return sample(lg, config, sp, k)

    return jax.shard_map(body, mesh=mesh, in_specs=tuple(specs),
                         out_specs=P("dp"), check_vma=False)(*args)
