"""Normalization ops (reference: modules/custom_calls.py ``CustomRMSNorm`` and
the NKI rmsnorm_quant kernel, models/llama/modeling_llama.py:553-575).

On TPU, RMSNorm is a plain fused elementwise reduction — XLA fuses it into the
surrounding matmuls, so no custom call is needed. Computation is done in fp32
and cast back (matches reference numerics: CustomRMSNorm upcasts)."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6,
             offset: float = 0.0) -> jnp.ndarray:
    """RMSNorm. ``offset`` = 1.0 gives the (1+w) Gemma variant."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    xf = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    w = weight.astype(jnp.float32) + offset
    return (xf * w).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    xf = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    out = xf * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)
