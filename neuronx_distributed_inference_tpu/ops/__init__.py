"""ops subpackage."""
