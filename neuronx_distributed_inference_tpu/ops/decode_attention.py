"""Pallas decode (token-generation) attention — TPU-native replacement for
the reference's NKI TKG attention kernels
(reference: modules/attention/attention_base.py:1186-1382 ``attention_block_tkg``
mega-kernel path and :1383-1461 decomposed prior+active attention).

Decomposition (same as the reference's decomposed TKG attention): the new
token's K/V never round-trips through the cache for the score computation —
the kernel attends over the PRIOR cache rows (0..pos_b-1) plus the ACTIVE
token handled in-registers, so the cache scatter write can be scheduled
independently by XLA.

The win over the XLA path is bandwidth: the grid walks cache blocks along S
and collapses every block past each row's live length onto the last live
block via the BlockSpec index map — Pallas elides the DMA when consecutive
grid steps map to the same block, so a 4k-slot cache at position 500 streams
~512 slots, not 4096 (the reference kernel gets the same effect from
explicit DMA skipping, kvcache/utils.py batch-write kernel).

Layouts: q (B, Hq, D); k/v cache (B, S, Hkv, D) per-layer slice (strided on
H inside a block — the S-major cache layout is shared with the XLA path);
new k/v (B, Hkv, D). All softmax math fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.3819763e38


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, nk_ref, nv_ref, sink_ref,
                   o_ref, acc_ref, m_ref, l_ref, *,
                   scale: float, block_s: int,
                   soft_cap: Optional[float], has_sink: bool):
    """Scalar-prefetch layout: lens_ref = [layer_idx, window, len_0, ...,
    len_{B-1}] (layer_idx consumed by the index maps of the stacked-cache
    variant; window is DYNAMIC so alternating local/global layer patterns
    can pass their per-layer window through one scan body — reference:
    gemma3 / gpt_oss alternating attention, SURVEY §2.7)."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    pos = lens_ref[2 + b]                   # prior length of this row
    w = lens_ref[1]                         # sliding window (0 = unlimited)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    k_start = j * block_s
    in_window = jnp.logical_or(w == 0, k_start + block_s > pos - w)

    @pl.when(jnp.logical_and(k_start < pos, in_window))
    def _prior():
        q = q_ref[0, 0].astype(jnp.float32)                # (G, D)
        k = k_ref[0, 0, :, 0].astype(jnp.float32)          # (bs, D)
        v = v_ref[0, 0, :, 0].astype(jnp.float32)          # (bs, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)          # (G, bs)
        kpos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        valid = kpos < pos
        valid = jnp.logical_and(
            valid, jnp.logical_or(w == 0, pos - kpos < w))
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_ref[:, 0:1] = l_ref[:, 0:1] * alpha + jnp.sum(p, -1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, 0:1] = m_cur

    @pl.when(j == nj - 1)
    def _active_and_finalize():
        # active token: its score joins the softmax; its V joins the acc
        q = q_ref[0, 0].astype(jnp.float32)                # (G, D)
        kn = nk_ref[0].astype(jnp.float32)                 # (1, D)
        vn = nv_ref[0].astype(jnp.float32)                 # (1, D)
        s = jax.lax.dot_general(q, kn, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)          # (G, 1)
        m_prev = m_ref[:, 0:1]
        m_cur = jnp.maximum(m_prev, s)
        if has_sink:
            # learned per-head sink joins the denominator only
            # (reference: modules/attention/sink.py)
            sk = sink_ref[0].astype(jnp.float32)[:, None]  # (G, 1)
            m_cur = jnp.maximum(m_cur, sk)
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                             # (G, 1)
        l_new = l_ref[:, 0:1] * alpha + p
        if has_sink:
            l_new = l_new + jnp.exp(sk - m_cur)
        acc = acc_ref[:] * alpha + p * vn                  # (G, D)
        o_ref[0, 0] = (acc / l_new).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "soft_cap", "block_s", "interpret"))
def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray, new_k: jnp.ndarray,
                     new_v: jnp.ndarray, lens: jnp.ndarray, *,
                     scale: float, window: int = 0,
                     soft_cap: Optional[float] = None,
                     sink: Optional[jnp.ndarray] = None,
                     block_s: int = 256, interpret: bool = False
                     ) -> jnp.ndarray:
    """One-token decode attention over prior cache + active token.

    q (B, Hq, D); k_cache/v_cache (B, S, Hkv, D) — rows [0, lens[b]) valid;
    new_k/new_v (B, Hkv, D) the active token's K/V (NOT yet required to be
    in the cache); lens (B,) int32 prior lengths; sink (Hq,) optional learned
    softmax sink logits. Returns (B, Hq, D).
    """
    return decode_attention_stacked(
        q, k_cache[None], v_cache[None], new_k, new_v,
        jnp.zeros((), jnp.int32), lens, scale=scale,
        window=jnp.asarray(window, jnp.int32), soft_cap=soft_cap, sink=sink,
        block_s=block_s, interpret=interpret)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "soft_cap", "block_s", "interpret"))
def decode_attention_stacked(q: jnp.ndarray, k_cache: jnp.ndarray,
                             v_cache: jnp.ndarray, new_k: jnp.ndarray,
                             new_v: jnp.ndarray, layer: jnp.ndarray,
                             lens: jnp.ndarray, *,
                             scale: float,
                             window: Optional[jnp.ndarray] = None,
                             soft_cap: Optional[float] = None,
                             sink: Optional[jnp.ndarray] = None,
                             block_s: int = 256, interpret: bool = False
                             ) -> jnp.ndarray:
    """Decode attention reading layer ``layer`` (traced scalar — inside the
    layer scan) directly out of the FULL stacked cache (L, B, S, Hkv, D):
    no per-layer dynamic-slice materialization between the carry and the
    kernel; the index maps address the layer through scalar prefetch."""
    b, hq, d = q.shape
    s = k_cache.shape[2]
    hkv = k_cache.shape[3]
    g = hq // hkv
    block_s = min(block_s, s)
    nj = pl.cdiv(s, block_s)

    qr = q.reshape(b, hkv, g, d)
    sink_in = (sink.reshape(hkv, g) if sink is not None
               else jnp.zeros((hkv, g), jnp.float32))

    def q_map(bi, h, j, sc):
        return (bi, h, 0, 0)

    def kv_map(bi, h, j, sc):
        # clamp to the live [window-start, prefix-end] block range:
        # consecutive identical indices -> Pallas skips the DMA
        pos_b = sc[2 + bi]
        last_live = jax.lax.max(
            jax.lax.div(jax.lax.max(pos_b - 1, 0), block_s), 0)
        w = sc[1]
        first_live = jax.lax.select(
            w > 0, jax.lax.max(jax.lax.div(jax.lax.max(pos_b - w, 0),
                                           block_s), 0), 0)
        return (sc[0], bi,
                jax.lax.min(jax.lax.max(j, first_live), last_live), h, 0)

    def nkv_map(bi, h, j, sc):
        return (bi, h, 0)

    def sink_map(bi, h, j, sc):
        return (h, 0)

    grid = (b, hkv, nj)
    kernel = functools.partial(
        _decode_kernel, scale=scale, block_s=block_s,
        soft_cap=soft_cap, has_sink=sink is not None)
    if window is None:
        window = jnp.zeros((), jnp.int32)
    scalars = jnp.concatenate([
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.asarray(window, jnp.int32).reshape(1), lens.astype(jnp.int32)])
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, g, d), q_map),
                pl.BlockSpec((1, 1, block_s, 1, d), kv_map),
                pl.BlockSpec((1, 1, block_s, 1, d), kv_map),
                pl.BlockSpec((1, 1, d), nkv_map),
                pl.BlockSpec((1, 1, d), nkv_map),
                pl.BlockSpec((1, g), sink_map),
            ],
            out_specs=pl.BlockSpec((1, 1, g, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((g, d), jnp.float32),
                pltpu.VMEM((g, 128), jnp.float32),
                pltpu.VMEM((g, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(scalars, qr, k_cache, v_cache,
      new_k.reshape(b, hkv, 1, d)[:, :, 0], new_v.reshape(b, hkv, 1, d)[:, :, 0],
      sink_in)
    return out.reshape(b, hq, d)


def supports(spec, phase_t: int) -> bool:
    """Kernel admission (reference analog: TKG kernel enablement flags,
    models/config.py:417-567): single active token, no MLA (different head
    dims), uniform-window handled per-layer by the caller."""
    return (phase_t == 1 and spec.mla is None
            and spec.head_dim in (64, 128) and spec.attn_soft_cap is None)
