"""Pallas decode (token-generation) attention — TPU-native replacement for
the reference's NKI TKG attention kernels
(reference: modules/attention/attention_base.py:1186-1382 ``attention_block_tkg``
mega-kernel path and :1383-1461 decomposed prior+active attention).

Decomposition (same as the reference's decomposed TKG attention): the new
token's K/V never round-trips through the cache for the score computation —
the kernel attends over the PRIOR cache rows (0..pos_b-1) plus the ACTIVE
token handled in-registers, so the cache scatter write can be scheduled
independently by XLA.

The win over the XLA path is bandwidth: the grid walks cache blocks along S
and collapses every block past each row's live length onto the last live
block via the BlockSpec index map — Pallas elides the DMA when consecutive
grid steps map to the same block, so a 4k-slot cache at position 500 streams
~512 slots, not 4096 (the reference kernel gets the same effect from
explicit DMA skipping, kvcache/utils.py batch-write kernel).

Layouts (native cache layouts, modules/kv_cache.py): q (B, Hq, D); k cache
TRANSPOSED (L, B, Hkv, D, S), v cache (L, B, Hkv, S, D) — the minor/tiled
dims per block are (D, block_s) for K and (block_s, D) for V, so each block
is one contiguous DMA, a legal Mosaic BlockSpec, and feeds its dot in its
natural orientation (a head-minor layout would make every per-head block
shape (…,1,D), which TPU lowering rejects); new k/v (B, Hkv, D). All
softmax math fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.3819763e38


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, nk_ref, nv_ref, sink_ref,
                   o_ref, acc_ref, m_ref, l_ref, *,
                   scale: float, block_s: int, nh: int,
                   soft_cap: Optional[float], has_sink: bool,
                   kv_scale: Optional[float] = None):
    """Scalar-prefetch layout: lens_ref = [layer_idx, window, len_0, ...,
    len_{B-1}] (layer_idx consumed by the index maps of the stacked-cache
    variant; window is DYNAMIC so alternating local/global layer patterns
    can pass their per-layer window through one scan body — reference:
    gemma3 / gpt_oss alternating attention, SURVEY §2.7).

    ``nh`` kv-heads are processed per grid step (an unrolled in-kernel
    loop over leading block dims — static indexing, no relayout): the
    coarse grid keeps the per-step overhead off the critical path, which
    is what made the fine-grained one-head-per-step variant lose to XLA."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    pos = lens_ref[2 + b]                   # prior length of this row
    w = lens_ref[1]                         # sliding window (0 = unlimited)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    k_start = j * block_s
    in_window = jnp.logical_or(w == 0, k_start + block_s > pos - w)

    @pl.when(jnp.logical_and(k_start < pos, in_window))
    def _prior():
        kpos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (q_ref.shape[3], block_s), 1)
        valid = kpos < pos
        valid = jnp.logical_and(
            valid, jnp.logical_or(w == 0, pos - kpos < w))
        for hh in range(nh):
            q = q_ref[0, 0, hh].astype(jnp.float32)        # (G, D)
            k = k_ref[0, 0, hh].astype(jnp.float32)        # (D, bs) transposed
            v = v_ref[0, 0, hh].astype(jnp.float32)        # (bs, D)
            if kv_scale is not None:
                # scaled KV quantization: stored value = x / kv_scale
                # (reference: kv_cache_manager.py:636-692 scaled fp8 mode);
                # the dequant rides the fp32 cast already on the block load
                k = k * kv_scale
                v = v * kv_scale
            s = jax.lax.dot_general(q, k, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)      # (G, bs)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[hh, :, 0:1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_ref[hh, :, 0:1] = (l_ref[hh, :, 0:1] * alpha
                                 + jnp.sum(p, -1, keepdims=True))
            acc_ref[hh] = acc_ref[hh] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[hh, :, 0:1] = m_cur

    @pl.when(j == nj - 1)
    def _active_and_finalize():
        # active token: its score joins the softmax; its V joins the acc
        for hh in range(nh):
            q = q_ref[0, 0, hh].astype(jnp.float32)        # (G, D)
            kn = nk_ref[0, 0, hh].astype(jnp.float32)      # (1, D)
            vn = nv_ref[0, 0, hh].astype(jnp.float32)      # (1, D)
            s = jax.lax.dot_general(q, kn, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)      # (G, 1)
            m_prev = m_ref[hh, :, 0:1]
            m_cur = jnp.maximum(m_prev, s)
            if has_sink:
                # learned per-head sink joins the denominator only
                # (reference: modules/attention/sink.py)
                sk = sink_ref[0, hh].astype(jnp.float32).reshape(-1)[:, None]
                m_cur = jnp.maximum(m_cur, sk)             # sk (G, 1)
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)                         # (G, 1)
            l_new = l_ref[hh, :, 0:1] * alpha + p
            if has_sink:
                l_new = l_new + jnp.exp(sk - m_cur)
            acc = acc_ref[hh] * alpha + p * vn             # (G, D)
            o_ref[0, 0, hh] = (acc / l_new).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "window", "soft_cap", "kv_scale", "block_s",
                     "interpret"))
def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray, new_k: jnp.ndarray,
                     new_v: jnp.ndarray, lens: jnp.ndarray, *,
                     scale: float, window: int = 0,
                     soft_cap: Optional[float] = None,
                     sink: Optional[jnp.ndarray] = None,
                     kv_scale: Optional[float] = None,
                     block_s: int = 256, interpret: bool = False
                     ) -> jnp.ndarray:
    """One-token decode attention over prior cache + active token.

    q (B, Hq, D); k_cache (B, Hkv, D, S) TRANSPOSED / v_cache (B, Hkv, S, D)
    — slots [0, lens[b]) valid;
    new_k/new_v (B, Hkv, D) the active token's K/V (NOT yet required to be
    in the cache); lens (B,) int32 prior lengths; sink (Hq,) optional learned
    softmax sink logits. Returns (B, Hq, D).
    """
    return decode_attention_stacked(
        q, k_cache[None], v_cache[None], new_k, new_v,
        jnp.zeros((), jnp.int32), lens, scale=scale,
        window=jnp.asarray(window, jnp.int32), soft_cap=soft_cap, sink=sink,
        kv_scale=kv_scale, block_s=block_s, interpret=interpret)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "soft_cap", "kv_scale", "block_s", "interpret"))
def decode_attention_stacked(q: jnp.ndarray, k_cache: jnp.ndarray,
                             v_cache: jnp.ndarray, new_k: jnp.ndarray,
                             new_v: jnp.ndarray, layer: jnp.ndarray,
                             lens: jnp.ndarray, *,
                             scale: float,
                             window: Optional[jnp.ndarray] = None,
                             soft_cap: Optional[float] = None,
                             sink: Optional[jnp.ndarray] = None,
                             kv_scale: Optional[float] = None,
                             block_s: int = 256, interpret: bool = False
                             ) -> jnp.ndarray:
    """Decode attention reading layer ``layer`` (traced scalar — inside the
    layer scan) directly out of the FULL stacked cache (L, B, Hkv, S, D):
    no per-layer dynamic-slice materialization between the carry and the
    kernel; the index maps address the layer through scalar prefetch."""
    b, hq, d = q.shape
    hkv = k_cache.shape[2]
    s = k_cache.shape[4]          # K stored transposed (L, B, Hkv, D, S)
    g = hq // hkv
    block_s = min(block_s, s)
    nj = pl.cdiv(s, block_s)

    # kv-heads per grid step: as many as fit the VMEM budget (k+v blocks,
    # double-buffered), capped to bound the in-kernel unroll
    vmem_budget = 4 * 1024 * 1024
    max_nh = max(1, min(8, vmem_budget // (block_s * d * 2 * 2 * 2)))
    nh = 1
    for cand in range(max_nh, 0, -1):
        if hkv % cand == 0:
            nh = cand
            break
    hb = hkv // nh

    qr = q.reshape(b, hb, nh, g, d)
    sink_in = (sink.reshape(hb, nh, 1, g) if sink is not None
               else jnp.zeros((hb, nh, 1, g), jnp.float32))

    def q_map(bi, h, j, sc):
        return (bi, h, 0, 0, 0)

    def _live_block(bi, j, sc):
        # clamp to the live [window-start, prefix-end] block range:
        # consecutive identical indices -> Pallas skips the DMA
        pos_b = sc[2 + bi]
        last_live = jax.lax.max(
            jax.lax.div(jax.lax.max(pos_b - 1, 0), block_s), 0)
        w = sc[1]
        first_live = jax.lax.select(
            w > 0, jax.lax.max(jax.lax.div(jax.lax.max(pos_b - w, 0),
                                           block_s), 0), 0)
        return jax.lax.min(jax.lax.max(j, first_live), last_live)

    def k_map(bi, h, j, sc):
        # K stored transposed (L, B, Hkv, D, S)
        return (sc[0], bi, h, 0, _live_block(bi, j, sc))

    def v_map(bi, h, j, sc):
        return (sc[0], bi, h, _live_block(bi, j, sc), 0)

    def nkv_map(bi, h, j, sc):
        return (bi, h, 0, 0, 0)

    def sink_map(bi, h, j, sc):
        return (h, 0, 0, 0)

    grid = (b, hb, nj)
    kernel = functools.partial(
        _decode_kernel, scale=scale, block_s=block_s, nh=nh,
        soft_cap=soft_cap, has_sink=sink is not None, kv_scale=kv_scale)
    if window is None:
        window = jnp.zeros((), jnp.int32)
    scalars = jnp.concatenate([
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.asarray(window, jnp.int32).reshape(1), lens.astype(jnp.int32)])
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, nh, g, d), q_map),
                pl.BlockSpec((1, 1, nh, d, block_s), k_map),
                pl.BlockSpec((1, 1, nh, block_s, d), v_map),
                pl.BlockSpec((1, 1, nh, 1, d), nkv_map),
                pl.BlockSpec((1, 1, nh, 1, d), nkv_map),
                pl.BlockSpec((1, nh, 1, g), sink_map),
            ],
            out_specs=pl.BlockSpec((1, 1, nh, g, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((nh, g, d), jnp.float32),
                pltpu.VMEM((nh, g, 128), jnp.float32),
                pltpu.VMEM((nh, g, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hb, nh, g, d), q.dtype),
        interpret=interpret,
    )(scalars, qr, k_cache, v_cache,
      new_k.reshape(b, hb, nh, 1, d), new_v.reshape(b, hb, nh, 1, d),
      sink_in)
    return out.reshape(b, hq, d)


def dispatch(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
             new_k: jnp.ndarray, new_v: jnp.ndarray, layer: jnp.ndarray,
             lens: jnp.ndarray, *, scale: float,
             window: Optional[jnp.ndarray] = None,
             soft_cap: Optional[float] = None,
             sink: Optional[jnp.ndarray] = None,
             kv_scale: Optional[float] = None,
             block_s: int = 256, interpret: bool = False) -> jnp.ndarray:
    """Mesh-aware entry: shard_map the kernel over the ambient mesh's
    model-parallel axes (kv-heads over ("ep","tp")) and the decode batch
    axis ("dp"), matching the cache layout P(None,"dp",("ep","tp"),None,None)
    (modules/kv_cache.py cache_pspec) — the TPU analog of the reference
    running its TKG kernel per-rank under SPMD
    (attention_base.py:1186-1382). On a single-device (or axis-free) mesh
    runs the bare pallas_call. Returns None when kv heads cannot be
    sharded over a >1 model-parallel degree — the caller must use the XLA
    attention path there."""
    mesh = jax.sharding.get_abstract_mesh()
    b, hq, d = q.shape
    hkv = k_cache.shape[2]
    mp_axes = tuple(a for a in ("ep", "tp")
                    if mesh is not None and a in mesh.axis_names
                    and mesh.shape[a] > 1)
    mp = 1
    for a in mp_axes:
        mp *= mesh.shape[a]
    if mp > 1 and hkv % mp != 0:
        # kv heads not shardable over the model-parallel axes: a bare
        # pallas_call here would run REPLICATED under GSPMD (full cache
        # all-gathered to every device per layer per step) — signal the
        # caller to take the head-sharded XLA path instead
        return None
    dp_axes = tuple(a for a in ("dp",)
                    if mesh is not None and a in mesh.axis_names
                    and mesh.shape[a] > 1 and b % mesh.shape[a] == 0)
    if not mp_axes and not dp_axes:
        return decode_attention_stacked(
            q, k_cache, v_cache, new_k, new_v, layer, lens, scale=scale,
            window=window, soft_cap=soft_cap, sink=sink, kv_scale=kv_scale,
            block_s=block_s, interpret=interpret)

    if window is None:
        window = jnp.zeros((), jnp.int32)
    from jax.sharding import PartitionSpec as P
    dp = dp_axes if dp_axes else None
    mpx = mp_axes if mp_axes else None
    in_specs = [
        P(dp, mpx, None),                  # q
        P(None, dp, mpx, None, None),      # k_cache
        P(None, dp, mpx, None, None),      # v_cache
        P(dp, mpx, None),                  # new_k
        P(dp, mpx, None),                  # new_v
        P(),                               # layer
        P(dp),                             # lens
        P(),                               # window
    ]
    args = [q, k_cache, v_cache, new_k, new_v, layer, lens,
            jnp.asarray(window, jnp.int32)]
    if sink is not None:
        in_specs.append(P(mpx))
        args.append(sink)

    def body(q, kc, vc, nk, nv, layer, lens, window, *rest):
        return decode_attention_stacked(
            q, kc, vc, nk, nv, layer, lens, scale=scale, window=window,
            soft_cap=soft_cap, sink=rest[0] if rest else None,
            kv_scale=kv_scale, block_s=block_s, interpret=interpret)

    return jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=P(dp, mpx, None), check_vma=False)(*args)


def _paged_kernel(sc_ref, q_ref, k_ref, v_ref, nk_ref, nv_ref, sink_ref,
                  o_ref, acc_ref, m_ref, l_ref, *,
                  scale: float, block_s: int, nh: int,
                  soft_cap: Optional[float], has_sink: bool,
                  kv_scale: Optional[float] = None):
    """Ragged PAGED decode attention (reference: the DMA-skipping TKG
    attention over the block layout, attention_base.py:1186-1382 +
    block_kv_cache_manager.py:183-267). Scalar layout:
    [layer, window, len_0..len_{B-1}, table_{b=0,j=0}.., table_{B-1,mb-1}]
    — the index maps gather PHYSICAL pages through the block table, so the
    kernel streams only each row's live pages (grid steps past the live
    range collapse onto the last live page and Pallas elides the DMA); the
    XLA gather path materializes the whole table every layer every token."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    pos = sc_ref[2 + b]
    w = sc_ref[1]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    k_start = j * block_s
    in_window = jnp.logical_or(w == 0, k_start + block_s > pos - w)

    @pl.when(jnp.logical_and(k_start < pos, in_window))
    def _prior():
        kpos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (q_ref.shape[3], block_s), 1)
        valid = kpos < pos
        valid = jnp.logical_and(
            valid, jnp.logical_or(w == 0, pos - kpos < w))
        for hh in range(nh):
            q = q_ref[0, 0, hh].astype(jnp.float32)        # (G, D)
            k = k_ref[0, 0, :, hh, :].astype(jnp.float32)  # (bs, D)
            v = v_ref[0, 0, :, hh, :].astype(jnp.float32)  # (bs, D)
            if kv_scale is not None:
                # scaled KV dequant on the page load (reference:
                # kv_cache_manager.py:636-692 scaled fp8 mode)
                k = k * kv_scale
                v = v * kv_scale
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)      # (G, bs)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[hh, :, 0:1]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_ref[hh, :, 0:1] = (l_ref[hh, :, 0:1] * alpha
                                 + jnp.sum(p, -1, keepdims=True))
            acc_ref[hh] = acc_ref[hh] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[hh, :, 0:1] = m_cur

    @pl.when(j == nj - 1)
    def _active_and_finalize():
        for hh in range(nh):
            q = q_ref[0, 0, hh].astype(jnp.float32)        # (G, D)
            kn = nk_ref[0, 0, hh].astype(jnp.float32)      # (1, D)
            vn = nv_ref[0, 0, hh].astype(jnp.float32)      # (1, D)
            s = jax.lax.dot_general(q, kn, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if soft_cap is not None:
                s = soft_cap * jnp.tanh(s / soft_cap)      # (G, 1)
            m_prev = m_ref[hh, :, 0:1]
            m_cur = jnp.maximum(m_prev, s)
            if has_sink:
                sk = sink_ref[0, hh].astype(jnp.float32).reshape(-1)[:, None]
                m_cur = jnp.maximum(m_cur, sk)
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s - m_cur)
            l_new = l_ref[hh, :, 0:1] * alpha + p
            if has_sink:
                l_new = l_new + jnp.exp(sk - m_cur)
            acc = acc_ref[hh] * alpha + p * vn
            o_ref[0, 0, hh] = (acc / l_new).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "soft_cap", "kv_scale", "interpret"))
def paged_decode_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, new_k: jnp.ndarray,
                           new_v: jnp.ndarray, layer: jnp.ndarray,
                           lens: jnp.ndarray, block_table: jnp.ndarray, *,
                           scale: float,
                           window: Optional[jnp.ndarray] = None,
                           soft_cap: Optional[float] = None,
                           sink: Optional[jnp.ndarray] = None,
                           kv_scale: Optional[float] = None,
                           interpret: bool = False) -> jnp.ndarray:
    """Ragged paged decode attention over the stacked block cache.

    q (B, Hq, D); k_pages/v_pages (L, N, Bs, Hkv, D); new_k/new_v
    (B, Hkv, D); lens (B,) prior lengths; block_table (B, max_blocks)
    logical→physical page map (entry 0 = null page). Returns (B, Hq, D).
    """
    b, hq, d = q.shape
    hkv = k_pages.shape[3]
    bs = k_pages.shape[2]
    mb = block_table.shape[1]
    g = hq // hkv

    vmem_budget = 4 * 1024 * 1024
    max_nh = max(1, min(8, vmem_budget // (bs * d * 2 * 2 * 2)))
    nh = 1
    for cand in range(max_nh, 0, -1):
        if hkv % cand == 0:
            nh = cand
            break
    hb = hkv // nh

    qr = q.reshape(b, hb, nh, g, d)
    sink_in = (sink.reshape(hb, nh, 1, g) if sink is not None
               else jnp.zeros((hb, nh, 1, g), jnp.float32))

    def q_map(bi, h, j, sc):
        return (bi, h, 0, 0, 0)

    def _live_page(bi, j, sc):
        pos_b = sc[2 + bi]
        last_live = jax.lax.max(
            jax.lax.div(jax.lax.max(pos_b - 1, 0), bs), 0)
        w = sc[1]
        first_live = jax.lax.select(
            w > 0, jax.lax.max(jax.lax.div(jax.lax.max(pos_b - w, 0), bs),
                               0), 0)
        jc = jax.lax.min(jax.lax.max(j, first_live), last_live)
        return sc[2 + b + bi * mb + jc]         # physical page id

    def kv_map(bi, h, j, sc):
        # pages (L, N, Bs, Hkv, D): full Bs rows, nh-head slab
        return (sc[0], _live_page(bi, j, sc), 0, h, 0)

    def nkv_map(bi, h, j, sc):
        return (bi, h, 0, 0, 0)

    def sink_map(bi, h, j, sc):
        return (h, 0, 0, 0)

    grid = (b, hb, mb)
    kernel = functools.partial(
        _paged_kernel, scale=scale, block_s=bs, nh=nh, kv_scale=kv_scale,
        soft_cap=soft_cap, has_sink=sink is not None)
    if window is None:
        window = jnp.zeros((), jnp.int32)
    scalars = jnp.concatenate([
        jnp.asarray(layer, jnp.int32).reshape(1),
        jnp.asarray(window, jnp.int32).reshape(1),
        lens.astype(jnp.int32),
        block_table.astype(jnp.int32).reshape(-1)])
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, nh, g, d), q_map),
                pl.BlockSpec((1, 1, bs, nh, d), kv_map),
                pl.BlockSpec((1, 1, bs, nh, d), kv_map),
                pl.BlockSpec((1, 1, nh, 1, d), nkv_map),
                pl.BlockSpec((1, 1, nh, 1, d), nkv_map),
                pl.BlockSpec((1, nh, 1, g), sink_map),
            ],
            out_specs=pl.BlockSpec((1, 1, nh, g, d), q_map),
            scratch_shapes=[
                pltpu.VMEM((nh, g, d), jnp.float32),
                pltpu.VMEM((nh, g, 128), jnp.float32),
                pltpu.VMEM((nh, g, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hb, nh, g, d), q.dtype),
        interpret=interpret,
    )(scalars, qr, k_pages, v_pages,
      new_k.reshape(b, hb, nh, 1, d), new_v.reshape(b, hb, nh, 1, d),
      sink_in)
    return out.reshape(b, hq, d)


def paged_dispatch(q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                   new_k: jnp.ndarray, new_v: jnp.ndarray, layer: jnp.ndarray,
                   lens: jnp.ndarray, block_table: jnp.ndarray, *,
                   scale: float, window: Optional[jnp.ndarray] = None,
                   soft_cap: Optional[float] = None,
                   sink: Optional[jnp.ndarray] = None,
                   kv_scale: Optional[float] = None,
                   interpret: bool = False) -> Optional[jnp.ndarray]:
    """Mesh-aware entry for the paged kernel: shard kv-heads over the
    model-parallel axes, matching the block-cache sharding
    P(None, None, None, ("ep","tp"), None) (modules/block_kv_cache.py).
    Returns None when the heads cannot be sharded over a >1 mp degree."""
    mesh = jax.sharding.get_abstract_mesh()
    b = q.shape[0]
    hkv = k_pages.shape[3]
    mp_axes = tuple(a for a in ("ep", "tp")
                    if mesh is not None and a in mesh.axis_names
                    and mesh.shape[a] > 1)
    mp = 1
    for a in mp_axes:
        mp *= mesh.shape[a]
    if mp > 1 and hkv % mp != 0:
        return None
    # batch rows split over dp (pages stay replicated across dp — the
    # block cache has no dp axis, block_cache_pspec)
    dp_axes = tuple(a for a in ("dp",)
                    if mesh is not None and a in mesh.axis_names
                    and mesh.shape[a] > 1 and b % mesh.shape[a] == 0)
    if not mp_axes and not dp_axes:
        return paged_decode_attention(
            q, k_pages, v_pages, new_k, new_v, layer, lens, block_table,
            scale=scale, window=window, soft_cap=soft_cap, sink=sink,
            kv_scale=kv_scale, interpret=interpret)

    if window is None:
        window = jnp.zeros((), jnp.int32)
    from jax.sharding import PartitionSpec as P
    mpx = mp_axes if mp_axes else None
    dp = dp_axes if dp_axes else None
    in_specs = [
        P(dp, mpx, None),                    # q
        P(None, None, None, mpx, None),      # k_pages
        P(None, None, None, mpx, None),      # v_pages
        P(dp, mpx, None),                    # new_k
        P(dp, mpx, None),                    # new_v
        P(),                                 # layer
        P(dp),                               # lens
        P(dp, None),                         # block_table
        P(),                                 # window
    ]
    args = [q, k_pages, v_pages, new_k, new_v, layer, lens, block_table,
            jnp.asarray(window, jnp.int32)]
    if sink is not None:
        in_specs.append(P(mpx))
        args.append(sink)

    def body(q, kp, vp, nk, nv, layer, lens, table, window, *rest):
        return paged_decode_attention(
            q, kp, vp, nk, nv, layer, lens, table, scale=scale,
            window=window, soft_cap=soft_cap,
            sink=rest[0] if rest else None, kv_scale=kv_scale,
            interpret=interpret)

    return jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=P(dp, mpx, None), check_vma=False)(*args)


def supports(spec, phase_t: int) -> bool:
    """Kernel admission (reference analog: TKG kernel enablement flags,
    models/config.py:417-567): single active token, no MLA (different head
    dims; the kernel streams K and V with one block shape), no chunked
    attention (the kernel masks by window, not chunk boundaries — llama4's
    chunked local layers take the XLA path)."""
    return (phase_t == 1 and spec.mla is None
            and spec.head_dim in (64, 128) and spec.attn_chunk == 0)


@functools.lru_cache(maxsize=None)
def quantized_cache_ok(cache_dtype_name: str) -> bool:
    """Whether Mosaic on this backend can stream cache blocks of the given
    (non-compute) dtype — fp8 KV caches (reference analog: the TKG kernel
    running over the fp8 KV cache, kv_cache_manager.py:636-692). Probed
    once with an AOT compile of a tiny kernel; CPU interpret always works."""
    if cache_dtype_name in ("bfloat16", "float32", "float16"):
        return True
    if jax.default_backend() != "tpu":
        return True          # tests run the interpret path
    try:
        sds = jax.ShapeDtypeStruct
        dt = jnp.dtype(cache_dtype_name)
        # probe BOTH kernels: q (B=1, Hq=4, D) over a 1-kv-head cache —
        # new_k/new_v carry Hkv=1 like the cache
        fn = functools.partial(decode_attention_stacked, scale=1.0,
                               kv_scale=None)
        jax.jit(fn).lower(
            sds((1, 4, 128), jnp.bfloat16),
            sds((1, 1, 1, 128, 256), dt),
            sds((1, 1, 1, 256, 128), dt),
            sds((1, 1, 128), jnp.bfloat16),
            sds((1, 1, 128), jnp.bfloat16),
            sds((), jnp.int32), sds((1,), jnp.int32)).compile()
        pfn = functools.partial(paged_decode_attention, scale=1.0,
                                kv_scale=None)
        jax.jit(pfn).lower(
            sds((1, 4, 128), jnp.bfloat16),
            sds((1, 4, 64, 1, 128), dt),
            sds((1, 4, 64, 1, 128), dt),
            sds((1, 1, 128), jnp.bfloat16),
            sds((1, 1, 128), jnp.bfloat16),
            sds((), jnp.int32), sds((1,), jnp.int32),
            sds((1, 2), jnp.int32)).compile()
        return True
    except Exception:         # Mosaic rejects the dtype on this TPU gen
        return False
