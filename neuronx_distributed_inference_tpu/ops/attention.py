"""Attention ops — XLA-native path (reference:
modules/attention/attention_base.py ``NeuronAttentionBase``).

The reference dispatches between NKI flash kernels and a native compiler path
(FlashAttentionStrategy.NONE, attention_base.py:985-1034). Here the roles are
mirrored: this module is the always-available XLA path (XLA already tiles these
einsums onto the MXU and fuses the softmax); a Pallas flash kernel
(``ops/flash_attention.py``, added separately) is the fast path for
long-context prefill.

Layout conventions (TPU-friendly: head_dim last = 128-lane dim):
  q:        (B, T, Hq, D)
  k/v:      (B, S, Hkv, D)
  mask:     (B, T, S) boolean, True = attend
All softmax math in fp32 (matches reference numerics: manual_softmax in
modules/attention/utils.py computes in fp32).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -30000.0  # large-negative fill used instead of -inf (reference uses
                    # torch.finfo.min clamps; finite value avoids fp16/bf16 NaNs)


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """(B, S, Hkv, D) -> (B, S, Hkv*n_rep, D)
    (reference: modules/attention/utils.py ``repeat_kv``)."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def alibi_slopes(num_heads: int, variant: str = "bloom") -> "np.ndarray":
    """Per-head ALiBi slopes (paper 2108.12409). "bloom" reproduces HF
    build_alibi_tensor (closest power of two + interleaved extras);
    "mpt" reproduces build_mpt_alibi_tensor (ceil power of two with
    alibi_bias_max=8, odd slopes first). Identical for power-of-two head
    counts."""
    import math

    import numpy as np
    if variant == "bloom":
        cp2 = 2 ** math.floor(math.log2(num_heads))
        base = 2.0 ** (-(2.0 ** -(math.log2(cp2) - 3)))
        slopes = base ** np.arange(1, cp2 + 1, dtype=np.float64)
        if cp2 != num_heads:
            extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * cp2) - 3)))
            n_extra = min(2 * cp2, num_heads) - cp2
            extra = extra_base ** np.arange(1, 2 * n_extra, 2,
                                            dtype=np.float64)
            slopes = np.concatenate([slopes, extra])
        return slopes.astype(np.float32)
    if variant == "mpt":
        n2 = 2 ** math.ceil(math.log2(num_heads))
        base = np.arange(1, n2 + 1, dtype=np.float64) * (8.0 / n2)
        slopes = 1.0 / np.power(2.0, base)
        if n2 != num_heads:
            slopes = np.concatenate([slopes[1::2], slopes[0::2]])[:num_heads]
        return slopes.astype(np.float32)
    raise ValueError(f"unknown alibi variant {variant!r}")


def _alibi_bias(alibi, hkv: int, g: int):
    """(slopes (Hq,), kv_pos (B,S) or (1,S)) -> additive score bias
    (B, Hkv, G, 1, S) in fp32."""
    slopes, kv_pos = alibi
    sl = slopes.astype(jnp.float32).reshape(1, hkv, g, 1, 1)
    return sl * kv_pos.astype(jnp.float32)[:, None, None, None, :]


def mha(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
        mask: Optional[jnp.ndarray], scale: float,
        logits_soft_cap: Optional[float] = None,
        sink: Optional[jnp.ndarray] = None,
        alibi=None) -> jnp.ndarray:
    """Masked multi-head attention core with GQA grouping.

    q (B,T,Hq,D), k/v (B,S,Hkv,D); Hq % Hkv == 0. Returns (B,T,Hq,D).
    ``sink``: per-head learned softmax sink logits (B-broadcast), shape (Hq,)
    (reference: modules/attention/sink.py — gpt-oss learned sinks).
    """
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    # QK^T on the MXU in the storage dtype (bf16 x bf16 -> fp32 accumulate);
    # softmax math stays fp32. This avoids materializing an fp32 copy of the
    # whole KV cache every decode step (the decode path is HBM-bound).
    qk = q.reshape(b, t, hkv, g, d)
    # scores: (B, Hkv, G, T, S)
    scores = jnp.einsum("bthgd,bshd->bhgts", qk, k,
                        preferred_element_type=jnp.float32) * scale
    if alibi is not None:
        scores = scores + _alibi_bias(alibi, hkv, g)
    if logits_soft_cap is not None:
        scores = logits_soft_cap * jnp.tanh(scores / logits_soft_cap)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    if sink is not None:
        # append a virtual sink column to the softmax denominator
        sink_col = jnp.broadcast_to(
            sink.astype(jnp.float32).reshape(1, hkv, g, 1, 1),
            (b, hkv, g, t, 1))
        scores_all = jnp.concatenate([scores, sink_col], axis=-1)
        m = jnp.max(scores_all, axis=-1, keepdims=True)
        e = jnp.exp(scores_all - m)
        probs = (e / jnp.sum(e, axis=-1, keepdims=True))[..., :-1]
    else:
        m = jnp.max(scores, axis=-1, keepdims=True)
        e = jnp.exp(scores - m)
        probs = e / jnp.sum(e, axis=-1, keepdims=True)
    out = jnp.einsum("bhgts,bshd->bthgd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    # v head dim may differ from q/k head dim (MLA, deepseek)
    return out.reshape(b, t, hq, v.shape[-1]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Mask construction (reference: models/model_base.py:197-376 — causal /
# windowed / chunked / speculation masks built on device from position ids)
# ---------------------------------------------------------------------------

def mha_hl(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
           mask: Optional[jnp.ndarray], scale: float,
           logits_soft_cap: Optional[float] = None,
           sink: Optional[jnp.ndarray] = None,
           alibi=None) -> jnp.ndarray:
    """:func:`mha` over the native KV-cache storage layouts
    (modules/kv_cache.py): k TRANSPOSED (B, Hkv, D, S), v (B, Hkv, S, D).
    Each einsum contracts its cache operand in place — with a shared
    layout, one of the two dots costs a materialized relayout of the live
    cache per layer per decode step (the score dot wants S on lanes, the
    value dot wants D on lanes)."""
    b, t, hq, d = q.shape
    hkv = k.shape[1]
    g = hq // hkv
    qk = q.reshape(b, t, hkv, g, d)
    scores = jnp.einsum("bthgd,bhds->bhgts", qk, k,
                        preferred_element_type=jnp.float32) * scale
    if alibi is not None:
        scores = scores + _alibi_bias(alibi, hkv, g)
    if logits_soft_cap is not None:
        scores = logits_soft_cap * jnp.tanh(scores / logits_soft_cap)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    if sink is not None:
        sink_col = jnp.broadcast_to(
            sink.astype(jnp.float32).reshape(1, hkv, g, 1, 1),
            (b, hkv, g, t, 1))
        scores_all = jnp.concatenate([scores, sink_col], axis=-1)
        m = jnp.max(scores_all, axis=-1, keepdims=True)
        e = jnp.exp(scores_all - m)
        probs = (e / jnp.sum(e, axis=-1, keepdims=True))[..., :-1]
    else:
        m = jnp.max(scores, axis=-1, keepdims=True)
        e = jnp.exp(scores - m)
        probs = e / jnp.sum(e, axis=-1, keepdims=True)
    out = jnp.einsum("bhgts,bhsd->bthgd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, hq, v.shape[-1]).astype(q.dtype)


def mha_decode_merged(q: jnp.ndarray, k_prior: jnp.ndarray,
                      v_prior: jnp.ndarray, mask_prior: jnp.ndarray,
                      k_side: jnp.ndarray, v_side: jnp.ndarray,
                      mask_side: jnp.ndarray, k_new: jnp.ndarray,
                      v_new: jnp.ndarray, scale: float,
                      logits_soft_cap: Optional[float] = None,
                      sink: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Decode attention over a READ-ONLY prior cache plus a small side
    buffer holding the current decode chunk's K/V, combined with a
    two-block online-softmax merge (the reference's decomposed prior+active
    TKG attention, attention_base.py:1383-1461, at chunk granularity).

    Keeping the big cache free of in-scan writes is what lets XLA read the
    loop-carried cache in place: a dynamic-update-slice inside the decode
    scan forces a materialized relayout of the live cache every step
    (measured ~0.29 ms/step at B=2/S=1024/16L on v5e).

    q (B,1,Hq,D); k_prior (B,Hkv,D,S) transposed-K; v_prior (B,Hkv,S,Dv);
    k_side (B,Hkv,D,C); v_side (B,Hkv,C,Dv); mask_prior (B,1,S) must
    exclude every slot the side buffer covers; mask_side (B,1,C) selects
    the side entries written so far. k_new/v_new (B,1,Hkv,D/Dv): the ACTIVE
    token's K/V folded in-register (the side buffer write is batched to the
    step end, so the active token is not in the side buffer yet).
    """
    b, t, hq, d = q.shape
    hkv = k_prior.shape[1]
    g = hq // hkv
    qk = q.reshape(b, t, hkv, g, d)
    sp = jnp.einsum("bthgd,bhds->bhgts", qk, k_prior,
                    preferred_element_type=jnp.float32) * scale
    ss = jnp.einsum("bthgd,bhdc->bhgtc", qk, k_side,
                    preferred_element_type=jnp.float32) * scale
    sa = jnp.einsum("bthgd,bthd->bhgt", qk, k_new,
                    preferred_element_type=jnp.float32)[..., None] * scale
    if logits_soft_cap is not None:
        sp = logits_soft_cap * jnp.tanh(sp / logits_soft_cap)
        ss = logits_soft_cap * jnp.tanh(ss / logits_soft_cap)
        sa = logits_soft_cap * jnp.tanh(sa / logits_soft_cap)
    sp = jnp.where(mask_prior[:, None, None, :, :], sp, NEG_INF)
    ss = jnp.where(mask_side[:, None, None, :, :], ss, NEG_INF)
    m = jnp.maximum(jnp.maximum(jnp.max(sp, axis=-1, keepdims=True),
                                jnp.max(ss, axis=-1, keepdims=True)), sa)
    if sink is not None:
        sink_b = sink.astype(jnp.float32).reshape(1, hkv, g, 1, 1)
        m = jnp.maximum(m, sink_b)
    ep = jnp.exp(sp - m)
    es = jnp.exp(ss - m)
    ea = jnp.exp(sa - m)
    den = (jnp.sum(ep, axis=-1, keepdims=True)
           + jnp.sum(es, axis=-1, keepdims=True) + ea)
    if sink is not None:
        # the sink column joins the denominator only (no value contribution)
        den = den + jnp.exp(sink_b - m)
    out = jnp.einsum("bhgts,bhsd->bthgd", (ep / den).astype(v_prior.dtype),
                     v_prior, preferred_element_type=jnp.float32)
    out = out + jnp.einsum("bhgtc,bhcd->bthgd",
                           (es / den).astype(v_side.dtype), v_side,
                           preferred_element_type=jnp.float32)
    # active-token value: coeff (B,Hkv,G,T,1) -> (B,T,Hkv,G,1) * v_new
    coeff = jnp.transpose(ea / den, (0, 3, 1, 2, 4))
    out = out + coeff * v_new[:, :, :, None, :].astype(jnp.float32)
    return out.reshape(b, t, hq, v_new.shape[-1]).astype(q.dtype)


def causal_mask(position_ids: jnp.ndarray, kv_positions: jnp.ndarray,
                kv_valid: Optional[jnp.ndarray] = None,
                window: int = 0, chunk: int = 0) -> jnp.ndarray:
    """Boolean attend-mask (B, T, S) from query positions (B, T) and key
    positions (B, S).

    window > 0: sliding-window attention (attend iff 0 <= qpos-kpos < window).
    chunk  > 0: chunked/local attention (attend iff same chunk, Llama4-style).
    kv_valid: (B, S) bool — which cache slots hold real tokens.
    """
    qp = position_ids[:, :, None]
    kp = kv_positions[:, None, :]
    m = kp <= qp
    if window > 0:
        m &= (qp - kp) < window
    if chunk > 0:
        m &= (qp // chunk) == (kp // chunk)
    if kv_valid is not None:
        m &= kv_valid[:, None, :]
    return m


def prefill_causal_mask(seq_len: int, position_ids: jnp.ndarray,
                        window: int = 0, chunk: int = 0) -> jnp.ndarray:
    """Standard in-context causal mask for context encoding: query/key
    positions are both ``position_ids`` (B, S) over the padded window."""
    return causal_mask(position_ids, position_ids, None, window, chunk)


def rolling_decode_mask(position_ids: jnp.ndarray, window: int
                        ) -> jnp.ndarray:
    """Decode mask over a ROLLING cache of ``window`` slots where slot j
    holds position p_j = P - ((P - j) mod w) for current position P —
    attend iff that position exists (p_j >= 0); the window constraint
    p_j > P - w is inherent to the layout (reference rolling write:
    kv_cache_manager.py:605-606)."""
    qp = position_ids[:, :, None]                    # (B, T, 1)
    j = jnp.arange(window, dtype=position_ids.dtype)[None, None, :]
    pj = qp - ((qp - j) % window)
    return pj >= 0


def decode_mask(position_ids: jnp.ndarray, cache_len: int,
                window: int = 0, chunk: int = 0) -> jnp.ndarray:
    """Mask for token generation over a contiguous cache of length
    ``cache_len`` whose slot i holds position i. position_ids: (B, T)."""
    kv_pos = jnp.arange(cache_len, dtype=position_ids.dtype)[None, :]
    kv_pos = jnp.broadcast_to(kv_pos, (position_ids.shape[0], cache_len))
    return causal_mask(position_ids, kv_pos, None, window, chunk)


def speculation_mask(position_ids: jnp.ndarray, cache_len: int,
                     window: int = 0) -> jnp.ndarray:
    """Mask for a block of k speculative tokens (B, k) against the cache —
    same math as decode_mask; kept as a named entry point for parity with the
    reference's speculation mask branch (model_base.py:259-306)."""
    return decode_mask(position_ids, cache_len, window)
