"""inference_demo CLI (reference: inference_demo.py — argparse mirror of the
config system :99-408, run flow :493-680).

Subcommand ``run`` compiles + loads a model, generates from prompts, and
optionally runs the accuracy gates and benchmark, mirroring the reference's
``inference_demo --model-type llama --task-type causal-lm run ...``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional

import numpy as np

logger = logging.getLogger("nxdi_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="inference_demo_tpu")
    p.add_argument("--model-type", default=None,
                   help="model family (llama/mistral/qwen2/qwen3/...); "
                        "default: read from config.json")
    p.add_argument("--task-type", default="causal-lm")
    sub = p.add_subparsers(dest="command", required=True)
    run = sub.add_parser("run", help="compile, load, generate")
    run.add_argument("--model-path", required=True)
    run.add_argument("--compiled-model-path", default=None)
    run.add_argument("--prompt", action="append", default=None)
    run.add_argument("--prompt-len", type=int, default=16,
                     help="random-token prompt length when no --prompt given "
                          "or no tokenizer available")
    run.add_argument("--tp-degree", type=int, default=1)
    run.add_argument("--cp-degree", type=int, default=1)
    run.add_argument("--ep-degree", type=int, default=1)
    run.add_argument("--attention-dp-degree", type=int, default=1)
    run.add_argument("--sequence-parallel", action="store_true")
    run.add_argument("--flash-decoding", action="store_true")
    run.add_argument("--batch-size", type=int, default=1)
    run.add_argument("--max-context-length", type=int, default=128)
    run.add_argument("--seq-len", type=int, default=256)
    run.add_argument("--dtype", default="bfloat16",
                     choices=["bfloat16", "float32", "float16"])
    run.add_argument("--max-new-tokens", type=int, default=64)
    run.add_argument("--random-weights", action="store_true",
                     help="skip checkpoint load; synthetic weights")
    run.add_argument("--on-cpu", action="store_true",
                     help="run on virtual CPU devices (reference --on-cpu)")
    run.add_argument("--enable-bucketing", action="store_true", default=True)
    run.add_argument("--no-bucketing", dest="enable_bucketing",
                     action="store_false")
    run.add_argument("--decode-chunk-tokens", type=int, default=1)
    run.add_argument("--enable-2d-bucketing", action="store_true",
                     help="batch x seq TKG buckets + paged table-width "
                          "buckets (reference: autobucketing.py:22-64,203)")
    run.add_argument("--windowed-context-encoding", type=int, default=None,
                     help="prefill window size for >=32k prompts "
                          "(reference: model_base.py:878-933)")
    # quantization (reference: models/config.py:216-241)
    run.add_argument("--quantized", action="store_true")
    run.add_argument("--quantization-dtype", default="int8",
                     choices=["int8", "fp8", "mxfp4"])
    run.add_argument("--quantization-type", default="per_channel_symmetric",
                     choices=["per_channel_symmetric", "per_tensor_symmetric",
                              "blockwise_symmetric"])
    run.add_argument("--moe-tkg-ep-degree", type=int, default=None,
                     help="hybrid CTE/TKG expert sharding: 1 = decode "
                          "all-experts-local (reference "
                          "HybridShardingConfig)")
    run.add_argument("--kv-cache-dtype", default=None)
    run.add_argument("--kv-cache-quant", action="store_true")
    # paged KV / prefix caching / chunked prefill
    run.add_argument("--block-kv", action="store_true",
                     help="paged (block) KV cache layout")
    run.add_argument("--prefix-caching", action="store_true")
    run.add_argument("--chunked-prefill", action="store_true")
    run.add_argument("--pa-block-size", type=int, default=32)
    # speculation (reference: --speculation-length / --draft-model-path)
    run.add_argument("--speculation-length", type=int, default=0)
    run.add_argument("--draft-model-path", default=None)
    # LoRA serving
    run.add_argument("--lora-ckpt", action="append", default=None,
                     metavar="NAME=PATH", help="PEFT adapter dir, repeatable")
    run.add_argument("--max-loras", type=int, default=4)
    run.add_argument("--max-lora-rank", type=int, default=16)
    run.add_argument("--adapter-id", type=int, default=None,
                     help="adapter slot used for this run's requests")
    # sampling
    run.add_argument("--on-device-sampling", action="store_true")
    run.add_argument("--do-sample", action="store_true")
    run.add_argument("--top-k", type=int, default=1)
    run.add_argument("--top-p", type=float, default=1.0)
    run.add_argument("--temperature", type=float, default=1.0)
    # accuracy (reference: --check-accuracy-mode)
    run.add_argument("--check-accuracy-mode", default="skip-accuracy-check",
                     choices=["skip-accuracy-check", "token-matching",
                              "logit-matching"])
    run.add_argument("--divergence-difference-tol", type=float, default=0.001)
    run.add_argument("--num-tokens-to-check", type=int, default=16)
    # benchmark (reference: --benchmark)
    run.add_argument("--benchmark", action="store_true")
    run.add_argument("--benchmark-runs", type=int, default=5)
    run.add_argument("--benchmark-report-path",
                     default="benchmark_report.json")
    # observability: enable the runtime telemetry registry and dump its JSON
    # snapshot (metrics + request spans) to PATH on exit
    run.add_argument("--metrics-json", default=None, metavar="PATH",
                     help="enable runtime telemetry; write the registry "
                          "snapshot (metrics + spans) as JSON to PATH")
    run.add_argument("--seed", type=int, default=0)
    return p


def _force_cpu(n: int = 8):
    from .compat import force_cpu_devices
    force_cpu_devices(n)


def run_inference(args) -> int:
    if args.on_cpu:
        _force_cpu(max(args.tp_degree, 8))
    metrics_reg = None
    if args.metrics_json:
        from . import telemetry
        metrics_reg = telemetry.enable()
    try:
        return _run_inference(args)
    finally:
        if metrics_reg is not None:
            # never let a bad --metrics-json path shadow the run's own error
            try:
                with open(args.metrics_json, "w") as f:
                    json.dump(metrics_reg.snapshot(), f, indent=2)
            except OSError as e:
                logger.error("could not write telemetry snapshot to %s: %s",
                             args.metrics_json, e)
            else:
                line = metrics_reg.stats_line()
                if line:
                    logger.info("telemetry: %s", line)
                logger.info("telemetry snapshot written to %s",
                            args.metrics_json)


def _run_inference(args) -> int:
    from .config import (InferenceConfig, LoraServingConfig, MoEConfig,
                         OnDeviceSamplingConfig, SpeculationConfig, TpuConfig,
                         load_pretrained_config)
    from .models.application import (CausalLMApplication,
                                     PagedCausalLMApplication)
    from .models.family import get_family

    sampling_cfg = None
    if args.on_device_sampling or args.do_sample:
        sampling_cfg = OnDeviceSamplingConfig(
            do_sample=args.do_sample, top_k=args.top_k, top_p=args.top_p,
            temperature=args.temperature)
    lora_cfg = None
    lora_paths = {}
    if args.lora_ckpt:
        for item in args.lora_ckpt:
            name, _, path = item.partition("=")
            lora_paths[name] = path
        lora_cfg = LoraServingConfig(max_loras=args.max_loras,
                                     max_lora_rank=args.max_lora_rank,
                                     lora_ckpt_paths=lora_paths)
    spec_cfg = None
    if args.speculation_length > 0:
        spec_cfg = SpeculationConfig(
            speculation_length=args.speculation_length,
            enable_fused_speculation=True,
            draft_model_path=args.draft_model_path)

    def make_tcfg(**over):
        kw = dict(
            batch_size=args.batch_size, seq_len=args.seq_len,
            max_context_length=args.max_context_length, dtype=args.dtype,
            tp_degree=args.tp_degree, cp_degree=args.cp_degree,
            ep_degree=args.ep_degree,
            attention_dp_degree=args.attention_dp_degree,
            sequence_parallel_enabled=args.sequence_parallel,
            flash_decoding_enabled=args.flash_decoding,
            enable_bucketing=args.enable_bucketing,
            enable_2d_bucketing=args.enable_2d_bucketing,
            windowed_context_encoding=args.windowed_context_encoding,
            decode_chunk_tokens=args.decode_chunk_tokens,
            on_device_sampling_config=sampling_cfg,
            quantized=args.quantized,
            quantization_dtype=args.quantization_dtype,
            quantization_type=args.quantization_type,
            kv_cache_dtype=args.kv_cache_dtype,
            kv_cache_quant=args.kv_cache_quant,
            is_block_kv_layout=args.block_kv or args.prefix_caching
            or args.chunked_prefill,
            is_prefix_caching=args.prefix_caching,
            is_chunked_prefill=args.chunked_prefill,
            pa_block_size=args.pa_block_size,
            lora_config=lora_cfg,
            moe_config=(MoEConfig(moe_tkg_ep_degree=args.moe_tkg_ep_degree)
                        if args.moe_tkg_ep_degree is not None else None),
            output_logits=args.check_accuracy_mode == "logit-matching",
            compile_cache_dir=args.compiled_model_path, seed=args.seed)
        kw.update(over)
        return TpuConfig(**kw)

    tcfg = make_tcfg(speculation_config=spec_cfg)

    # model family from config.json unless overridden
    with open(os.path.join(args.model_path, "config.json")) as f:
        model_type = args.model_type or json.load(f).get("model_type")
    family = get_family(model_type)
    icfg = family.config_cls(tcfg,
                             load_config=load_pretrained_config(args.model_path))
    app_cls = (PagedCausalLMApplication if tcfg.is_block_kv_layout
               else CausalLMApplication)
    app = app_cls(args.model_path, icfg, family)
    if args.random_weights:
        app.init_random_weights(args.seed)
    else:
        app.load_weights()
    app.init_cache()
    if lora_cfg is not None and lora_paths and not args.random_weights:
        app.load_lora_adapters(lora_paths)
    if args.compiled_model_path:
        app.compile(args.compiled_model_path)

    decoder = None
    if spec_cfg is not None and args.draft_model_path:
        from .models.speculation import SpeculativeDecoder
        with open(os.path.join(args.draft_model_path, "config.json")) as f:
            draft_type = json.load(f).get("model_type")
        d_family = get_family(draft_type)
        d_icfg = d_family.config_cls(
            make_tcfg(speculation_config=spec_cfg),
            load_config=load_pretrained_config(args.draft_model_path))
        draft = CausalLMApplication(args.draft_model_path, d_icfg, d_family)
        if args.random_weights:
            draft.init_random_weights(args.seed + 1)
        else:
            draft.load_weights()
        draft.init_cache()
        decoder = SpeculativeDecoder(app, draft)

    # build input ids: tokenizer if available, else random tokens
    tokenizer = None
    eos = None
    try:
        from transformers import AutoTokenizer
        tokenizer = AutoTokenizer.from_pretrained(args.model_path)
        eos = tokenizer.eos_token_id
    except Exception:
        logger.info("no tokenizer found; using random token prompts")
    if args.prompt and tokenizer is not None:
        prompts = args.prompt * args.batch_size
        enc = tokenizer(prompts[:args.batch_size], return_tensors="np",
                        padding=True, padding_side="right")
        input_ids = enc["input_ids"].astype(np.int32)
        attention_mask = enc["attention_mask"].astype(np.int32)
    else:
        rng = np.random.default_rng(args.seed)
        input_ids = rng.integers(
            1, icfg.vocab_size, size=(args.batch_size, args.prompt_len),
            dtype=np.int32)
        attention_mask = np.ones_like(input_ids)

    gen_kwargs = {}
    if args.adapter_id is not None:
        gen_kwargs["adapter_ids"] = np.full((args.batch_size,),
                                            args.adapter_id, np.int32)
    if decoder is not None:
        res = decoder.generate(input_ids, max_new_tokens=args.max_new_tokens,
                               eos_token_id=eos,
                               attention_mask=attention_mask)
        print(f"speculation: {res['mean_tokens_per_step']:.2f} tokens/step")
    else:
        res = app.generate(input_ids, attention_mask=attention_mask,
                           max_new_tokens=args.max_new_tokens,
                           eos_token_id=eos, **gen_kwargs)
        print(f"TTFT: {res['ttft_s'] * 1e3:.1f} ms")
    for i, row in enumerate(res["sequences"]):
        if tokenizer is not None:
            print(f"--- output {i} ---")
            print(tokenizer.decode(row, skip_special_tokens=True))
        else:
            print(f"--- output {i} --- {row.tolist()}")

    rc = 0
    if args.check_accuracy_mode != "skip-accuracy-check":
        from .utils import accuracy
        hf_model = family.load_hf_model(args.model_path)
        app.reset()
        if args.check_accuracy_mode == "token-matching":
            rep = accuracy.check_accuracy(
                app, hf_model, input_ids, attention_mask=attention_mask,
                max_new_tokens=args.num_tokens_to_check, eos_token_id=eos)
        else:
            rep = accuracy.check_accuracy_logits(
                app, hf_model, input_ids, attention_mask=attention_mask,
                max_new_tokens=args.num_tokens_to_check,
                divergence_difference_tol=args.divergence_difference_tol)
        print(rep)
        rc = 0 if rep.passed else 1

    if args.benchmark:
        from .utils.benchmark import benchmark_sampling
        app.reset()
        report = benchmark_sampling(app, input_ids,
                                    max_new_tokens=args.max_new_tokens,
                                    n_runs=args.benchmark_runs,
                                    report_path=args.benchmark_report_path)
        print(json.dumps(report, indent=2))
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=logging.INFO)
    args = build_parser().parse_args(argv)
    if args.command == "run":
        return run_inference(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
