"""Dependency-free serving metrics registry.

The reference NxDI stack leans on external tooling (neuron-profile, runtime
counters) for production visibility; serving engines treat per-request
latency, occupancy gauges and recompile accounting as first-class (vLLM /
Orca-style continuous batching — PAPERS.md). This module is the TPU repro's
equivalent: a tiny Prometheus-style registry with three instrument kinds
(:class:`Counter`, :class:`Gauge`, :class:`Histogram` with fixed log-spaced
latency buckets) and labeled series, plus two pure export surfaces —
``render_prometheus()`` (text exposition format) and ``snapshot()`` (a
JSON-able dict) — so tests and CLIs need no HTTP server.

Zero-cost-when-disabled: the module-global default registry is a
:class:`NullRegistry` whose instruments are shared no-ops, so library code
can call ``registry.counter(...).inc(...)`` unconditionally on the host path.
Instrumented call sites must still measure at host boundaries only — never
inside traced code (a host sync inside a jitted graph would change the graph).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_LATENCY_BUCKETS", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "NullRegistry", "NULL_REGISTRY",
    "get_registry", "set_registry", "enable", "disable",
    "render_series",
]

# Log-spaced latency ladder (seconds), 100 us .. 60 s. Fixed so that series
# from different processes/runs line up; chosen to straddle both host-side
# dispatch (~100 us) and cold-compile stalls (tens of seconds).
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0)

_NAME_OK = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"


def _check_name(name: str):
    if not name or name[0].isdigit() or any(c not in _NAME_OK for c in name):
        raise ValueError(f"invalid metric name {name!r}")


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    """Float → Prometheus sample text (shortest round-trippable form)."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    out = repr(float(v))
    return out[:-2] if out.endswith(".0") else out


def _labels_key(label_names: Tuple[str, ...], labels: Dict[str, Any]
                ) -> Tuple[str, ...]:
    if set(labels) != set(label_names):
        raise ValueError(f"expected labels {label_names}, got "
                         f"{tuple(sorted(labels))}")
    return tuple(str(labels[k]) for k in label_names)


def _labels_to_text(labels: Dict[str, Any]) -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in labels.items()]
    return "{" + ",".join(parts) + "}" if parts else ""


def render_series(name: str, kind: str, entry: Dict[str, Any],
                  extra_labels: Optional[Dict[str, str]] = None
                  ) -> List[str]:
    """Prometheus sample lines for ONE ``snapshot()`` series entry —
    THE snapshot-driven renderer, shared by the live registry's own
    ``render_prometheus()`` and the fleet aggregator
    (serving/fleet/aggregator.py), so the two exposition surfaces can
    never drift apart. ``extra_labels`` are prepended (the aggregator's
    ``replica`` label)."""
    labels = dict(extra_labels or {})
    labels.update(entry["labels"])
    lt = _labels_to_text(labels)
    if kind == "histogram":
        lines = []
        for bound, cum in entry["buckets"]:
            le = _labels_to_text({**labels, "le": _fmt(bound)})
            lines.append(f"{name}_bucket{le} {cum}")
        inf = _labels_to_text({**labels, "le": "+Inf"})
        lines.append(f"{name}_bucket{inf} {entry['count']}")
        lines.append(f"{name}_sum{lt} {_fmt(entry['sum'])}")
        lines.append(f"{name}_count{lt} {entry['count']}")
        return lines
    return [f"{name}{lt} {_fmt(entry['value'])}"]


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Sequence[str] = ()):
        _check_name(name)
        for ln in labels:
            _check_name(ln)
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(labels)
        self._series: Dict[Tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    def _render(self) -> List[str]:
        # snapshot-driven, through THE shared renderer (render_series) —
        # the fleet aggregator rides the same code path
        return [line for entry in self._snapshot()
                for line in render_series(self.name, self.kind, entry)]


class Counter(_Metric):
    """Monotonically increasing counter with optional labels."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        key = _labels_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def get(self, **labels) -> float:
        return self._series.get(_labels_key(self.label_names, labels), 0.0)

    def _snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"labels": dict(zip(self.label_names, k)), "value": v}
                    for k, v in sorted(self._series.items())]


class Gauge(_Metric):
    """Point-in-time value with optional labels."""

    kind = "gauge"

    def set(self, value: float, **labels):
        key = _labels_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = _labels_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def get(self, **labels) -> float:
        return self._series.get(_labels_key(self.label_names, labels), 0.0)

    _snapshot = Counter._snapshot


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative counts, Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help, labels)
        bs = tuple(float(b) for b in buckets)
        if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError("buckets must be strictly increasing")
        self.buckets = bs

    def observe(self, value: float, **labels):
        key = _labels_key(self.label_names, labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = self._series[key] = {
                    "counts": [0] * len(self.buckets),
                    "sum": 0.0, "count": 0}
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["counts"][i] += 1
                    break
            st["sum"] += float(value)
            st["count"] += 1

    def count(self, **labels) -> int:
        st = self._series.get(_labels_key(self.label_names, labels))
        return st["count"] if st else 0

    def sum(self, **labels) -> float:
        st = self._series.get(_labels_key(self.label_names, labels))
        return st["sum"] if st else 0.0

    def percentile(self, q: float, **labels) -> float:
        """Bucket-upper-bound approximation of the q-th percentile
        (0 <= q <= 1). Returns 0.0 for an empty series."""
        st = self._series.get(_labels_key(self.label_names, labels))
        if not st or st["count"] == 0:
            return 0.0
        target = q * st["count"]
        acc = 0
        for i, c in enumerate(st["counts"]):
            acc += c
            if acc >= target and c:
                return self.buckets[i]
        return st["sum"] / st["count"]  # everything beyond the last bucket

    def _cumulative(self, st) -> List[int]:
        out, acc = [], 0
        for c in st["counts"]:
            acc += c
            out.append(acc)
        return out

    def _snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for key, st in sorted(self._series.items()):
                out.append({
                    "labels": dict(zip(self.label_names, key)),
                    "count": st["count"], "sum": st["sum"],
                    "buckets": [[b, c] for b, c in
                                zip(self.buckets, self._cumulative(st))],
                })
            return out


class MetricsRegistry:
    """Live registry: get-or-create instruments by name, export as
    Prometheus text or a JSON-able snapshot. Also keeps a bounded ring of
    finished request :class:`~..telemetry.spans.Span` event logs."""

    enabled = True

    def __init__(self, max_spans: int = 256):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._spans: List[Dict[str, Any]] = []
        self._max_spans = max_spans
        self.spans_dropped = 0

    # -- instruments ------------------------------------------------------
    def _get_or_create(self, cls, name, help, labels, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labels, **kw)
                return m
        if not isinstance(m, cls):
            raise ValueError(f"metric {name!r} already registered as "
                             f"{m.kind}")
        if tuple(labels) != m.label_names:
            raise ValueError(f"metric {name!r} registered with labels "
                             f"{m.label_names}, asked for {tuple(labels)}")
        return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    # -- spans ------------------------------------------------------------
    def start_span(self, name: str, **labels):
        from .spans import Span
        return Span(name, labels=labels, registry=self)

    def record_span(self, span_dict: Dict[str, Any]):
        with self._lock:
            self._spans.append(span_dict)
            excess = len(self._spans) - self._max_spans
            if excess > 0:
                del self._spans[:excess]
                self.spans_dropped += excess
        if excess > 0:
            # evictions were silent before the flight-recorder work: count
            # them so a snapshot/post-mortem states its own truncation
            # (counter registration outside self._lock — it re-takes it)
            from . import metrics as tmetrics
            tmetrics.trace_events_dropped_counter(self).inc(excess,
                                                            ring="spans")

    @property
    def spans(self) -> List[Dict[str, Any]]:
        return list(self._spans)

    # -- export (pure; no server required) --------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m._render())
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump of every metric series + finished request spans."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return {
            "metrics": {
                name: {"type": m.kind, "help": m.help,
                       "series": m._snapshot()}
                for name, m in metrics
            },
            "spans": self.spans,
        }

    def stats_line(self) -> str:
        """One compact human line (bench/CLI heartbeat)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        parts = []
        for name, m in metrics:
            with m._lock:   # a concurrent inc() may add a new label series
                if isinstance(m, Histogram):
                    n = sum(st["count"] for st in m._series.values())
                    s = sum(st["sum"] for st in m._series.values())
                    if n:
                        parts.append(f"{name}: n={n} mean={s / n * 1e3:.2f}ms")
                else:
                    total = sum(m._series.values())
                    if total:
                        parts.append(f"{name}={_fmt(total)}")
        return " | ".join(parts)


class _NullInstrument:
    """Shared do-nothing instrument handed out by :class:`NullRegistry`."""

    def inc(self, *a, **k):
        pass

    def dec(self, *a, **k):
        pass

    def set(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass

    def get(self, **k):
        return 0.0

    def count(self, **k):
        return 0

    def sum(self, **k):
        return 0.0

    def percentile(self, q, **k):
        return 0.0


_NULL_INSTRUMENT = _NullInstrument()


class NullRegistry:
    """Disabled registry: every instrument is a shared no-op; exports are
    empty. The library default — callers pay one attribute check."""

    enabled = False
    spans: List[Dict[str, Any]] = []

    def counter(self, *a, **k):
        return _NULL_INSTRUMENT

    def gauge(self, *a, **k):
        return _NULL_INSTRUMENT

    def histogram(self, *a, **k):
        return _NULL_INSTRUMENT

    def get(self, name):
        return None

    def start_span(self, name, **labels):
        from .spans import NULL_SPAN
        return NULL_SPAN

    def record_span(self, span_dict):
        pass

    def render_prometheus(self) -> str:
        return ""

    def snapshot(self) -> Dict[str, Any]:
        return {"metrics": {}, "spans": []}

    def stats_line(self) -> str:
        return ""


NULL_REGISTRY = NullRegistry()
_global_registry: Any = NULL_REGISTRY


def get_registry():
    """The process-global registry (a NullRegistry unless :func:`enable`\\ d
    or explicitly :func:`set_registry`'d)."""
    return _global_registry


def set_registry(reg) -> None:
    global _global_registry
    _global_registry = reg if reg is not None else NULL_REGISTRY


def enable() -> MetricsRegistry:
    """Swap a live registry into the global slot (idempotent)."""
    global _global_registry
    if not isinstance(_global_registry, MetricsRegistry):
        _global_registry = MetricsRegistry()
    return _global_registry


def disable() -> None:
    global _global_registry
    _global_registry = NULL_REGISTRY
