"""Runtime telemetry: metrics registry + request spans (serving-grade
observability, complementing the debug-grade snapshot/profiling tools in
``utils/``).

Quick start::

    from neuronx_distributed_inference_tpu import telemetry
    reg = telemetry.enable()          # global registry (default: disabled)
    ... serve ...
    print(reg.render_prometheus())    # Prometheus text exposition
    json.dump(reg.snapshot(), fh)     # JSON snapshot (+ request spans)

Disabled (the library default) every instrument is a shared no-op and the
instrumented hot paths skip their timing blocks — outputs and jit cache keys
are bit-identical to an uninstrumented build.
"""

from . import metrics
from .registry import (DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram,
                       MetricsRegistry, NULL_REGISTRY, NullRegistry, disable,
                       enable, get_registry, set_registry)
from .request_trace import (chrome_by_trace, new_trace_id, trace_events,
                            trace_of)
from .slo import RollingWindow, SLOPolicy, SLOTracker
from .spans import NULL_SPAN, NullSpan, Span
from .trace import (FlightRecorder, NULL_RECORDER, NullFlightRecorder,
                    disable_recorder, enable_recorder, get_recorder,
                    set_recorder)

__all__ = [
    "metrics",
    "DEFAULT_LATENCY_BUCKETS", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "NULL_REGISTRY", "NullRegistry",
    "enable", "disable", "get_registry", "set_registry",
    "Span", "NullSpan", "NULL_SPAN",
    "FlightRecorder", "NullFlightRecorder", "NULL_RECORDER",
    "enable_recorder", "disable_recorder", "get_recorder", "set_recorder",
    "new_trace_id", "trace_of", "trace_events", "chrome_by_trace",
    "RollingWindow", "SLOPolicy", "SLOTracker",
]
