"""Compiled-graph observatory — static truth about the serving graphs.

AOT-lowers and compiles every (kind, bucket, batch) graph an application's
bucket ladders imply (``jax.jit(...).lower(...).compile()`` — no execution,
no device state touched) and harvests XLA's own static analysis:

  * ``cost_analysis()``   — flops and bytes accessed per invocation;
  * ``memory_analysis()`` — argument/output/temp byte footprints (peak ≈
    arguments + outputs + temps);
  * compile wall time per graph (the cold-start cost item 5 of the
    ROADMAP tracks: ``compile_plus_first_gen_s`` grew 5.7s→14.3s).

All of it works on the CPU backend — this is the evidence base for
re-earning the frozen kernel-admission constants (``model_base.py``
heuristics) and for AOT warm-start work, WITHOUT waiting for TPU hardware
(cf. full-program XLA compilation analysis, PAPERS.md arxiv 1810.09868).

Per-graph results land in the metrics registry when one is live
(``nxdi_compile_seconds`` / ``nxdi_graph_flops`` / ``nxdi_graph_bytes`` /
``nxdi_graph_peak_bytes``, labels ``kind``+``bucket``) and in the returned
report dict (schema ``nxdi-graph-report-v1``), which also carries a static
roofline estimate per bucket: arithmetic intensity, the
compute-vs-memory-bound verdict, and the estimated step time under the
assumed peak flops / HBM bandwidth (``NXDI_TPU_PEAK_TFLOPS``, default 197
— v5e bf16; ``NXDI_TPU_HBM_GBPS``, default 819).

``bench.py --graph-report`` drives this on the tiny synthetic model and
commits the artifact (``artifacts/graph_report_r08.json``) so cold-start
and graph-size regressions show up in BENCH_* rounds with no hardware.

Compiling through fresh ``jax.jit`` wrappers keeps the application's own
jit cache keys untouched — running the observatory can never change what
the serving path executes (the XLA persistent compile cache still
deduplicates the work).

Sharding observatory (multichip census)
---------------------------------------
When the application's mesh spans more than one device the same AOT
compile yields the **post-SPMD partitioned** HLO, and
:func:`census_collectives` reads every collective out of it: op kind
(all-reduce / all-gather / reduce-scatter / collective-permute /
all-to-all), payload bytes, and the replica-group shape mapped back to
the mesh axes the groups ride (``comm="tp"`` / ``"dp"`` / ``"ep+tp"`` /
…) plus the wire payload dtype (``f32`` / ``s8`` / ``f8e4m3fn`` — the
dimension that makes the quantized-collective win census-visible). The
census lands per graph in the report, in the
``nxdi_graph_collectives_total`` / ``nxdi_graph_collective_bytes``
gauges (labels ``kind``+``comm``+``dtype``), and in a third roofline
leg: the estimated collective wire time under ``NXDI_TPU_ICI_GBPS``
(default 200 GB/s — v5e ICI) and ``NXDI_TPU_DCN_GBPS`` (default 25
GB/s; axes named by the ``parallel.mesh.Topology`` spec — by default
``dp``, the outermost axis — are priced at DCN, everything else at
ICI), upgrading the per-graph verdict to compute- vs memory- vs
**comm**-bound — the regime EQuARX (PAPERS.md arxiv 2506.17615) shows
dominates DCN-scale decode. The leg also reports ``comm_bytes_saved``:
wire bytes the sub-fp32 payloads avoid relative to an fp32 exchange of
the same shapes.

Collectives censused inside a ``while``/``scan`` body are counted once
(static census, not dynamic executions). On a single-device mesh the
census doubles as a guard: the unsharded graphs must contain ZERO
collectives (an accidental ``shard_map``/``psum`` leaking into the
1-device path raises here instead of silently running).

``scripts/check_spmd_sharding.py`` builds on this census as a tier-1
lint: it compiles a pinned multichip graph set, fails on the SPMD
partitioner's involuntary-full-rematerialization pattern, and diffs the
census against the committed golden (``artifacts/spmd_golden.json``).
"""

from __future__ import annotations

import contextlib
import os
import re
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import metrics as tmetrics
from .registry import get_registry
from ..parallel.mesh import Topology, topology_from_env

__all__ = ["analyze_app", "census_collectives", "aggregate_census",
           "comm_roofline_seconds", "mesh_comm_labels",
           "capture_compiler_stderr", "REMAT_WARNING_RE", "SPMD_CHANNEL_RE",
           "GRAPH_REPORT_SCHEMA", "SHARDING_REPORT_SCHEMA",
           "COLLECTIVE_KINDS"]

GRAPH_REPORT_SCHEMA = "nxdi-graph-report-v1"
SHARDING_REPORT_SCHEMA = "nxdi-sharding-report-v1"

# ---------------------------------------------------------------------------
# SPMD partitioner warning channel (shared by __graft_entry__'s multichip
# runner and scripts/check_spmd_sharding.py — one copy of the spellings)
# ---------------------------------------------------------------------------

# the partitioner's replicate-then-partition last resort, spelled
# differently across XLA builds (older W-lines: "[SPMD] Involuntary full
# rematerialization. ... SPMD will replicate the tensor"; newer E-lines:
# "[spmd] Involuntary full rematerialization. The compiler was not able
# to go from sharding ...") — match the stable core phrase
REMAT_WARNING_RE = re.compile(r"involuntary full rematerialization", re.I)
SPMD_CHANNEL_RE = re.compile(r"\[spmd\]", re.I)


@contextlib.contextmanager
def capture_compiler_stderr(counts: Optional[Dict[str, int]] = None,
                            tee: bool = True):
    """Capture everything written to fd 2 (Python AND C++ — the SPMD
    partitioner logs through glog) around a compile. Yields a one-element
    list holding the captured text after exit. With ``tee``, bytes are
    written THROUGH to the real stderr as they arrive (a pump thread off
    a pipe) — a hard kill mid-compile loses the counts but not the live
    warning tail the multichip runner's log used to stream. With
    ``counts``, accumulates ``spmd_warnings`` (all [SPMD] channel lines)
    and ``involuntary_remat`` (the replicate-then-partition subset).
    Degrades to a no-op when fd 2 is not a real descriptor."""
    out: List[str] = [""]
    # glog/XLA logs to LITERAL fd 2, not sys.stderr — which under test
    # runners (pytest capture) is a temp-file wrapper on another fd
    fd = 2
    try:
        saved = os.dup(fd)
    except OSError:
        yield out
        return
    read_fd, write_fd = os.pipe()
    chunks: List[bytes] = []

    def _pump():
        while True:
            try:
                data = os.read(read_fd, 65536)
            except OSError:
                break
            if not data:
                break
            chunks.append(data)
            if tee:
                try:
                    os.write(saved, data)
                except OSError:
                    pass
        os.close(read_fd)

    pump = threading.Thread(target=_pump, daemon=True)
    pump.start()
    try:
        sys.stderr.flush()
        os.dup2(write_fd, fd)
        yield out
    finally:
        sys.stderr.flush()
        os.dup2(saved, fd)
        os.close(write_fd)      # EOF to the pump (fd now points at saved)
        pump.join(timeout=10.0)
        if not pump.is_alive():
            os.close(saved)
        # else: pump stalled on a blocked downstream write — leak
        # `saved` rather than free an fd number the thread still tees to
        out[0] = b"".join(list(chunks)).decode("utf-8", "replace")
        if counts is not None:
            counts["involuntary_remat"] += len(
                REMAT_WARNING_RE.findall(out[0]))
            counts["spmd_warnings"] += sum(
                1 for l in out[0].splitlines() if SPMD_CHANNEL_RE.search(l))


# ---------------------------------------------------------------------------
# HLO collective census
# ---------------------------------------------------------------------------

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

# one HLO instruction line: "%name = <type> <op>(...), attr=..., ..."
# (async pairs: count the -start, skip the -done — one wire transfer)
_COLLECTIVE_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.-]+\s*=\s*(?P<type>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(COLLECTIVE_KINDS) + r")(?P<suffix>-start|-done)?\(")

# dtype tokens are arbitrary letter/digit runs (f32, bf16, f8e4m3b11fnuz)
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "s4": 1, "u4": 1,
}

_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(?P<dims>[0-9,]+)\]<=\[(?P<reshape>[0-9,]+)\]"
    r"(?:T\((?P<perm>[0-9,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}")


def _shape_payload(type_str: str, async_start: bool = False
                   ) -> Tuple[int, str, int]:
    """(bytes, dtype, element count) of an HLO result type. A sync tuple
    result (a variadic combined collective) transfers EVERY element; an
    async ``-start`` tuple carries (operand..., result) where the earlier
    elements alias inputs already counted — only the LAST element is the
    transferred output. ``dtype`` is the first transferred shape's element
    type token (variadic collectives are homogeneous in practice)."""
    shapes = _SHAPE_RE.findall(type_str)
    if not shapes:
        return 0, "f32", 0
    if async_start:
        # legacy 4-element permute-start tuples trail u32[] context
        # scalars after the result — strip them before taking the last
        while len(shapes) > 1 and shapes[-1][1] == "" and \
                shapes[-1][0] in ("u32", "s32"):
            shapes.pop()
        shapes = shapes[-1:]
    total = 0
    elems = 0
    for dt, dims in shapes:
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 4)
        elems += n
    return total, shapes[0][0], elems


def _shape_bytes(type_str: str, async_start: bool = False) -> int:
    return _shape_payload(type_str, async_start)[0]


def _parse_int_groups(body: str) -> List[Tuple[int, ...]]:
    return [tuple(int(x) for x in grp.split(",") if x.strip())
            for grp in re.findall(r"\{([0-9,\s]*)\}", body)]


def _iota_groups(dims: Sequence[int], reshape: Sequence[int],
                 perm: Optional[Sequence[int]]) -> List[Tuple[int, ...]]:
    """Expand the V2 iota replica-group syntax
    ``[g,s]<=[r...]T(p...)``: arange(prod) reshaped to ``r``, transposed
    by ``p``, re-reshaped to ``g`` groups of ``s``."""
    ids = np.arange(int(np.prod(reshape))).reshape(tuple(reshape))
    if perm is not None:
        ids = ids.transpose(tuple(perm))
    ids = ids.reshape(tuple(dims))
    return [tuple(int(x) for x in row) for row in ids]


def _line_groups(line: str) -> Optional[List[Tuple[int, ...]]]:
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        dims = [int(x) for x in m.group("dims").split(",")]
        reshape = [int(x) for x in m.group("reshape").split(",")]
        perm = ([int(x) for x in m.group("perm").split(",")]
                if m.group("perm") else None)
        return _iota_groups(dims, reshape, perm)
    m = _EXPLICIT_GROUPS_RE.search(line)
    if m:
        return _parse_int_groups(m.group(1))
    return None


def mesh_comm_labels(mesh) -> Dict[frozenset, str]:
    """Map replica-group *signatures* (frozenset of frozenset of LOGICAL
    device indices — position in ``mesh.devices.flat``, which is the
    device-assignment order the partitioned HLO numbers its partitions
    in) to the mesh-axis subsets they ride, e.g. ``{{0,1},{2,3}} ->
    "tp"`` on a dp2xtp2 mesh. Only axes with extent > 1 participate."""
    shape = tuple(mesh.devices.shape)
    names = tuple(mesh.axis_names)
    logical = np.arange(int(np.prod(shape))).reshape(shape)
    live = [i for i, s in enumerate(shape) if s > 1]
    out: Dict[frozenset, str] = {}
    for bits in range(1, 1 << len(live)):
        subset = [live[i] for i in range(len(live)) if bits & (1 << i)]
        rest = [i for i in range(len(shape)) if i not in subset]
        grouped = logical.transpose(rest + subset).reshape(
            -1, int(np.prod([shape[i] for i in subset])))
        sig = frozenset(frozenset(int(x) for x in row) for row in grouped)
        out.setdefault(sig, "+".join(names[i] for i in subset))
    return out


def _groups_label(groups: List[Tuple[int, ...]],
                  labels: Optional[Dict[frozenset, str]]) -> str:
    if labels is None:
        return "unmapped"
    sig = frozenset(frozenset(g) for g in groups)
    return labels.get(sig, "other")


def _pairs_label(pairs: List[Tuple[int, ...]],
                 labels: Optional[Dict[frozenset, str]]) -> str:
    """collective-permute has source→target pairs, not groups: the comm
    axis is the smallest axis subset within whose groups every pair
    stays (a tp-ring shift maps to "tp")."""
    if labels is None:
        return "unmapped"
    if not pairs:
        # unparseable/empty pairs would vacuously match EVERY subset —
        # surface them as unmatched instead of mislabeling (and
        # mispricing) the permute
        return "other"
    best = None
    for sig, label in labels.items():
        if all(any(s in grp and t in grp for grp in sig)
               for s, t in pairs):
            if best is None or len(label) < len(best):
                best = label
    return best or "other"


def census_collectives(hlo_text: str, mesh=None) -> List[Dict[str, Any]]:
    """Census every collective op in post-SPMD optimized HLO text.

    Returns one entry per op occurrence: ``{"kind", "comm", "dtype",
    "bytes", "elems", "elem_bytes", "group_size"}`` where ``kind`` is the
    op with underscores (``all_reduce``…), ``comm`` names the mesh-axis
    subset the replica groups ride (via :func:`mesh_comm_labels`;
    ``"unmapped"`` without a mesh, ``"other"`` when groups match no axis
    subset), ``dtype`` is the wire payload element type (``f32``, ``s8``,
    ``f8e4m3fn``…), ``bytes`` the op's result-tensor payload and
    ``elems``/``elem_bytes`` its element count and per-element wire width.
    Async ``-start``/``-done`` pairs are counted once (at the start)."""
    labels = mesh_comm_labels(mesh) if mesh is not None else None
    entries: List[Dict[str, Any]] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_LINE_RE.match(line)
        if not m or m.group("suffix") == "-done":
            continue
        kind = m.group("op")
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            pairs = _parse_int_groups(pm.group(1)) if pm else []
            comm = _pairs_label(pairs, labels)
            group_size = 2
        else:
            groups = _line_groups(line) or []
            comm = _groups_label(groups, labels) if groups else "other"
            group_size = max((len(g) for g in groups), default=1)
        nbytes, dtype, elems = _shape_payload(m.group("type"),
                                              m.group("suffix") == "-start")
        entries.append({
            "kind": kind.replace("-", "_"),
            "comm": comm,
            "dtype": dtype,
            "bytes": nbytes,
            "elems": elems,
            "elem_bytes": _DTYPE_BYTES.get(dtype, 4),
            "group_size": group_size,
        })
    return entries


def aggregate_census(entries: Sequence[Dict[str, Any]]
                     ) -> Dict[str, Dict[str, Any]]:
    """Aggregate per-op census entries to ``{"kind@comm@dtype": {"count",
    "bytes"}}`` — the shape the golden diff and the gauges key on. The
    dtype leg makes quantized (s8/f8) wire payloads first-class: an int8
    ring exchange and an fp32 all-reduce never fold into one bucket."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in entries:
        key = f"{e['kind']}@{e['comm']}@{e.get('dtype', 'f32')}"
        slot = out.setdefault(key, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += e["bytes"]
    return out


# ring-model wire-byte factors per collective kind: how many times the
# result tensor's bytes cross the wire per participating device
# (g = replica-group size)
def _wire_factor(kind: str, group_size: int) -> float:
    g = max(group_size, 2)
    if kind == "all_reduce":         # reduce-scatter + all-gather ring
        return 2.0 * (g - 1) / g
    if kind == "reduce_scatter":     # result is the 1/g shard
        return float(g - 1)
    if kind == "collective_permute":
        return 1.0
    # all_gather / all_to_all: result is the full tensor
    return (g - 1) / g


def _wire_bytes(entry: Dict[str, Any]) -> float:
    # element byte-width comes from the CENSUS ENTRY — the op's actual
    # wire payload dtype, not the graph dtype — so a quantized s8
    # all-reduce prices at a quarter of the f32 one. Entries from older
    # callers without the dtype leg fall back to their total bytes.
    if "elems" in entry and "elem_bytes" in entry:
        b = float(entry["elems"] * entry["elem_bytes"])
    else:
        b = float(entry["bytes"])
    return _wire_factor(entry["kind"], entry["group_size"]) * b


def _wire_bytes_saved(entry: Dict[str, Any]) -> float:
    """Wire bytes this op avoids relative to an fp32 exchange of the same
    shape — nonzero only for sub-fp32 *numeric* payloads (s8/u8/f8…), the
    quantized-collective win. Bool masks (pred) are not savings."""
    eb = entry.get("elem_bytes", 4)
    if eb >= 4 or entry.get("dtype") == "pred" or "elems" not in entry:
        return 0.0
    return (_wire_factor(entry["kind"], entry["group_size"])
            * entry["elems"] * (4 - eb))


def comm_roofline_seconds(entries: Sequence[Dict[str, Any]],
                          ici_gbps: float, dcn_gbps: float,
                          topology: Optional[Topology] = None) -> float:
    """Estimated wire time of one invocation's collectives under the
    assumed link bandwidths (GB/s). Traffic over axes the ``topology``
    marks as DCN-crossing (default: :func:`~..parallel.mesh
    .topology_from_env` — ``dp``, the outermost, DCN-friendly mesh axis)
    is priced at DCN bandwidth; every other axis (and unmapped/other
    groups) rides ICI."""
    if topology is None:
        topology = topology_from_env()
    total = 0.0
    for e in entries:
        axes = set(e["comm"].split("+"))
        bw = dcn_gbps if topology.is_dcn(axes) else ici_gbps
        if bw > 0:
            total += _wire_bytes(e) / (bw * 1e9)
    return total


def _cost(compiled) -> Tuple[float, float]:
    """(flops, bytes accessed) from XLA cost analysis; zeros when the
    backend reports nothing. Handles both the dict and the legacy
    list-of-dicts return shape."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return 0.0, 0.0
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


def _memory(compiled) -> Optional[Dict[str, int]]:
    """Byte footprints from XLA memory analysis; None when the backend
    does not expose it. ``peak_bytes`` approximates live memory as
    arguments + outputs + temps (donated aliases excluded by XLA)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def g(attr: str) -> int:
        return int(getattr(ma, attr, 0) or 0)

    out = {
        "argument_bytes": g("argument_size_in_bytes"),
        "output_bytes": g("output_size_in_bytes"),
        "temp_bytes": g("temp_size_in_bytes"),
        "alias_bytes": g("alias_size_in_bytes"),
        "generated_code_bytes": g("generated_code_size_in_bytes"),
    }
    out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                         + out["temp_bytes"])
    return out


def _graph_entries(app) -> List[Tuple[str, str, Callable[[], Tuple]]]:
    """Enumerate the (kind, bucket_label, build) entries of ``app``'s
    bucket ladders — the same graphs ``warmup()`` would run, but built
    through FRESH jit wrappers so lowering never touches the app's
    compiled-callable cache. ``build()`` returns (jitted_fn, args, kwargs)
    ready for ``.lower()``."""
    cfg = app.tpu_config
    rng = jax.random.PRNGKey(0)
    entries: List[Tuple[str, str, Callable[[], Tuple]]] = []
    chunk = max(cfg.decode_chunk_tokens, 1)

    if getattr(cfg, "is_block_kv_layout", False):
        b = cfg.batch_size
        width_bt = app.max_blocks

        def paged_args(w: int, b=b):
            return (app.params, app.cache,
                    np.zeros((b, w), np.int32), np.zeros((b, w), np.int32),
                    np.full((b, w), -1, np.int32),
                    np.zeros((b, width_bt), np.int32),
                    np.zeros((b,), np.int32),
                    app._default_sampling_params(b), rng)

        for w in app.ctx_buckets:
            entries.append((
                "paged", f"w{w}xb{b}",
                lambda w=w: (app._jit_paged(), paged_args(w), {})))
        entries.append((
            "paged", f"w1xb{b}",
            lambda: (app._jit_paged(), paged_args(1), {})))
        if chunk > 1:
            entries.append((
                "paged_loop", f"k{chunk}xb{b}",
                lambda: (app._jit_paged_loop(chunk),
                         (app.params, app.cache, np.zeros((b,), np.int32),
                          np.zeros((b,), np.int32),
                          np.zeros((b, width_bt), np.int32),
                          app._default_sampling_params(b), rng), {})))
        # the speculative verify graph (serving/speculation/): the ragged
        # k+1-wide dispatch at the default self-draft ladder top (k=3)
        sw = 4
        entries.append((
            "spec_verify", f"W{sw}xb{b}",
            lambda: (app._jit_spec_verify(False),
                     (app.params, app.cache, np.zeros((b, sw), np.int32),
                      np.zeros((b, sw), np.int32),
                      np.full((b, sw), -1, np.int32),
                      np.zeros((b, width_bt), np.int32),
                      np.ones((b,), np.int32)), {})))
        # the ragged UNIFIED dispatch (serving/ragged/): one mixed
        # prefill+decode+verify graph at the same representative width
        def ragged_args():
            return (app.params, app.cache, np.zeros((b, sw), np.int32),
                    np.zeros((b, sw), np.int32),
                    np.full((b, sw), -1, np.int32),
                    np.zeros((b, width_bt), np.int32),
                    np.ones((b,), np.int32),
                    np.zeros((b,), np.int32),
                    app._default_sampling_params(b),
                    rng)

        entries.append((
            "ragged", f"W{sw}xb{b}",
            lambda: (app._jit_ragged(False), ragged_args(), {})))
        if app.spec.lora is not None:
            # the multi-LoRA variant: same ragged graph plus the per-row
            # adapter gather (serving/lora_pool.py) — reported separately
            # so the bytes/flops delta of the gathered (A,B) einsum is
            # visible in the graph report
            entries.append((
                "ragged_lora", f"W{sw}xb{b}",
                lambda: (app._jit_ragged(False), ragged_args(),
                         {"adapter_ids": np.zeros((b,), np.int32)})))
        return entries

    cb = cfg.ctx_batch_size

    def prefill_args(s: int, b: int):
        return (app.params, app.cache, np.zeros((b, s), np.int32),
                np.zeros((b, s), np.int32), np.arange(b, dtype=np.int32),
                np.ones((b,), np.int32), app._default_sampling_params(b),
                rng, None, app.replacements, None, None, None, None)

    for s in app.ctx_buckets:
        entries.append((
            "prefill", f"ctx{s}xb{cb}",
            lambda s=s: (app._jit_prefill(), prefill_args(s, cb), {})))
    for bb in app.batch_buckets:
        entries.append((
            "decode", f"b{bb}",
            lambda bb=bb: (app._jit_decode(None),
                           (app.params, app.cache,
                            np.zeros((bb, 1), np.int32),
                            np.zeros((bb, 1), np.int32),
                            np.arange(bb, dtype=np.int32),
                            app._default_sampling_params(bb), rng,
                            None, app.replacements, None), {})))
        if chunk > 1:
            entries.append((
                "decode_loop", f"b{bb}xk{chunk}",
                lambda bb=bb: (app._jit_decode_loop(chunk),
                               (app.params, app.cache,
                                np.zeros((bb,), np.int32),
                                np.zeros((bb,), np.int32),
                                np.arange(bb, dtype=np.int32),
                                app._default_sampling_params(bb), rng),
                               {"num_steps": chunk})))
    return entries


def _hlo_text(compiled) -> Optional[str]:
    try:
        return compiled.as_text()
    except Exception:
        return None


def analyze_app(app, registry=None, hbm_gbps: Optional[float] = None,
                peak_tflops: Optional[float] = None,
                ici_gbps: Optional[float] = None,
                dcn_gbps: Optional[float] = None) -> Dict[str, Any]:
    """AOT-compile every bucket-ladder graph of ``app`` and return the
    graph report (see module docstring). Gauges are recorded on
    ``registry`` (default: the process-global one) when it is enabled.

    On a multi-device mesh the partitioned HLO of each graph is censused
    for collectives (per-graph ``collectives`` + the third roofline leg);
    on a single-device mesh the census is a guard — any collective in an
    unsharded graph raises RuntimeError."""
    reg = registry if registry is not None else get_registry()
    if hbm_gbps is None:
        hbm_gbps = float(os.environ.get("NXDI_TPU_HBM_GBPS", "819"))
    if peak_tflops is None:
        peak_tflops = float(os.environ.get("NXDI_TPU_PEAK_TFLOPS", "197"))
    if ici_gbps is None:
        ici_gbps = float(os.environ.get("NXDI_TPU_ICI_GBPS", "200"))
    if dcn_gbps is None:
        dcn_gbps = float(os.environ.get("NXDI_TPU_DCN_GBPS", "25"))
    if app.params is None:
        raise ValueError("load_weights() or init_random_weights() first")
    if app.cache is None:
        raise ValueError("init_cache() first")
    mesh = app.mesh
    n_mesh_devices = int(np.prod(mesh.devices.shape))
    graphs: List[Dict[str, Any]] = []
    app_census: List[Dict[str, Any]] = []
    for kind, bucket, build in _graph_entries(app):
        fn, args, kwargs = build()
        t0 = time.perf_counter()
        with app._mesh_ctx():
            compiled = fn.lower(*args, **kwargs).compile()
        compile_s = time.perf_counter() - t0
        flops, bytes_acc = _cost(compiled)
        mem = _memory(compiled)
        peak = mem["peak_bytes"] if mem else 0
        hlo = _hlo_text(compiled)
        census = (census_collectives(hlo, mesh)
                  if hlo is not None else None)
        if census is not None and n_mesh_devices == 1 and census:
            # single-device collective pin: an accidental shard_map/psum
            # leaking into the unsharded path would silently tax every
            # step — make it loud instead
            raise RuntimeError(
                f"single-device graph ({kind}, {bucket}) contains "
                f"collectives: {aggregate_census(census)} — a "
                "shard_map/psum leaked into the unsharded path")
        coll_bytes = sum(e["bytes"] for e in census) if census else 0
        roofline = None
        if peak_tflops > 0 and hbm_gbps > 0:
            # a zero assumption means "unknown chip" — the static
            # flops/bytes/compile data is still valid without a roofline
            t_compute = flops / (peak_tflops * 1e12)
            t_memory = bytes_acc / (hbm_gbps * 1e9)
            t_comm = (comm_roofline_seconds(census, ici_gbps, dcn_gbps)
                      if census else 0.0)
            saved = (sum(_wire_bytes_saved(e) for e in census)
                     if census else 0.0)
            legs = {"compute": t_compute, "memory": t_memory,
                    "comm": t_comm}
            bound = max(legs, key=legs.get)
            roofline = {
                "est_step_ms": round(max(legs.values()) * 1e3, 6),
                "bound": bound,
                "t_compute_ms": round(t_compute * 1e3, 6),
                "t_memory_ms": round(t_memory * 1e3, 6),
                "t_comm_ms": round(t_comm * 1e3, 6),
                # wire bytes the quantized (sub-fp32) payloads avoid vs
                # an fp32 exchange of the same shapes — 0 on fp32 graphs
                "comm_bytes_saved": int(round(saved)),
            }
        graph: Dict[str, Any] = {
            "kind": kind,
            "bucket": bucket,
            "compile_seconds": round(compile_s, 4),
            "flops": flops,
            "bytes_accessed": bytes_acc,
            "memory": mem,
            "arithmetic_intensity": (round(flops / bytes_acc, 3)
                                     if bytes_acc else None),
            "collectives": (aggregate_census(census)
                            if census is not None else None),
            "collective_count": len(census) if census is not None else None,
            "collective_bytes": coll_bytes if census is not None else None,
            "roofline": roofline,
        }
        graphs.append(graph)
        if census:
            app_census.extend(census)
        if reg.enabled:
            tmetrics.compile_seconds_gauge(reg).set(compile_s, kind=kind,
                                                    bucket=bucket)
            tmetrics.graph_flops_gauge(reg).set(flops, kind=kind,
                                                bucket=bucket)
            tmetrics.graph_bytes_gauge(reg).set(bytes_acc, kind=kind,
                                                bucket=bucket)
            tmetrics.graph_peak_bytes_gauge(reg).set(peak, kind=kind,
                                                     bucket=bucket)
    if reg.enabled:
        # collective census gauges aggregate over the app's whole graph
        # set — kind here is the COLLECTIVE kind, comm the mesh-axis
        # group, dtype the wire payload element type
        coll_g = tmetrics.graph_collectives_gauge(reg)
        bytes_g = tmetrics.graph_collective_bytes_gauge(reg)
        for key, slot in aggregate_census(app_census).items():
            ckind, comm, dtype = key.split("@", 2)
            coll_g.set(slot["count"], kind=ckind, comm=comm, dtype=dtype)
            bytes_g.set(slot["bytes"], kind=ckind, comm=comm, dtype=dtype)
    return {
        "schema": GRAPH_REPORT_SCHEMA,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "mesh": {"devices": n_mesh_devices,
                 "axes": {a: int(s) for a, s in
                          zip(mesh.axis_names, mesh.devices.shape)
                          if int(s) > 1}},
        "assumptions": {"hbm_gbps": hbm_gbps, "peak_tflops": peak_tflops,
                        "ici_gbps": ici_gbps, "dcn_gbps": dcn_gbps},
        "graphs": graphs,
        "totals": {
            "graphs": len(graphs),
            "compile_seconds": round(sum(g["compile_seconds"]
                                         for g in graphs), 4),
            "flops": sum(g["flops"] for g in graphs),
            "bytes_accessed": sum(g["bytes_accessed"] for g in graphs),
            "collectives": len(app_census),
            "collective_bytes": sum(e["bytes"] for e in app_census),
            "comm_bytes_saved": int(round(sum(
                _wire_bytes_saved(e) for e in app_census))),
        },
    }
