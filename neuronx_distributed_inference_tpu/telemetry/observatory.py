"""Compiled-graph observatory — static truth about the serving graphs.

AOT-lowers and compiles every (kind, bucket, batch) graph an application's
bucket ladders imply (``jax.jit(...).lower(...).compile()`` — no execution,
no device state touched) and harvests XLA's own static analysis:

  * ``cost_analysis()``   — flops and bytes accessed per invocation;
  * ``memory_analysis()`` — argument/output/temp byte footprints (peak ≈
    arguments + outputs + temps);
  * compile wall time per graph (the cold-start cost item 5 of the
    ROADMAP tracks: ``compile_plus_first_gen_s`` grew 5.7s→14.3s).

All of it works on the CPU backend — this is the evidence base for
re-earning the frozen kernel-admission constants (``model_base.py``
heuristics) and for AOT warm-start work, WITHOUT waiting for TPU hardware
(cf. full-program XLA compilation analysis, PAPERS.md arxiv 1810.09868).

Per-graph results land in the metrics registry when one is live
(``nxdi_compile_seconds`` / ``nxdi_graph_flops`` / ``nxdi_graph_bytes`` /
``nxdi_graph_peak_bytes``, labels ``kind``+``bucket``) and in the returned
report dict (schema ``nxdi-graph-report-v1``), which also carries a static
roofline estimate per bucket: arithmetic intensity, the
compute-vs-memory-bound verdict, and the estimated step time under the
assumed peak flops / HBM bandwidth (``NXDI_TPU_PEAK_TFLOPS``, default 197
— v5e bf16; ``NXDI_TPU_HBM_GBPS``, default 819).

``bench.py --graph-report`` drives this on the tiny synthetic model and
commits the artifact (``artifacts/graph_report_r08.json``) so cold-start
and graph-size regressions show up in BENCH_* rounds with no hardware.

Compiling through fresh ``jax.jit`` wrappers keeps the application's own
jit cache keys untouched — running the observatory can never change what
the serving path executes (the XLA persistent compile cache still
deduplicates the work).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from . import metrics as tmetrics
from .registry import get_registry

__all__ = ["analyze_app", "GRAPH_REPORT_SCHEMA"]

GRAPH_REPORT_SCHEMA = "nxdi-graph-report-v1"


def _cost(compiled) -> Tuple[float, float]:
    """(flops, bytes accessed) from XLA cost analysis; zeros when the
    backend reports nothing. Handles both the dict and the legacy
    list-of-dicts return shape."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return 0.0, 0.0
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


def _memory(compiled) -> Optional[Dict[str, int]]:
    """Byte footprints from XLA memory analysis; None when the backend
    does not expose it. ``peak_bytes`` approximates live memory as
    arguments + outputs + temps (donated aliases excluded by XLA)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def g(attr: str) -> int:
        return int(getattr(ma, attr, 0) or 0)

    out = {
        "argument_bytes": g("argument_size_in_bytes"),
        "output_bytes": g("output_size_in_bytes"),
        "temp_bytes": g("temp_size_in_bytes"),
        "alias_bytes": g("alias_size_in_bytes"),
        "generated_code_bytes": g("generated_code_size_in_bytes"),
    }
    out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                         + out["temp_bytes"])
    return out


def _graph_entries(app) -> List[Tuple[str, str, Callable[[], Tuple]]]:
    """Enumerate the (kind, bucket_label, build) entries of ``app``'s
    bucket ladders — the same graphs ``warmup()`` would run, but built
    through FRESH jit wrappers so lowering never touches the app's
    compiled-callable cache. ``build()`` returns (jitted_fn, args, kwargs)
    ready for ``.lower()``."""
    cfg = app.tpu_config
    rng = jax.random.PRNGKey(0)
    entries: List[Tuple[str, str, Callable[[], Tuple]]] = []
    chunk = max(cfg.decode_chunk_tokens, 1)

    if getattr(cfg, "is_block_kv_layout", False):
        b = cfg.batch_size
        width_bt = app.max_blocks

        def paged_args(w: int, b=b):
            return (app.params, app.cache,
                    np.zeros((b, w), np.int32), np.zeros((b, w), np.int32),
                    np.full((b, w), -1, np.int32),
                    np.zeros((b, width_bt), np.int32),
                    np.zeros((b,), np.int32),
                    app._default_sampling_params(b), rng)

        for w in app.ctx_buckets:
            entries.append((
                "paged", f"w{w}xb{b}",
                lambda w=w: (app._jit_paged(), paged_args(w), {})))
        entries.append((
            "paged", f"w1xb{b}",
            lambda: (app._jit_paged(), paged_args(1), {})))
        if chunk > 1:
            entries.append((
                "paged_loop", f"k{chunk}xb{b}",
                lambda: (app._jit_paged_loop(chunk),
                         (app.params, app.cache, np.zeros((b,), np.int32),
                          np.zeros((b,), np.int32),
                          np.zeros((b, width_bt), np.int32),
                          app._default_sampling_params(b), rng), {})))
        return entries

    cb = cfg.ctx_batch_size

    def prefill_args(s: int, b: int):
        return (app.params, app.cache, np.zeros((b, s), np.int32),
                np.zeros((b, s), np.int32), np.arange(b, dtype=np.int32),
                np.ones((b,), np.int32), app._default_sampling_params(b),
                rng, None, app.replacements, None, None, None, None)

    for s in app.ctx_buckets:
        entries.append((
            "prefill", f"ctx{s}xb{cb}",
            lambda s=s: (app._jit_prefill(), prefill_args(s, cb), {})))
    for bb in app.batch_buckets:
        entries.append((
            "decode", f"b{bb}",
            lambda bb=bb: (app._jit_decode(None),
                           (app.params, app.cache,
                            np.zeros((bb, 1), np.int32),
                            np.zeros((bb, 1), np.int32),
                            np.arange(bb, dtype=np.int32),
                            app._default_sampling_params(bb), rng,
                            None, app.replacements, None), {})))
        if chunk > 1:
            entries.append((
                "decode_loop", f"b{bb}xk{chunk}",
                lambda bb=bb: (app._jit_decode_loop(chunk),
                               (app.params, app.cache,
                                np.zeros((bb,), np.int32),
                                np.zeros((bb,), np.int32),
                                np.arange(bb, dtype=np.int32),
                                app._default_sampling_params(bb), rng),
                               {"num_steps": chunk})))
    return entries


def analyze_app(app, registry=None, hbm_gbps: Optional[float] = None,
                peak_tflops: Optional[float] = None) -> Dict[str, Any]:
    """AOT-compile every bucket-ladder graph of ``app`` and return the
    graph report (see module docstring). Gauges are recorded on
    ``registry`` (default: the process-global one) when it is enabled."""
    reg = registry if registry is not None else get_registry()
    if hbm_gbps is None:
        hbm_gbps = float(os.environ.get("NXDI_TPU_HBM_GBPS", "819"))
    if peak_tflops is None:
        peak_tflops = float(os.environ.get("NXDI_TPU_PEAK_TFLOPS", "197"))
    if app.params is None:
        raise ValueError("load_weights() or init_random_weights() first")
    if app.cache is None:
        raise ValueError("init_cache() first")
    graphs: List[Dict[str, Any]] = []
    for kind, bucket, build in _graph_entries(app):
        fn, args, kwargs = build()
        t0 = time.perf_counter()
        with app._mesh_ctx():
            compiled = fn.lower(*args, **kwargs).compile()
        compile_s = time.perf_counter() - t0
        flops, bytes_acc = _cost(compiled)
        mem = _memory(compiled)
        peak = mem["peak_bytes"] if mem else 0
        roofline = None
        if peak_tflops > 0 and hbm_gbps > 0:
            # a zero assumption means "unknown chip" — the static
            # flops/bytes/compile data is still valid without a roofline
            t_compute = flops / (peak_tflops * 1e12)
            t_memory = bytes_acc / (hbm_gbps * 1e9)
            roofline = {
                "est_step_ms": round(max(t_compute, t_memory) * 1e3, 6),
                "bound": ("compute" if t_compute >= t_memory
                          else "memory"),
            }
        graph: Dict[str, Any] = {
            "kind": kind,
            "bucket": bucket,
            "compile_seconds": round(compile_s, 4),
            "flops": flops,
            "bytes_accessed": bytes_acc,
            "memory": mem,
            "arithmetic_intensity": (round(flops / bytes_acc, 3)
                                     if bytes_acc else None),
            "roofline": roofline,
        }
        graphs.append(graph)
        if reg.enabled:
            tmetrics.compile_seconds_gauge(reg).set(compile_s, kind=kind,
                                                    bucket=bucket)
            tmetrics.graph_flops_gauge(reg).set(flops, kind=kind,
                                                bucket=bucket)
            tmetrics.graph_bytes_gauge(reg).set(bytes_acc, kind=kind,
                                                bucket=bucket)
            tmetrics.graph_peak_bytes_gauge(reg).set(peak, kind=kind,
                                                     bucket=bucket)
    return {
        "schema": GRAPH_REPORT_SCHEMA,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "assumptions": {"hbm_gbps": hbm_gbps, "peak_tflops": peak_tflops},
        "graphs": graphs,
        "totals": {
            "graphs": len(graphs),
            "compile_seconds": round(sum(g["compile_seconds"]
                                         for g in graphs), 4),
            "flops": sum(g["flops"] for g in graphs),
            "bytes_accessed": sum(g["bytes_accessed"] for g in graphs),
        },
    }
