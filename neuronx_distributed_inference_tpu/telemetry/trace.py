"""Flight recorder — a bounded, lock-guarded ring of structured trace
events, the "what happened, in what order" layer on top of the metrics
registry ("how much / how often").

Every event carries a monotonic ``perf_counter()`` timestamp, a stable
``name``, a category lane (``engine`` — scheduler pass phases, ``adapter``
— dispatch/fetch boundaries, ``app`` — ``_run_*`` compile/execute,
``error`` — typed failures) and structured ``args`` (request / tenant /
seq_id labels). Two pure exporters:

  * :meth:`FlightRecorder.to_chrome` — Chrome trace-event JSON
    (``chrome://tracing`` / Perfetto loadable: ``traceEvents`` with
    ``ph="X"`` complete slices and ``ph="i"`` instants, one ``tid`` lane
    per category, timestamps in microseconds from the recorder epoch);
  * :meth:`FlightRecorder.to_jsonl` — one JSON object per line, for
    grep/jq post-mortems.

Event **names are a stable contract** exactly like the metric names in
``metrics.py`` — dashboards, the post-mortem tooling, and the golden test
(``tests/test_flight_recorder.py``) key on them; renames are breaking.
The canonical set lives in :data:`ENGINE_PASS_PHASES` /
:data:`ADAPTER_EVENTS` / :data:`APP_EVENTS`.

Disabled by default with the PR-1 zero-cost contract: the module-global
recorder is a shared no-op (:data:`NULL_RECORDER`); instrumented call
sites pay one attribute check (``rec.enabled``) and never touch device
state — recording can change neither jit cache keys nor token streams
(pinned bit-identical by ``tests/test_flight_recorder.py``). When the ring
wraps, dropped events are counted (:attr:`FlightRecorder.dropped` plus the
``nxdi_trace_events_dropped_total{ring="trace"}`` counter when a live
metrics registry is installed) so a post-mortem states its own truncation
instead of silently starting mid-story.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Dict, List, Optional

from .registry import get_registry

__all__ = [
    "ENGINE_PASS_PHASES", "ENGINE_EVENTS", "ADAPTER_EVENTS", "APP_EVENTS",
    "FLEET_EVENTS", "DEGRADE_EVENTS", "WARMUP_EVENTS", "EVENT_NAMES",
    "FlightRecorder", "NullFlightRecorder", "NULL_RECORDER",
    "get_recorder", "set_recorder", "enable_recorder", "disable_recorder",
]

#: Engine scheduling-pass phases, one complete slice per ``run_pass``
#: stage (serving/engine/scheduler.py). STABLE names.
ENGINE_PASS_PHASES = ("pass.expire", "pass.preempt", "pass.admit",
                      "pass.dispatch")

#: Other engine-lane events (serving/engine/scheduler.py). STABLE names.
#:   ``stream.deliver``         tokens routed to request streams
#:   ``admission.headroom``     the scheduler hit a capacity reject/stall;
#:                              carries the adapter's live admission-
#:                              headroom estimate (free_blocks,
#:                              headroom_tokens, free_slots)
ENGINE_EVENTS = ("stream.deliver", "admission.headroom")

#: Adapter boundary events (serving/adapter.py + serving/ragged/path.py).
#: STABLE names.
#:   ``dispatch.decode``        one decode dispatch (eager or pipelined)
#:   ``dispatch.decode_loop``   one fused step_many(k) dispatch
#:   ``dispatch.prefill_chunk`` one packed prefill-chunk dispatch
#:   ``dispatch.ragged``        THE unified mixed dispatch of a ragged
#:                              engine step (serving/ragged/; carries
#:                              per-row ``seq_ids`` and ``traces``)
#:   ``fetch.tokens``           a blocking device->host token fetch
#:   ``preempt``                one sequence evicted (any reason)
ADAPTER_EVENTS = ("dispatch.decode", "dispatch.decode_loop",
                  "dispatch.prefill_chunk", "dispatch.ragged",
                  "fetch.tokens", "preempt")

#: Application events (models/application.py). STABLE names.
#:   ``run.<kind>``   host window of one _run_* call (entry -> dispatch
#:                    return; asynchronous — excludes device wait)
#:   ``compile``      first-time (kind, bucket, shape) graph build
APP_EVENTS = ("run.prefill", "run.decode", "run.decode_loop", "run.paged",
              "run.paged_loop", "compile")

#: Fleet-layer events (serving/fleet/). STABLE names.
#:   ``fleet.route``    one request routed to a replica (request_id,
#:                      replica, warmth, affinity)
#:   ``fleet.drain``    a replica transitioned to draining/dead
#:                      (replica, state, reason)
#:   ``kv.spill``       one block payload spilled to the host-RAM tier
#:   ``kv.restore``     spilled block payloads restored to device at
#:                      admission (seq_id, blocks, tokens)
#:   ``handoff.send``   a prefill-role engine captured a handoff record
#:   ``handoff.recv``   a decode-role engine admitted a handoff record
#:   ``fleet.all_dead`` the LAST healthy replica left rotation — the
#:                      operator page (replica, reason, in_flight)
#:   ``fleet.scale_up`` the FleetAutoscaler added a replica (replica,
#:                      reason, n_compiles, queue, burn, free_slots)
#:   ``fleet.scale_down`` the FleetAutoscaler started retiring a replica
#:                      (replica, reason, migrated, queue, burn)
FLEET_EVENTS = ("fleet.route", "fleet.drain", "kv.spill", "kv.restore",
                "handoff.send", "handoff.recv", "fleet.all_dead",
                "fleet.scale_up", "fleet.scale_down")

#: Degradation-controller events (resilience/controller.py). STABLE
#: names; both carry ``tenant``, ``action`` and the deciding ``burn``.
#:   ``degrade.enter``  an action engaged (burn crossed the enter
#:                      threshold in BOTH windows)
#:   ``degrade.exit``   an action released (burn below the exit
#:                      threshold after the minimum hold)
DEGRADE_EVENTS = ("degrade.enter", "degrade.exit")

#: Request-trace lifecycle events (telemetry/request_trace.py +
#: serving/engine/scheduler.py + serving/fleet/router.py). STABLE names.
#: Every one carries ``trace`` — the request's stable trace id
#: (``meta["trace"]``), which also rides ``Preempted``/handoff records
#: across replicas so a continuation stitches onto the same trace.
#:   ``trace.begin``    frontend/router/engine ingress (request_id,
#:                      tenant, prompt_len, deadline_s)
#:   ``trace.admit``    the request left the queue into one transactional
#:                      admission (seq_id, wait_s)
#:   ``trace.requeue``  the request went back to a queue — preemption or
#:                      replica failover (reason, replica when fleet)
#:   ``trace.emit``     terminal emission (reason, n_tokens)
TRACE_EVENTS = ("trace.begin", "trace.admit", "trace.requeue",
                "trace.emit")

#: Cold-start / steady-state compile events (serving/warmup.py +
#: models/application.py). STABLE names.
#:   ``compile.unexpected``  a graph build AFTER precompile() declared
#:                           steady state — a tracked incident (kind,
#:                           bucket, sig, plus ``traces`` = the request
#:                           trace ids packed into the triggering
#:                           dispatch, so the incident lands on the
#:                           victims' trace lanes)
WARMUP_EVENTS = ("compile.unexpected",)

EVENT_NAMES = (ENGINE_PASS_PHASES + ENGINE_EVENTS + ADAPTER_EVENTS
               + APP_EVENTS + FLEET_EVENTS + TRACE_EVENTS
               + DEGRADE_EVENTS + WARMUP_EVENTS)

#: Category -> Chrome trace tid lane (deterministic ordering in the UI).
_CAT_TIDS = {"engine": 1, "adapter": 2, "app": 3, "error": 4, "fleet": 5,
             "request": 6}


class _TraceSpan:
    """Context manager handed out by :meth:`FlightRecorder.span`: records
    one complete event over the ``with`` body."""

    __slots__ = ("_rec", "_name", "_cat", "_args", "_t0")

    def __init__(self, rec: "FlightRecorder", name: str, cat: str,
                 args: Dict[str, Any]):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_TraceSpan":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._rec.complete(self._name, self._t0, cat=self._cat,
                           **self._args)


class FlightRecorder:
    """Bounded ring of structured events (see module docstring)."""

    enabled = True

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.epoch = time.perf_counter()   # chrome ts origin
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._ids = itertools.count()
        self.dropped = 0
        self._dropped_flushed = 0      # high-water mark already counted

    # -- recording ---------------------------------------------------------
    def _push(self, ev: Dict[str, Any]) -> str:
        with self._lock:
            eid = ev["id"] = f"e{next(self._ids)}"
            self._events.append(ev)
            excess = len(self._events) - self.capacity
            if excess > 0:
                del self._events[:excess]
                self.dropped += excess
        return eid

    def _flush_drops(self) -> None:
        """Report accumulated ring evictions to the metrics registry.
        Deferred off the per-event hot path (once the ring is full EVERY
        push evicts) onto the read/export surfaces, where the count is
        actually consumed.

        Accounting is delta-against-a-high-water-mark, serialized by its
        own lock: concurrent ``tail()``/``events()`` exports each flush
        exactly the drops no other flush has claimed yet (never the same
        delta twice), and a flush while the registry is disabled counts
        NOTHING as flushed — the drops are reported, not lost, once a
        live registry is back. Invariant (regression-pinned):
        ``nxdi_trace_events_dropped_total{ring="trace"}`` on one live
        registry equals ``self.dropped`` after any export."""
        reg = get_registry()
        if not reg.enabled:
            return                 # deferred, not discarded
        with self._flush_lock:
            with self._lock:
                n = self.dropped - self._dropped_flushed
                self._dropped_flushed += n
            if n:
                from . import metrics as tmetrics
                tmetrics.trace_events_dropped_counter(reg).inc(n,
                                                               ring="trace")

    def instant(self, name: str, cat: str = "engine", **args) -> str:
        """Record a point-in-time event; returns its event id."""
        return self._push({"name": name, "cat": cat, "ph": "i",
                           "ts": time.perf_counter(), "args": args})

    def complete(self, name: str, t0: float, cat: str = "engine",
                 t1: Optional[float] = None, **args) -> str:
        """Record a complete slice spanning ``[t0, t1]`` (``t1`` defaults
        to now); returns its event id."""
        if t1 is None:
            t1 = time.perf_counter()
        return self._push({"name": name, "cat": cat, "ph": "X",
                           "ts": t0, "dur": t1 - t0, "args": args})

    def span(self, name: str, cat: str = "engine", **args) -> _TraceSpan:
        """``with rec.span("pass.admit"): ...`` — one complete event over
        the body."""
        return _TraceSpan(self, name, cat, args)

    def error(self, err: BaseException, cat: str = "error", **args):
        """Record a typed failure as an ``error.<Type>`` instant event
        (message, seq_ids, phase/retry_safe when present) and attach the
        event id to the exception as ``err.trace_id`` so a post-mortem
        can jump from the raised error to its place in the timeline.
        Returns ``err`` for ``raise rec.error(...)`` chaining."""
        attrs: Dict[str, Any] = {
            "message": str(err),
            "seq_ids": [int(s) for s in getattr(err, "seq_ids", ()) or ()],
        }
        phase = getattr(err, "phase", None)
        if phase:
            attrs["phase"] = phase
        retry_safe = getattr(err, "retry_safe", None)
        if retry_safe is not None:
            attrs["retry_safe"] = bool(retry_safe)
        attrs.update(args)
        eid = self.instant(f"error.{type(err).__name__}", cat=cat, **attrs)
        try:
            err.trace_id = eid
        except Exception:                  # frozen/slotted carriers
            pass
        return err

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._dropped_flushed = 0

    # -- reading -----------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        self._flush_drops()
        with self._lock:
            return [dict(e) for e in self._events]

    def tail(self, n: int = 256) -> List[Dict[str, Any]]:
        """The newest ``n`` events (post-mortem dump payload)."""
        self._flush_drops()
        with self._lock:
            return [dict(e) for e in self._events[-n:]]

    def __len__(self) -> int:
        return len(self._events)

    # -- exporters (pure) --------------------------------------------------
    def to_chrome(self, events: Optional[List[Dict[str, Any]]] = None
                  ) -> Dict[str, Any]:
        """Chrome trace-event JSON (load in ``chrome://tracing`` or
        Perfetto). Timestamps are microseconds from the recorder epoch;
        each category gets its own named thread lane."""
        if events is None:
            events = self.events()
        out: List[Dict[str, Any]] = []
        cats = sorted({e["cat"] for e in events},
                      key=lambda c: _CAT_TIDS.get(c, 99))
        for cat in cats:
            out.append({"name": "thread_name", "ph": "M", "pid": 1,
                        "tid": _CAT_TIDS.get(cat, 99),
                        "args": {"name": f"nxdi.{cat}"}})
        for e in events:
            ce: Dict[str, Any] = {
                "name": e["name"], "cat": e["cat"], "ph": e["ph"],
                "ts": (e["ts"] - self.epoch) * 1e6,
                "pid": 1, "tid": _CAT_TIDS.get(e["cat"], 99),
                "args": {**e["args"], "id": e["id"]},
            }
            if e["ph"] == "X":
                ce["dur"] = e["dur"] * 1e6
            else:
                ce["s"] = "t"          # instant scope: thread
            out.append(ce)
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def to_jsonl(self, events: Optional[List[Dict[str, Any]]] = None) -> str:
        """One JSON object per line (grep/jq-friendly), timestamps kept in
        raw ``perf_counter()`` seconds."""
        if events is None:
            events = self.events()
        return "\n".join(json.dumps(e, sort_keys=True) for e in events)


class NullFlightRecorder:
    """Disabled recorder: every method is a no-op; the library default."""

    enabled = False
    capacity = 0
    epoch = 0.0
    dropped = 0

    _NULL_SPAN = None                  # set below (shared instance)

    def instant(self, name, cat="engine", **args):
        return ""

    def complete(self, name, t0, cat="engine", t1=None, **args):
        return ""

    def span(self, name, cat="engine", **args):
        return self._NULL_SPAN

    def error(self, err, cat="error", **args):
        return err

    def clear(self):
        pass

    def events(self):
        return []

    def tail(self, n=256):
        return []

    def __len__(self):
        return 0

    def to_chrome(self, events=None):
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"dropped_events": 0}}

    def to_jsonl(self, events=None):
        return ""


class _NullSpanCM:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NullFlightRecorder._NULL_SPAN = _NullSpanCM()

NULL_RECORDER = NullFlightRecorder()
_global_recorder: Any = NULL_RECORDER


def get_recorder():
    """The process-global flight recorder (a no-op unless
    :func:`enable_recorder`'d or :func:`set_recorder`'d)."""
    return _global_recorder


def set_recorder(rec) -> None:
    global _global_recorder
    _global_recorder = rec if rec is not None else NULL_RECORDER


def enable_recorder(capacity: int = 4096) -> FlightRecorder:
    """Swap a live recorder into the global slot (idempotent; an existing
    live recorder is kept regardless of ``capacity``)."""
    global _global_recorder
    if not isinstance(_global_recorder, FlightRecorder):
        _global_recorder = FlightRecorder(capacity)
    return _global_recorder


def disable_recorder() -> None:
    global _global_recorder
    _global_recorder = NULL_RECORDER
