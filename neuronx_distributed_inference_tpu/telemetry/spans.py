"""Per-request spans — a tiny host-side event log.

A :class:`Span` marks one request's lifetime through the serving stack:
created at admission, annotated with named events (``first_token``, one per
decode step boundary, ...), ended at release. Finished spans land in the
owning registry's bounded ring (``registry.snapshot()["spans"]``) so a
``--metrics-json`` dump carries per-request timelines alongside the
aggregate metrics. All timestamps come from ``time.perf_counter()`` —
monotonic, host-only; a span never touches device state.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

__all__ = ["Span", "NullSpan", "NULL_SPAN"]


class Span:
    """One request's event log. Not thread-safe per instance (a request is
    driven from one host thread)."""

    __slots__ = ("name", "labels", "t_start", "t_end", "events",
                 "_registry_ref")

    def __init__(self, name: str, labels: Optional[Dict[str, Any]] = None,
                 registry=None):
        self.name = name
        self.labels = {k: str(v) for k, v in (labels or {}).items()}
        self.t_start = time.perf_counter()
        self.t_end: Optional[float] = None
        self.events: List[Dict[str, Any]] = []
        self._registry_ref = registry

    def event(self, name: str, **attrs) -> "Span":
        """Record a named event at now (relative time kept in seconds)."""
        e: Dict[str, Any] = {"name": name,
                             "t": time.perf_counter() - self.t_start}
        if attrs:
            e.update(attrs)
        self.events.append(e)
        return self

    def elapsed(self) -> float:
        return time.perf_counter() - self.t_start

    def elapsed_since(self, event_name: str) -> Optional[float]:
        """Seconds since the FIRST event with this name; None if absent."""
        for e in self.events:
            if e["name"] == event_name:
                return self.elapsed() - e["t"]
        return None

    def end(self) -> float:
        """Close the span, push it into the registry ring, return its
        duration in seconds. Idempotent."""
        if self.t_end is None:
            self.t_end = time.perf_counter()
            if self._registry_ref is not None:
                self._registry_ref.record_span(self.to_dict())
        return self.t_end - self.t_start

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "labels": dict(self.labels),
            "duration_s": (None if self.t_end is None
                           else self.t_end - self.t_start),
            "events": [dict(e) for e in self.events],
        }

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class NullSpan:
    """Shared no-op span handed out by a disabled registry."""

    __slots__ = ()
    name = ""
    labels: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    t_start = 0.0
    t_end = None

    def event(self, name: str, **attrs) -> "NullSpan":
        return self

    def elapsed(self) -> float:
        return 0.0

    def elapsed_since(self, event_name: str) -> Optional[float]:
        return None

    def end(self) -> float:
        return 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {"name": "", "labels": {}, "duration_s": None, "events": []}

    def __enter__(self) -> "NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_SPAN = NullSpan()
