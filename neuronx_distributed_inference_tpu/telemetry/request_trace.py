"""Request-scoped tracing — one stable ``trace_id`` per request, end to
end across the whole fleet.

The flight recorder (``trace.py``) answers "what happened, in what
order" per PROCESS; this module adds the per-REQUEST thread through it:
a trace id minted at ingress (:func:`new_trace_id`) that rides the
request's opaque ``meta`` passthrough (``meta["trace"]``) everywhere the
request goes — queue wait, transactional admission, every ragged-step
row it occupies (the ``dispatch.ragged`` event's ``traces`` list),
preemption + requeue, replica failover, and the disaggregated prefill →
decode handoff. Because :class:`~...resilience.preemption.Preempted`
serializes ``meta`` verbatim in ``to_json()``, the trace context crosses
process boundaries for free: a decode-replica continuation stitches onto
the prefill replica's trace with the SAME id (pinned by
``tests/test_slo_observability.py``).

Event convention (stable, like every other recorder contract):

  * lifecycle events (``trace.begin`` / ``trace.admit`` /
    ``trace.requeue`` / ``trace.emit``, cat ``request``) carry
    ``trace=<id>``;
  * batched device events (``dispatch.ragged``) carry
    ``traces=[<id>...]`` — one entry per packed row;
  * error/preempt events carry ``trace=<id>`` when the victim's meta
    held one.

Pure helpers below filter a recorder's event list down to one request
(:func:`trace_events`) and export per-request Chrome lanes
(:func:`chrome_by_trace` — one ``tid`` lane per trace id, so Perfetto
shows each request as its own swimlane). Zero-cost-off: nothing here
runs unless the flight recorder is enabled; minting the id itself is one
``uuid4`` at submit time and changes no device work.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["TRACE_META_KEY", "new_trace_id", "trace_of",
           "trace_events", "trace_ids_in", "chrome_by_trace"]

#: The key the serving layers park the trace id under in the opaque
#: per-request ``meta`` passthrough (a stable contract: ``Preempted``
#: and handoff records serialize meta verbatim, so this key IS the wire
#: format of the cross-replica trace context).
TRACE_META_KEY = "trace"


def new_trace_id() -> str:
    """A fresh request trace id (16 hex chars — short enough for log
    lines, collision-safe for a serving process's lifetime)."""
    return uuid.uuid4().hex[:16]


def trace_of(meta: Any) -> Optional[str]:
    """The trace id carried by an opaque per-request ``meta`` payload,
    or None (non-mapping metas — e.g. the non-engine default None —
    never carry one)."""
    try:
        tid = meta.get(TRACE_META_KEY)
    except AttributeError:
        return None
    return None if tid is None else str(tid)


def _matches(ev: Dict[str, Any], trace_id: str) -> bool:
    args = ev.get("args") or {}
    if args.get("trace") == trace_id:
        return True
    traces = args.get("traces")
    return bool(traces) and trace_id in traces


def trace_events(events: Iterable[Dict[str, Any]],
                 trace_id: str) -> List[Dict[str, Any]]:
    """The subset of recorder events belonging to one request: lifecycle
    events tagged ``trace=<id>`` plus batched device events whose
    ``traces`` row list contains it (recorder order preserved)."""
    return [ev for ev in events if _matches(ev, trace_id)]


def trace_ids_in(events: Iterable[Dict[str, Any]]) -> List[str]:
    """Every distinct trace id present in ``events``, ordered by first
    appearance (the lane order :func:`chrome_by_trace` uses)."""
    seen: Dict[str, None] = {}
    for ev in events:
        args = ev.get("args") or {}
        tid = args.get("trace")
        if tid:
            seen.setdefault(str(tid), None)
        for t in args.get("traces") or ():
            if t:
                seen.setdefault(str(t), None)
    return list(seen)


def chrome_by_trace(recorder, trace_ids: Optional[Iterable[str]] = None
                    ) -> Dict[str, Any]:
    """Chrome trace-event JSON with one thread lane PER REQUEST: every
    event of each trace id lands on its own named ``tid``
    (``trace:<id>``), so Perfetto renders each request as a swimlane
    through queue wait, admission, dispatches and emission. Events
    belonging to several traces (a batched ragged dispatch) are repeated
    on every involved lane — that repetition is the point: each request's
    lane shows the dispatches it actually rode. ``trace_ids=None`` lanes
    every trace in the ring."""
    events = recorder.events()
    ids = list(trace_ids) if trace_ids is not None else trace_ids_in(events)
    out: List[Dict[str, Any]] = []
    epoch = getattr(recorder, "epoch", 0.0)
    for lane, tid in enumerate(ids, start=1):
        out.append({"name": "thread_name", "ph": "M", "pid": 1,
                    "tid": lane, "args": {"name": f"trace:{tid}"}})
    for ev in events:
        for lane, tid in enumerate(ids, start=1):
            if not _matches(ev, tid):
                continue
            ce: Dict[str, Any] = {
                "name": ev["name"], "cat": ev["cat"], "ph": ev["ph"],
                "ts": (ev["ts"] - epoch) * 1e6,
                "pid": 1, "tid": lane,
                "args": {**ev["args"], "id": ev["id"]},
            }
            if ev["ph"] == "X":
                ce["dur"] = ev["dur"] * 1e6
            else:
                ce["s"] = "t"
            out.append(ce)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"dropped_events": recorder.dropped,
                          "traces": ids}}
