"""Canonical metric names + label sets — a STABLE contract.

Dashboards and tests key on these strings; treat renames as breaking
changes (README "Observability" documents each one). Helpers here build the
instruments with their canonical help text/labels so every call site agrees
on the schema.
"""

from __future__ import annotations

from .registry import DEFAULT_LATENCY_BUCKETS

# -- serving adapters (serving.py) -----------------------------------------
# engine label: "cb" (ContinuousBatchingAdapter) | "paged" (PagedEngineAdapter)
REQUEST_TTFT_SECONDS = "nxdi_request_ttft_seconds"
DECODE_STEP_SECONDS = "nxdi_decode_step_seconds"      # TPOT per step() call
REQUEST_TPOT_SECONDS = "nxdi_request_tpot_seconds"    # per-request mean TPOT
LIVE_BATCH_SIZE = "nxdi_live_batch_size"
LIVE_ROWS_TOTAL = "nxdi_live_rows_total"              # phase=prefill|decode
PAD_ROWS_TOTAL = "nxdi_pad_rows_total"                # phase=prefill|decode
REQUESTS_TOTAL = "nxdi_requests_total"                # event=added|released

# -- chunked prefill (serving.py PagedEngineAdapter) -------------------------
PREFILL_CHUNKS_TOTAL = "nxdi_prefill_chunks_total"      # engine
PREFILL_PAD_WASTE = "nxdi_prefill_pad_waste"            # engine

# -- serving engine (serving/engine/) ----------------------------------------
QUEUE_DEPTH = "nxdi_queue_depth"                        # tenant
QUEUE_WAIT_SECONDS = "nxdi_queue_wait_seconds"          # tenant, outcome

# -- decode pipeline (serving.py) --------------------------------------------
DISPATCH_DEPTH = "nxdi_dispatch_depth"                  # engine
HOST_OVERLAP_SECONDS = "nxdi_host_overlap_seconds"      # engine
STEPS_PER_FETCH = "nxdi_steps_per_fetch"                # engine

# -- serving resilience (serving.py + resilience/) --------------------------
PREEMPTIONS_TOTAL = "nxdi_preemptions_total"            # engine, reason, tenant
ADMISSION_ROLLBACKS_TOTAL = "nxdi_admission_rollbacks_total"   # engine
DEADLINE_EXPIRED_TOTAL = "nxdi_deadline_expired_total"  # engine, tenant
STEP_FAILURES_TOTAL = "nxdi_step_failures_total"        # engine, phase, tenant

# -- flight recorder + span ring (telemetry/trace.py, registry.py) -----------
TRACE_EVENTS_DROPPED_TOTAL = "nxdi_trace_events_dropped_total"  # ring

# -- compiled-graph observatory (telemetry/observatory.py) -------------------
COMPILE_SECONDS = "nxdi_compile_seconds"                # kind, bucket
GRAPH_FLOPS = "nxdi_graph_flops"                        # kind, bucket
GRAPH_BYTES = "nxdi_graph_bytes"                        # kind, bucket
GRAPH_PEAK_BYTES = "nxdi_graph_peak_bytes"              # kind, bucket

# -- sharding observatory: SPMD collective census ----------------------------
# kind here = COLLECTIVE kind (all_reduce|all_gather|reduce_scatter|
# collective_permute|all_to_all); comm = mesh-axis subset ("tp", "dp",
# "ep+tp", …) the replica groups ride
GRAPH_COLLECTIVES_TOTAL = "nxdi_graph_collectives_total"    # kind, comm
GRAPH_COLLECTIVE_BYTES = "nxdi_graph_collective_bytes"      # kind, comm

# -- application hot paths (models/application.py) --------------------------
# kind: prefill|decode|decode_loop|paged ; part: host|device
RUN_SECONDS = "nxdi_run_seconds"
GENERATED_TOKENS_TOTAL = "nxdi_generated_tokens_total"      # engine=cb|paged
DEVICE_SAMPLED_ROWS_TOTAL = "nxdi_device_sampled_rows_total"  # kind

# -- jit / bucketing (models/application.py, modules/autobucketing.py) ------
JIT_COMPILES_TOTAL = "nxdi_jit_compiles_total"        # kind, bucket
JIT_CACHE_HITS_TOTAL = "nxdi_jit_cache_hits_total"    # kind
BUCKET_SELECTED_TOTAL = "nxdi_bucket_selected_total"  # kind, bucket

# -- cold-start / steady-state compile discipline (serving/warmup.py) --------
STEADY_STATE_RECOMPILES_TOTAL = \
    "nxdi_steady_state_recompiles_total"              # kind, bucket

# -- HBM ledger (serving/warmup.py memory_ledger) ----------------------------
# state: used|free|unwritten|spilled (spilled = host-RAM tier residency,
# reported in the same account so the device + spill total is one read)
HBM_MODEL_BYTES = "nxdi_hbm_model_bytes"
HBM_KV_BYTES = "nxdi_hbm_kv_bytes"                    # state
KV_FRAGMENTATION_RATIO = "nxdi_kv_fragmentation_ratio"

# -- paged KV cache (modules/block_kv_cache.py) ------------------------------
KV_BLOCKS_TOTAL = "nxdi_kv_blocks_total"
KV_BLOCKS_IN_USE = "nxdi_kv_blocks_in_use"
KV_BLOCK_ALLOC_FAILURES_TOTAL = "nxdi_kv_block_alloc_failures_total"
PREFIX_CACHE_HIT_TOKENS_TOTAL = "nxdi_prefix_cache_hit_tokens_total"

# -- speculative serving (serving/speculation/) ------------------------------
RAGGED_ROWS_TOTAL = "nxdi_ragged_rows_total"     # engine, kind
RAGGED_PAD_WASTE = "nxdi_ragged_pad_waste"       # engine

SPEC_DRAFTED_TOKENS_TOTAL = "nxdi_spec_drafted_tokens_total"     # engine
SPEC_ACCEPTED_TOKENS_TOTAL = "nxdi_spec_accepted_tokens_total"   # engine
SPEC_ACCEPT_RATE = "nxdi_spec_accept_rate"                       # engine
SPEC_VERIFY_WIDTH = "nxdi_spec_verify_width"                     # engine

# -- fleet layer (serving/fleet/) --------------------------------------------
FLEET_ROUTED_TOTAL = "nxdi_fleet_routed_total"       # replica, affinity
FLEET_REQUEUES_TOTAL = "nxdi_fleet_requeues_total"   # replica
HANDOFFS_TOTAL = "nxdi_handoff_total"                # role=send|recv|migrate_*
FLEET_REPLICAS = "nxdi_fleet_replicas"               # state

# -- host-RAM KV spill tier (serving/fleet/kv_tier.py) -----------------------
KV_SPILL_BLOCKS_TOTAL = "nxdi_kv_spill_blocks_total"
KV_SPILL_EVICTIONS_TOTAL = "nxdi_kv_spill_evictions_total"
KV_SPILL_BYTES = "nxdi_kv_spill_bytes"
KV_RESTORE_BLOCKS_TOTAL = "nxdi_kv_restore_blocks_total"
KV_RESTORE_TOKENS_TOTAL = "nxdi_kv_restore_tokens_total"

# -- multi-LoRA adapter pool (serving/lora_pool.py) --------------------------
LORA_RESIDENCY_HITS_TOTAL = "nxdi_lora_residency_hits_total"
LORA_SWAPS_TOTAL = "nxdi_lora_swaps_total"           # adapter
LORA_SWAP_BYTES = "nxdi_lora_swap_bytes"

# -- per-tenant SLO plane (telemetry/slo.py) ---------------------------------
# signal: ttft|tpot|queue_wait ; window: short|long (policy window lengths)
SLO_ATTAINMENT = "nxdi_slo_attainment"               # tenant, signal, window
SLO_BURN_RATE = "nxdi_slo_burn_rate"                 # tenant, signal, window

# -- degradation controller (resilience/controller.py) -----------------------
# action: shed_speculation|tighten_admission|drop_ragged|shed_adapters
DEGRADED = "nxdi_degraded"                           # tenant, action

# -- degradations -----------------------------------------------------------
MOE_TKG_LOCAL_QUANT_DEGRADED_TOTAL = \
    "nxdi_moe_tkg_local_quant_degraded_total"


def ttft_histogram(reg):
    # tenant label: "" outside the multi-tenant serving engine (additive —
    # single-tenant dashboards aggregate over it unchanged)
    return reg.histogram(
        REQUEST_TTFT_SECONDS,
        "Time from request admission to its first generated token (s)",
        labels=("engine", "tenant"), buckets=DEFAULT_LATENCY_BUCKETS)


def decode_step_histogram(reg):
    return reg.histogram(
        DECODE_STEP_SECONDS,
        "Wall time of one engine decode step() call (s)",
        labels=("engine",), buckets=DEFAULT_LATENCY_BUCKETS)


def tpot_histogram(reg):
    return reg.histogram(
        REQUEST_TPOT_SECONDS,
        "Per-request mean time-per-output-token after the first token (s)",
        labels=("engine", "tenant"), buckets=DEFAULT_LATENCY_BUCKETS)


def queue_depth_gauge(reg):
    return reg.gauge(
        QUEUE_DEPTH,
        "Requests waiting in the serving engine's admission queue",
        labels=("tenant",))


def queue_wait_histogram(reg):
    return reg.histogram(
        QUEUE_WAIT_SECONDS,
        "Time a request spent queued before admission "
        "(outcome=admitted|expired|cancelled)",
        labels=("tenant", "outcome"), buckets=DEFAULT_LATENCY_BUCKETS)


def live_batch_gauge(reg):
    return reg.gauge(LIVE_BATCH_SIZE,
                     "Live rows submitted in the most recent engine call",
                     labels=("engine",))


def live_rows_counter(reg):
    return reg.counter(LIVE_ROWS_TOTAL,
                       "Live (non-pad) rows submitted to the device",
                       labels=("engine", "phase"))


def pad_rows_counter(reg):
    return reg.counter(
        PAD_ROWS_TOTAL,
        "Pad rows submitted to the device (pad-waste = pad/(pad+live))",
        labels=("engine", "phase"))


def requests_counter(reg):
    return reg.counter(REQUESTS_TOTAL, "Engine request lifecycle events",
                       labels=("engine", "event"))


def prefill_chunks_counter(reg):
    return reg.counter(
        PREFILL_CHUNKS_TOTAL,
        "Prompt chunks driven through the packed paged prefill path "
        "(one per sequence per packed chunk dispatch)",
        labels=("engine",))


def prefill_pad_waste_histogram(reg):
    return reg.histogram(
        PREFILL_PAD_WASTE,
        "Padded-token waste fraction of one packed prefill dispatch "
        "((padded - real) / padded over the rows x width grid; monolithic "
        "admission of skewed prompts pushes this toward 1)",
        labels=("engine",),
        buckets=(0.05, 0.1, 0.2, 0.4, 0.6, 0.8, 0.95))


def dispatch_depth_gauge(reg):
    return reg.gauge(
        DISPATCH_DEPTH,
        "Device decode dispatches in flight whose tokens have not been "
        "fetched to the host yet (0 = eager; pipeline_depth bounds it)",
        labels=("engine",))


def host_overlap_histogram(reg):
    return reg.histogram(
        HOST_OVERLAP_SECONDS,
        "Host wall time between a pipelined decode dispatch and its "
        "deferred token fetch — bookkeeping overlapped with device "
        "compute (s)",
        labels=("engine",), buckets=DEFAULT_LATENCY_BUCKETS)


def steps_per_fetch_histogram(reg):
    return reg.histogram(
        STEPS_PER_FETCH,
        "Device decode steps retired per blocking host fetch (1 = eager "
        "step(), k = step_many(k); _count is the fetches, _sum the steps)",
        labels=("engine",), buckets=(1, 2, 4, 8, 16, 32, 64))


def preemptions_counter(reg):
    # tenant label: "" outside the multi-tenant serving engine (additive —
    # single-tenant dashboards aggregate over it unchanged)
    return reg.counter(
        PREEMPTIONS_TOTAL,
        "Sequences evicted (recompute preemption); "
        "reason=grow|admission|scheduler",
        labels=("engine", "reason", "tenant"))


def admission_rollbacks_counter(reg):
    return reg.counter(
        ADMISSION_ROLLBACKS_TOTAL,
        "add_requests calls that failed and were rolled back atomically",
        labels=("engine",))


def deadline_expired_counter(reg):
    return reg.counter(
        DEADLINE_EXPIRED_TOTAL,
        "Requests that blew their per-request wall-clock deadline "
        "(counted once per request; tenant=\"\" outside the engine)",
        labels=("engine", "tenant"))


def step_failures_counter(reg):
    return reg.counter(
        STEP_FAILURES_TOTAL,
        "Device steps that raised and were rolled back (StepFailure); "
        "phase=prefill|decode (tenant=\"\" outside the engine or when the "
        "failed call mixed tenants)",
        labels=("engine", "phase", "tenant"))


def trace_events_dropped_counter(reg):
    return reg.counter(
        TRACE_EVENTS_DROPPED_TOTAL,
        "Events evicted from a bounded observability ring "
        "(ring=spans|trace) — nonzero means post-mortems are truncated",
        labels=("ring",))


def compile_seconds_gauge(reg):
    return reg.gauge(
        COMPILE_SECONDS,
        "AOT lower+compile wall time of one (kind, bucket) serving graph "
        "(s); the stats_line total tracks cold-start cost",
        labels=("kind", "bucket"))


def graph_flops_gauge(reg):
    return reg.gauge(
        GRAPH_FLOPS,
        "XLA cost_analysis flops of one compiled (kind, bucket) graph",
        labels=("kind", "bucket"))


def graph_bytes_gauge(reg):
    return reg.gauge(
        GRAPH_BYTES,
        "XLA cost_analysis bytes accessed of one compiled (kind, bucket) "
        "graph",
        labels=("kind", "bucket"))


def graph_peak_bytes_gauge(reg):
    return reg.gauge(
        GRAPH_PEAK_BYTES,
        "XLA memory_analysis peak bytes (arguments + outputs + temps) of "
        "one compiled (kind, bucket) graph",
        labels=("kind", "bucket"))


def graph_collectives_gauge(reg):
    return reg.gauge(
        GRAPH_COLLECTIVES_TOTAL,
        "Collective ops censused across an app's partitioned (post-SPMD) "
        "graphs; kind=all_reduce|all_gather|reduce_scatter|"
        "collective_permute|all_to_all, comm=the mesh-axis subset the "
        "replica groups ride, dtype=the wire payload element type "
        "(f32|s8|f8e4m3fn|...) (static census — loop bodies count once)",
        labels=("kind", "comm", "dtype"))


def graph_collective_bytes_gauge(reg):
    return reg.gauge(
        GRAPH_COLLECTIVE_BYTES,
        "Result-tensor payload bytes of the censused collectives "
        "(summed over an app's graph set per kind x comm x dtype)",
        labels=("kind", "comm", "dtype"))


def run_seconds_histogram(reg):
    return reg.histogram(
        RUN_SECONDS,
        "Application _run_* wall time, split host-prep vs device wait (s)",
        labels=("kind", "part"), buckets=DEFAULT_LATENCY_BUCKETS)


def generated_tokens_counter(reg):
    return reg.counter(GENERATED_TOKENS_TOTAL,
                       "Tokens generated for live requests (engine-observed; "
                       "excludes pad rows)",
                       labels=("engine",))


def device_sampled_rows_counter(reg):
    return reg.counter(
        DEVICE_SAMPLED_ROWS_TOTAL,
        "Rows sampled per device forward (includes pad rows; the gap to "
        "nxdi_generated_tokens_total is engine pad waste)",
        labels=("kind",))


def jit_compiles_counter(reg):
    return reg.counter(
        JIT_COMPILES_TOTAL,
        "First-time (kind, bucket, shape) graph builds — each one is a "
        "trace+compile (or persistent-cache load) stall",
        labels=("kind", "bucket"))


def jit_cache_hits_counter(reg):
    return reg.counter(JIT_CACHE_HITS_TOTAL,
                       "Executions that reused an already-built graph",
                       labels=("kind",))


def bucket_selected_counter(reg):
    return reg.counter(BUCKET_SELECTED_TOTAL,
                       "Host-side pad-target bucket selections",
                       labels=("kind", "bucket"))


def steady_state_recompiles_counter(reg):
    return reg.counter(
        STEADY_STATE_RECOMPILES_TOTAL,
        "Graph builds observed AFTER precompile() declared steady state — "
        "every one is a tracked incident (compile.unexpected on the "
        "flight recorder, attributed to the triggering request traces)",
        labels=("kind", "bucket"))


def hbm_model_bytes_gauge(reg):
    return reg.gauge(
        HBM_MODEL_BYTES,
        "Bytes held by the replica's model parameters (exact pytree "
        "leaf-byte sum — the static side of the HBM ledger)")


def hbm_kv_bytes_gauge(reg):
    return reg.gauge(
        HBM_KV_BYTES,
        "KV pool bytes by ledger state (used|free|unwritten device "
        "blocks; spilled = host-RAM tier residency in the same account)",
        labels=("state",))


def kv_fragmentation_ratio_gauge(reg):
    return reg.gauge(
        KV_FRAGMENTATION_RATIO,
        "Wasted slot fraction inside allocated KV blocks: 1 - live "
        "tokens / (blocks_in_use * block_size); 0 with nothing allocated")


def kv_blocks_total_gauge(reg):
    return reg.gauge(KV_BLOCKS_TOTAL,
                     "Usable KV cache blocks (excludes the null block)")


def kv_blocks_in_use_gauge(reg):
    return reg.gauge(KV_BLOCKS_IN_USE,
                     "KV cache blocks currently referenced by sequences")


def kv_alloc_failures_counter(reg):
    return reg.counter(KV_BLOCK_ALLOC_FAILURES_TOTAL,
                       "Block allocations that failed (cache exhausted)")


def prefix_hit_tokens_counter(reg):
    return reg.counter(PREFIX_CACHE_HIT_TOKENS_TOTAL,
                       "Prompt tokens served from the prefix cache")


def ragged_rows_counter(reg):
    return reg.counter(
        RAGGED_ROWS_TOTAL,
        "Rows packed into ragged unified dispatches, by kind: decode "
        "steps, prefill chunks, speculative verify windows and batch-pad "
        "rows (serving/ragged/)",
        labels=("engine", "kind"))


def ragged_pad_waste_gauge(reg):
    return reg.gauge(
        RAGGED_PAD_WASTE,
        "Padded-token waste fraction of the last ragged unified dispatch "
        "((padded - real) / padded over the rows x unified-width grid)",
        labels=("engine",))


def spec_drafted_counter(reg):
    return reg.counter(
        SPEC_DRAFTED_TOKENS_TOTAL,
        "Draft tokens proposed per speculative verify dispatch "
        "(accepted + rejected; excludes the always-emitted bonus token), "
        "split by verify mode (greedy | sampled)",
        labels=("engine", "mode"))


def spec_accepted_counter(reg):
    return reg.counter(
        SPEC_ACCEPTED_TOKENS_TOTAL,
        "Draft tokens the verify dispatch accepted (the gap to "
        "nxdi_spec_drafted_tokens_total is wasted draft work), split by "
        "verify mode (greedy | sampled)",
        labels=("engine", "mode"))


def spec_accept_rate_gauge(reg):
    return reg.gauge(
        SPEC_ACCEPT_RATE,
        "Per-step draft acceptance rate (accepted/drafted of the last "
        "speculative engine step; 1.0 under greedy or coupled-sampled "
        "self-drafting), split by verify mode (greedy | sampled)",
        labels=("engine", "mode"))


def spec_verify_width_histogram(reg):
    return reg.histogram(
        SPEC_VERIFY_WIDTH,
        "Bucketed candidate width (drafts + 1) of each speculative verify "
        "dispatch — width 1 means the step degenerated to eager decode",
        labels=("engine",), buckets=(1, 2, 4, 8, 16, 32))


def fleet_routed_counter(reg):
    return reg.counter(
        FLEET_ROUTED_TOTAL,
        "Requests routed to a replica by the fleet EngineRouter "
        "(affinity=warm when prefix-affinity picked the replica, cold "
        "when it fell through to least queue depth)",
        labels=("replica", "affinity"))


def fleet_requeues_counter(reg):
    return reg.counter(
        FLEET_REQUEUES_TOTAL,
        "In-flight requests requeued onto another replica after their "
        "replica failed or closed (labeled with the FAILED replica)",
        labels=("replica",))


def handoffs_counter(reg):
    return reg.counter(
        HANDOFFS_TOTAL,
        "Disaggregated prefill/decode handoffs (role=send on capture, "
        "role=recv on decode-side admission) and live decode->decode "
        "migrations (role=migrate_send / migrate_recv)",
        labels=("role",))


def fleet_replicas_gauge(reg):
    return reg.gauge(
        FLEET_REPLICAS,
        "Replicas in the fleet router's rotation by health state "
        "(healthy/draining/backing_off/probation/dead) — refreshed by "
        "every FleetAutoscaler evaluation",
        labels=("state",))


def kv_spill_blocks_counter(reg):
    return reg.counter(
        KV_SPILL_BLOCKS_TOTAL,
        "KV block payloads spilled from device to the host-RAM tier "
        "(on prefix-cache LRU eviction)")


def kv_spill_evictions_counter(reg):
    return reg.counter(
        KV_SPILL_EVICTIONS_TOTAL,
        "Block payloads evicted from the bounded host-RAM spill tier "
        "(oldest-touched first) — nonzero means the tier is undersized "
        "for the working set")


def kv_spill_bytes_gauge(reg):
    return reg.gauge(
        KV_SPILL_BYTES,
        "Host RAM currently held by the KV spill tier's block payloads")


def kv_restore_blocks_counter(reg):
    return reg.counter(
        KV_RESTORE_BLOCKS_TOTAL,
        "Spilled KV blocks restored to device by H2D copy at admission "
        "(each one replaces a recompute of block_size prompt tokens)")


def kv_restore_tokens_counter(reg):
    return reg.counter(
        KV_RESTORE_TOKENS_TOTAL,
        "Prompt tokens whose prefill recompute was replaced by a "
        "spill-tier restore")


def lora_residency_hits_counter(reg):
    return reg.counter(
        LORA_RESIDENCY_HITS_TOTAL,
        "Adapter acquisitions served by an already device-resident slot "
        "(no swap H2D traffic) — hits / (hits + swaps) is the pool's "
        "residency hit-rate")


def lora_swaps_counter(reg):
    return reg.counter(
        LORA_SWAPS_TOTAL,
        "Adapter swaps written into a stacked device slot (H2D), by "
        "adapter name — each swap pays the (A,B) factor upload the "
        "residency pool exists to amortize",
        labels=("adapter",))


def lora_swap_bytes_counter(reg):
    return reg.counter(
        LORA_SWAP_BYTES,
        "Bytes of stacked (A,B) LoRA factors uploaded to device slots by "
        "adapter swaps (cumulative H2D swap traffic)")


def slo_attainment_gauge(reg):
    return reg.gauge(
        SLO_ATTAINMENT,
        "Fraction of a tenant's requests meeting the signal's SLO target "
        "inside the window (signal=ttft|tpot|queue_wait, "
        "window=short|long; pull-time export from the SLO tracker)",
        labels=("tenant", "signal", "window"))


def slo_burn_rate_gauge(reg):
    return reg.gauge(
        SLO_BURN_RATE,
        "Error-budget burn rate inside the window: violation fraction / "
        "(1 - objective) — 1.0 means spending budget exactly as fast as "
        "the objective allows",
        labels=("tenant", "signal", "window"))


def degraded_gauge(reg):
    return reg.gauge(
        DEGRADED,
        "1 while the degradation controller holds the action active for "
        "the tenant (hysteresis-guarded; set on degrade.enter, cleared "
        "on degrade.exit), 0 after exit "
        "(action=shed_speculation|tighten_admission|drop_ragged|"
        "shed_adapters)",
        labels=("tenant", "action"))


def moe_tkg_degraded_counter(reg):
    return reg.counter(
        MOE_TKG_LOCAL_QUANT_DEGRADED_TOTAL,
        "tkg_experts_local requested but quantized expert weights kept the "
        "prefill layout (decode resharding skipped)")
