"""Per-tenant SLO plane: rolling-window streaming percentiles over
TTFT / TPOT / queue-wait, burn-rate tracking, and a degradation hint.

The metrics registry's histograms answer "what is the all-time
distribution"; an operator paging on an SLO needs "what is the
distribution over the last minute / last ten minutes, per tenant, and
how fast is the error budget burning". This module computes exactly
that, host-side, with **bounded memory and no numpy on the hot path**
(``observe()`` is one deque append; sorting happens only at report
time):

  * :class:`RollingWindow` — a bounded ring of ``(timestamp, value)``
    samples; ``percentile(q, window_s)`` sorts a time-filtered snapshot.
    Memory is capped by ``max_samples`` (oldest evicted first), so a
    traffic burst degrades *resolution*, never footprint;
  * :class:`SLOPolicy` — per-signal latency targets plus the objective
    (the fraction of requests that must meet them, default 0.99) and the
    short/long burn windows;
  * :class:`SLOTracker` — per-(tenant, signal) windows,
    :meth:`~SLOTracker.report` (p50/p99, attainment, burn rate per
    window), :meth:`~SLOTracker.export` (the ``nxdi_slo_*`` gauges), and
    :meth:`~SLOTracker.degradation_hint`.

**Burn rate** (README "Observability contract"): over a window,
``burn = (fraction of requests violating the target) / (1 - objective)``
— the rate at which the error budget is being spent, normalized so 1.0
means "exactly on budget". A hint fires only when BOTH the short and the
long window burn past ``burn_threshold`` (the classic multiwindow rule:
the long window proves it is real, the short window proves it is still
happening). The hint is **advisory** in this PR: the router/scheduler
may consult it (shed speculation when decode latency burns, tighten
admission when queue wait burns) but nothing acts on it yet — it is
wired read-only into ``/v1/debug/state`` and ``bench.py --slo-report``.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from . import metrics as tmetrics

__all__ = ["SLO_SIGNALS", "RollingWindow", "SLOPolicy", "SLOTracker"]

#: The three per-tenant latency signals the SLO plane tracks. STABLE
#: (label values of the ``nxdi_slo_*`` gauges):
#:   ``ttft``       submit -> first token (client-observed, queue incl.)
#:   ``tpot``       per-request mean time-per-output-token after the first
#:   ``queue_wait`` submit -> admission
SLO_SIGNALS = ("ttft", "tpot", "queue_wait")


class RollingWindow:
    """Bounded ring of timestamped samples with on-demand percentiles.

    ``observe()`` is O(1) (one deque append + bounded evictions); the
    percentile/attainment reads sort a snapshot filtered to the queried
    window — report-time cost, never serving-time cost. One ring serves
    every window length up to ``horizon_s`` (samples older than that are
    evicted on write)."""

    def __init__(self, horizon_s: float = 600.0, max_samples: int = 2048):
        if horizon_s <= 0 or max_samples < 1:
            raise ValueError("horizon_s must be > 0, max_samples >= 1")
        self.horizon_s = float(horizon_s)
        self.max_samples = int(max_samples)
        self._samples: Deque[Tuple[float, float]] = deque(maxlen=max_samples)

    def observe(self, value: float, now: Optional[float] = None) -> None:
        if now is None:
            now = time.perf_counter()
        self._samples.append((now, float(value)))
        cutoff = now - self.horizon_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def __len__(self) -> int:
        return len(self._samples)

    def values(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[float]:
        if now is None:
            now = time.perf_counter()
        cutoff = now - (self.horizon_s if window_s is None else window_s)
        return [v for t, v in self._samples if t >= cutoff]

    def percentile(self, q: float, window_s: Optional[float] = None,
                   now: Optional[float] = None) -> float:
        """The q-th percentile (0 <= q <= 1) of the samples inside the
        window, by nearest-rank on a sorted snapshot; 0.0 when empty."""
        vals = sorted(self.values(window_s, now))
        if not vals:
            return 0.0
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]

    def violation_fraction(self, target: float,
                           window_s: Optional[float] = None,
                           now: Optional[float] = None) -> float:
        """Fraction of in-window samples strictly above ``target``
        (0.0 when the window is empty — no traffic burns no budget)."""
        vals = self.values(window_s, now)
        if not vals:
            return 0.0
        return sum(1 for v in vals if v > target) / len(vals)


@dataclass(frozen=True)
class SLOPolicy:
    """Targets + burn semantics for one serving surface.

    ``targets`` maps signal name -> latency target in SECONDS (a signal
    without a target is tracked for percentiles but never burns).
    ``objective`` is the attainment the budget is written against
    (0.99 = "99% of requests meet the target"); ``burn_threshold`` is
    the normalized burn rate BOTH windows must exceed before
    :meth:`SLOTracker.degradation_hint` speaks up."""

    targets: Dict[str, float] = field(default_factory=dict)
    objective: float = 0.99
    short_window_s: float = 60.0
    long_window_s: float = 600.0
    burn_threshold: float = 2.0

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if self.short_window_s <= 0 or self.long_window_s <= 0:
            raise ValueError("windows must be > 0")
        if self.short_window_s > self.long_window_s:
            raise ValueError("short window must not exceed the long one")
        for sig in self.targets:
            if sig not in SLO_SIGNALS:
                raise ValueError(f"unknown SLO signal {sig!r}; expected "
                                 f"one of {SLO_SIGNALS}")

    @property
    def budget(self) -> float:
        """The error-budget fraction (1 - objective)."""
        return 1.0 - self.objective


class SLOTracker:
    """Per-(tenant, signal) rolling windows + the report/hint surface.

    One tracker per serving engine (``ServingEngine(slo=...)``); the
    engine feeds it host-side timestamps only, so attaching it cannot
    change device work, graphs, or token streams (zero-cost contract,
    pinned). All read surfaces are pure."""

    def __init__(self, policy: Optional[SLOPolicy] = None,
                 max_samples: int = 2048):
        self.policy = policy if policy is not None else SLOPolicy()
        self.max_samples = max_samples
        self._windows: Dict[Tuple[str, str], RollingWindow] = {}

    # -- write side (engine) ----------------------------------------------
    def observe(self, tenant: str, signal: str, value: float,
                now: Optional[float] = None) -> None:
        if signal not in SLO_SIGNALS:
            raise ValueError(f"unknown SLO signal {signal!r}; expected "
                             f"one of {SLO_SIGNALS}")
        key = (str(tenant), signal)
        win = self._windows.get(key)
        if win is None:
            win = self._windows[key] = RollingWindow(
                horizon_s=self.policy.long_window_s,
                max_samples=self.max_samples)
        win.observe(value, now)

    # -- read side (pure) --------------------------------------------------
    @property
    def tenants(self) -> List[str]:
        return sorted({t for t, _ in self._windows})

    def _signal_report(self, win: RollingWindow, signal: str,
                       now: float) -> Dict[str, Any]:
        pol = self.policy
        out: Dict[str, Any] = {
            "n": len(win),
            "p50_s": win.percentile(0.50, now=now),
            "p99_s": win.percentile(0.99, now=now),
        }
        target = pol.targets.get(signal)
        if target is not None:
            burns = {}
            attain = {}
            for label, w in (("short", pol.short_window_s),
                             ("long", pol.long_window_s)):
                viol = win.violation_fraction(target, w, now)
                attain[label] = 1.0 - viol
                burns[label] = viol / pol.budget
            out.update(target_s=target, attainment=attain,
                       burn_rate=burns)
        return out

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-able per-tenant SLO report: per-signal sample count,
        p50/p99 over the long window and — for targeted signals —
        short/long attainment + burn rate. Served read-only as the
        ``slo`` section of ``/v1/debug/state`` and by
        ``bench.py --slo-report``."""
        if now is None:
            now = time.perf_counter()
        pol = self.policy
        tenants: Dict[str, Any] = {}
        for (tenant, signal), win in sorted(self._windows.items()):
            tenants.setdefault(tenant, {})[signal] = \
                self._signal_report(win, signal, now)
        return {
            "schema": "nxdi-slo-report-v1",
            "policy": {
                "targets": dict(pol.targets),
                "objective": pol.objective,
                "short_window_s": pol.short_window_s,
                "long_window_s": pol.long_window_s,
                "burn_threshold": pol.burn_threshold,
            },
            "tenants": tenants,
            "hint": self.degradation_hint(now=now),
        }

    def burn_index(self, now: Optional[float] = None
                   ) -> Dict[Tuple[str, str], float]:
        """``(tenant, signal) -> min(short burn, long burn)`` for every
        TARGETED signal with samples — the multiwindow burn number both
        :meth:`degradation_hint` (against ``policy.burn_threshold``) and
        the :class:`~...resilience.controller.DegradationController`
        (against its own hysteresis thresholds) decide on. Taking the
        MIN of the two windows encodes the classic multiwindow rule:
        both must burn before anyone acts."""
        if now is None:
            now = time.perf_counter()
        pol = self.policy
        out: Dict[Tuple[str, str], float] = {}
        for (tenant, signal), win in sorted(self._windows.items()):
            target = pol.targets.get(signal)
            if target is None:
                continue
            out[(tenant, signal)] = min(
                win.violation_fraction(target, w, now) / pol.budget
                for w in (pol.short_window_s, pol.long_window_s))
        return out

    def degradation_hint(self, now: Optional[float] = None
                         ) -> Dict[str, Any]:
        """Advisory multiwindow burn alerts, per tenant:

          * ``shed_speculation`` — a DECODE-side signal (ttft/tpot) is
            burning in both windows: speculative decode's draft overhead
            is the first latency lever to drop;
          * ``tighten_admission`` — queue wait is burning in both
            windows: the engine is admitting more than it can serve
            inside the target.

        The hint is the threshold-crossed view of :meth:`burn_index`;
        ``ServingEngine(degradation=...)`` attaches the closed-loop
        actuator (resilience/controller.py) that actually acts on the
        same burn numbers with hysteresis — without it the hint stays
        advisory (``/v1/debug/state``)."""
        if now is None:
            now = time.perf_counter()
        pol = self.policy
        tenants: Dict[str, Any] = {}
        for (tenant, signal), burn in self.burn_index(now).items():
            if burn < pol.burn_threshold:
                continue
            entry = tenants.setdefault(
                tenant, {"shed_speculation": False,
                         "tighten_admission": False, "signals": {}})
            entry["signals"][signal] = round(burn, 3)
            if signal in ("ttft", "tpot"):
                entry["shed_speculation"] = True
            else:
                entry["tighten_admission"] = True
        return {"degrade": bool(tenants), "tenants": tenants}

    def export(self, reg, now: Optional[float] = None) -> None:
        """Set the ``nxdi_slo_attainment`` / ``nxdi_slo_burn_rate``
        gauges from the current windows (pull-time export — called by
        the ``/v1/metrics`` scrape path and the bench, never per
        request)."""
        if not getattr(reg, "enabled", False):
            return
        if now is None:
            now = time.perf_counter()
        pol = self.policy
        attain = tmetrics.slo_attainment_gauge(reg)
        burn = tmetrics.slo_burn_rate_gauge(reg)
        for (tenant, signal), win in self._windows.items():
            target = pol.targets.get(signal)
            if target is None:
                continue
            for label, w in (("short", pol.short_window_s),
                             ("long", pol.long_window_s)):
                viol = win.violation_fraction(target, w, now)
                attain.set(1.0 - viol, tenant=tenant, signal=signal,
                           window=label)
                burn.set(viol / pol.budget, tenant=tenant, signal=signal,
                         window=label)
