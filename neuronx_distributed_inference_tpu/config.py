"""Configuration system for the TPU-native inference framework.

Mirrors the knob surface of the reference config system
(reference: src/neuronx_distributed_inference/models/config.py:84-1042 —
``NeuronConfig`` / ``InferenceConfig`` / sub-configs) but is designed TPU-first:
parallelism degrees map onto named mesh axes (tp/cp/dp/ep) of a
``jax.sharding.Mesh`` rather than process-group construction, and dtypes are
JAX dtypes.

Sub-config parity (reference: models/config.py):
  - OnDeviceSamplingConfig      (:1064)
  - ChunkedPrefillConfig        (:1078)
  - MoEConfig / MoENeuronConfig (:798-846)
  - FusedSpecConfig             (:1045)
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax.numpy as jnp

logger = logging.getLogger("nxdi_tpu")

_DTYPE_MAP = {
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "float16": jnp.float16,
    "fp16": jnp.float16,
    "int8": jnp.int8,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}


def to_jax_dtype(dtype: Any):
    """Resolve a string / jnp dtype spec to a jnp dtype."""
    if isinstance(dtype, str):
        if dtype not in _DTYPE_MAP:
            raise ValueError(f"unknown dtype {dtype!r}; expected one of {sorted(_DTYPE_MAP)}")
        return _DTYPE_MAP[dtype]
    return dtype


def dtype_name(dtype: Any) -> str:
    for name, dt in _DTYPE_MAP.items():
        if dt == dtype and name in ("bfloat16", "float32", "float16", "int8",
                                    "float8_e4m3fn", "float8_e5m2"):
            return name
    return str(dtype)


@dataclass
class OnDeviceSamplingConfig:
    """On-device sampling knobs (reference: models/config.py:1064-1076)."""

    do_sample: bool = False
    top_k: int = 1
    top_p: float = 1.0
    temperature: float = 1.0
    dynamic: bool = True          # per-request sampling params tensor
    deterministic: bool = False
    global_topk: int = 256        # stage-1 topk width for hierarchical top-k
    on_device: bool = True
    # Positionally coupled streams (ops/sampling.coupled_sample): every
    # draw keyed by (stream_seed, request seed, absolute position), so
    # sampled streams are reproducible and path-invariant — the knob
    # that unlocks sampled speculation / ragged serving (README
    # "Sampled speculation & compressed decode"). None = per-dispatch
    # rng (legacy; refused under speculation).
    stream_seed: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class ChunkedPrefillConfig:
    """Chunked prefill / prefix caching (reference: models/config.py:1078-1094)."""

    max_num_seqs: int = 8
    kernel_q_tile_size: int = 128
    kernel_kv_tile_size: int = 1024

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class MoEConfig:
    """MoE knobs (reference: models/config.py:798-846 ``MoENeuronConfig``)."""

    capacity_factor: Optional[float] = None   # None => full capacity (dropless)
    glu_mlp: bool = True
    glu_type: str = "glu"
    normalize_top_k_affinities: bool = True
    early_expert_affinity_modulation: bool = False
    fused_shared_experts: bool = False
    routed_scaling_factor: Optional[float] = None
    moe_tp_degree: Optional[int] = None       # defaults to tp_degree
    moe_ep_degree: Optional[int] = None       # defaults to ep_degree
    # hybrid CTE/TKG expert sharding (reference: moe_v2.py:135-161
    # HybridShardingConfig): moe_tkg_ep_degree=1 switches DECODE to
    # all-experts-local with the intermediate dim split over every model
    # axis; prefill keeps the ep-sharded layout. Other degree combinations
    # are not supported (the GSPMD mesh fixes the axis extents).
    moe_cte_tp_degree: Optional[int] = None
    moe_cte_ep_degree: Optional[int] = None
    moe_tkg_tp_degree: Optional[int] = None
    moe_tkg_ep_degree: Optional[int] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class LoraServingConfig:
    """Multi-LoRA serving knobs (reference: modules/lora_serving/lora_serving_config.py)."""

    max_loras: int = 1
    max_lora_rank: int = 16
    target_modules: Optional[List[str]] = None
    lora_ckpt_paths: Optional[Dict[str, str]] = None
    lora_dtype: str = "bfloat16"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class SpeculationConfig:
    """Speculative decoding knobs (reference: models/config.py:243-274 block).

    Covers vanilla draft/target, EAGLE and Medusa variants; the fused-spec
    draft model class is referenced by import path so the config JSON
    round-trips (reference: models/config.py:956-1038).
    """

    speculation_length: int = 0
    spec_batch_size: Optional[int] = None
    enable_fused_speculation: bool = False
    enable_eagle_speculation: bool = False
    enable_eagle_draft_input_norm: bool = False
    is_eagle_draft: bool = False
    medusa_speculation_length: int = 0
    num_medusa_heads: int = 0
    token_tree_config: Optional[Dict[str, Any]] = None
    draft_model_path: Optional[str] = None
    draft_model_module: Optional[str] = None  # "module:Class" for round-trip

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class TensorCaptureConfig:
    """Intermediate-tensor capture appended to graph outputs
    (reference: models/config.py:1121-1169 + utils/tensor_capture_utils.py).

    capture_targets: per-layer points — "layer_output", "attn_output",
    "mlp_output" (stacked (L, B, T, H) in the step output under
    ``captured``)."""

    capture_targets: List[str] = field(
        default_factory=lambda: ["layer_output"])

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class TensorReplacementConfig:
    """Feed golden tensors into chosen layer points for fault localization
    (reference: models/config.py:1172-1202 + utils/tensor_replacement/).

    targets: point names (same vocabulary as capture); source_path: .npz
    with one array per target, shaped (L, B, T, H); layers: which layer
    indices to replace (None = all layers present in the arrays)."""

    targets: List[str] = field(default_factory=list)
    source_path: Optional[str] = None
    layers: Optional[List[int]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass
class CollectiveConfig:
    """Quantized decode-collective knobs (EQuARX-style wire compression,
    PAPERS.md arxiv 2506.17615).

    dtype: "int8" | "fp8" | None. None (default) keeps the implicit GSPMD
    fp32 collectives — graphs are bit-unchanged. int8/fp8 swaps the
    row-parallel decode all-reduce for a shard_map ring exchange whose wire
    payload is quantized (parallel/collectives.py); accumulation stays full
    precision.
    block: absmax-scale block size along each ring chunk — the activation
    analog of the weight stack's blockwise_symmetric group_size.
    """

    dtype: Optional[str] = None
    block: int = 32

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


_SUBCONFIG_TYPES = {
    "on_device_sampling_config": OnDeviceSamplingConfig,
    "chunked_prefill_config": ChunkedPrefillConfig,
    "moe_config": MoEConfig,
    "lora_config": LoraServingConfig,
    "speculation_config": SpeculationConfig,
    "tensor_capture_config": TensorCaptureConfig,
    "tensor_replacement_config": TensorReplacementConfig,
    "collective_config": CollectiveConfig,
}


@dataclass
class TpuConfig:
    """TPU-native equivalent of the reference ``NeuronConfig``
    (reference: models/config.py:84-786). Same knob names where sensible.

    Parallelism degrees are mesh-axis sizes:
      tp_degree -> "tp" axis, cp_degree -> "cp", attention_dp_degree -> "dp",
      ep_degree -> "ep" (reference: models/config.py:361-375).
    """

    # --- batch / sequence geometry (reference: models/config.py:120-164) ---
    batch_size: int = 1
    ctx_batch_size: Optional[int] = None      # prefill batch
    tkg_batch_size: Optional[int] = None      # decode batch
    max_batch_size: Optional[int] = None
    is_continuous_batching: bool = False
    seq_len: int = 128                        # max total sequence length
    max_context_length: Optional[int] = None  # max prefill length
    # windowed context encoding (reference: models/model_base.py:878-933 +
    # the >=32k long-context mode, models/config.py:612-621): prompts are
    # prefilled in fixed windows re-invoking one graph with growing KV —
    # the (S, S) prefill attention materialization becomes (W, S), which
    # is what makes >=32k contexts feasible. None = one-shot prefill.
    windowed_context_encoding: Optional[int] = None
    n_active_tokens: int = 1
    n_positions: Optional[int] = None

    # --- dtypes ---
    dtype: str = "bfloat16"                   # weights/activations
    kv_cache_dtype: Optional[str] = None      # default = dtype; fp8 supported
    logits_dtype: str = "float32"
    rope_dtype: str = "float32"

    # --- parallelism degrees (reference: models/config.py:361-390) ---
    tp_degree: int = 1
    cp_degree: int = 1                        # context parallel (prefill)
    attention_dp_degree: int = 1              # data parallel decode attention
    pp_degree: int = 1
    ep_degree: int = 1
    mlp_cp_degree: int = 1
    sequence_parallel_enabled: bool = False
    # vocab-parallel embedding table (sharded on V); False replicates the
    # table on every device (reference: models/config.py:142)
    vocab_parallel: bool = True
    world_size: Optional[int] = None
    start_rank_id: int = 0
    local_ranks_size: Optional[int] = None

    # --- KV cache (reference: models/config.py:167-170, 277-317) ---
    kv_cache_batch_size: Optional[int] = None
    kv_cache_padding_size: int = 0
    is_block_kv_layout: bool = False
    # rolling sliding-window KV cache (reference: kv_cache_manager.py:605-606
    # pos %% (w-1) rolling write): cache holds only ``sliding_window`` slots.
    # None = auto (on for uniform-window models without speculation/paged)
    rolling_kv_cache: Optional[bool] = None
    pa_num_blocks: Optional[int] = None
    pa_block_size: int = 32
    is_prefix_caching: bool = False
    is_chunked_prefill: bool = False
    flash_decoding_enabled: bool = False

    # --- bucketing (reference: models/config.py:186-213) ---
    enable_bucketing: bool = True
    buckets: Optional[List[int]] = None           # explicit decode buckets
    context_encoding_buckets: Optional[List[int]] = None
    token_generation_buckets: Optional[List[int]] = None
    bucket_n_active_tokens: bool = False
    # 2-D bucketing (reference: autobucketing.py:22-64,203 — batch x seq
    # TKG buckets + prefix x prefill buckets; selection
    # model_wrapper.py:923-1045): short batches pad to the smallest BATCH
    # bucket instead of the full compiled batch, and the paged app sizes
    # its block-table width from a ladder instead of max_blocks.
    # Tradeoff: a sub-cache-batch decode graph takes the row-gather paths
    # instead of the identity fast path / fused decode kernel — worth it
    # when pad rows dominate (large batch, small requests), not for
    # window/sink models that lean on the kernel; hence default OFF
    enable_2d_bucketing: bool = False
    tkg_batch_buckets: Optional[List[int]] = None   # explicit batch ladder

    # --- sampling ---
    on_device_sampling_config: Optional[OnDeviceSamplingConfig] = None
    output_logits: bool = False               # return logits (accuracy/debug)
    # prefill returns the full (B,S,H) hidden states — needed once per
    # request to prime the EAGLE draft cache (reference: EAGLE CTE,
    # model_base.py:1931-2092)
    output_full_hidden: bool = False

    # --- speculation ---
    speculation_config: Optional[SpeculationConfig] = None

    # --- MoE ---
    moe_config: Optional[MoEConfig] = None

    # --- LoRA ---
    lora_config: Optional[LoraServingConfig] = None

    # --- chunked prefill ---
    chunked_prefill_config: Optional[ChunkedPrefillConfig] = None

    # --- observability (reference: models/config.py:320-353) ---
    tensor_capture_config: Optional[TensorCaptureConfig] = None
    tensor_replacement_config: Optional[TensorReplacementConfig] = None

    # --- quantization (reference: models/config.py:216-241) ---
    quantized: bool = False
    quantization_dtype: str = "int8"
    quantization_type: str = "per_channel_symmetric"
    quantized_checkpoints_path: Optional[str] = None
    modules_to_not_convert: Optional[List[str]] = None
    kv_cache_quant: bool = False
    # scaled-mode KV quantization: store x/scale (reference:
    # kv_cache_manager.py:661-692); 1.0 = direct cast
    kv_cache_scale: float = 1.0

    # --- quantized decode collectives (parallel/collectives.py) ---
    collective_config: Optional[CollectiveConfig] = None

    # --- low-rank (SVD-compressed) decode MLP (modules/low_rank.py,
    # NeuronMLP arxiv 2510.25977): factorize gate/up/down into rank-r
    # (U, V) pairs host-side; None = dense ---
    mlp_low_rank: Optional[int] = None

    # --- kernels (reference: models/config.py:417-567 — ~25 enable flags) ---
    # None/False = XLA attention path (measured faster than the v1 Pallas
    # kernel on v5e); True = opt into the Pallas flash prefill kernel where
    # ops/flash_attention.supports() holds (tp=1, arange positions)
    attn_kernel_enabled: Optional[bool] = None
    qkv_kernel_enabled: bool = False
    mlp_kernel_enabled: bool = False
    attn_block_tkg_nki_kernel_enabled: bool = False
    # Pallas fused decode attention (reference: attn_block_tkg NKI kernel
    # family, models/config.py:417-567); None = auto (on where supported)
    attn_block_tkg_kernel_enabled: Optional[bool] = None

    # --- async / host loop (reference: models/config.py:183) ---
    async_mode: bool = False
    decode_chunk_tokens: int = 1              # tokens per device call in decode

    # --- misc / runtime ---
    rpl_reduce_dtype: Optional[str] = None
    cast_type: str = "config"                 # or "as-declared"
    save_sharded_checkpoint: bool = False
    skip_sharding: bool = False
    compile_cache_dir: Optional[str] = None
    seed: int = 0

    # note: unknown kwargs warn (reference: models/config.py:639-640) — handled
    # by from_dict below.

    def __post_init__(self):
        if self.max_context_length is None:
            self.max_context_length = self.seq_len
        if self.max_batch_size is None:
            self.max_batch_size = self.batch_size
        if self.ctx_batch_size is None:
            self.ctx_batch_size = 1 if self.is_continuous_batching else self.batch_size
        if self.tkg_batch_size is None:
            self.tkg_batch_size = self.batch_size
        if self.kv_cache_batch_size is None:
            self.kv_cache_batch_size = max(self.tkg_batch_size, self.max_batch_size)
        if self.kv_cache_dtype is None:
            self.kv_cache_dtype = self.dtype
        if self.n_positions is None:
            self.n_positions = self.seq_len
        if self.world_size is None:
            # tp_degree counts all model-parallel ranks; cp/dp/ep subdivide
            # them rather than multiplying the world (reference:
            # models/config.py:382-390 world-size calc)
            self.world_size = self.tp_degree * self.pp_degree
        if self.local_ranks_size is None:
            self.local_ranks_size = self.world_size
        self.validate()

    # -- validation (reference: models/config.py:645-721) --
    def validate(self):
        if self.seq_len < 1:
            raise ValueError("seq_len must be >= 1")
        if self.max_context_length > self.seq_len:
            raise ValueError(
                f"max_context_length ({self.max_context_length}) cannot exceed "
                f"seq_len ({self.seq_len})")
        if self.cp_degree > 1 and self.tp_degree % self.cp_degree != 0:
            raise ValueError("cp_degree must divide tp_degree (cp shards the tp axis "
                             "during prefill)")
        if self.attention_dp_degree > 1:
            if self.tp_degree % self.attention_dp_degree != 0:
                raise ValueError("attention_dp_degree must divide tp_degree")
            if self.tkg_batch_size % self.attention_dp_degree != 0:
                raise ValueError("tkg_batch_size must be divisible by attention_dp_degree")
        if self.pp_degree > 1:
            # honest surface: like the reference, there is no pipeline
            # SCHEDULE in the inference path (reference plumbs pp into
            # ModelBuilder but runs no pipeline, SURVEY §2.8); refuse
            # rather than silently running tp-only
            raise ValueError(
                "pp_degree > 1 is not supported: inference has no pipeline "
                "schedule (shard wider with tp_degree instead)")
        if self.mlp_cp_degree > 1:
            if not self.sequence_parallel_enabled or \
                    self.mlp_cp_degree != max(self.cp_degree, 1):
                raise ValueError(
                    "mlp_cp_degree requires sequence_parallel_enabled and "
                    "mlp_cp_degree == cp_degree: MLP context parallelism is "
                    "realized as sequence-sharded MLP activations over the "
                    "cp axis (model_base._layer_body sp_axis)")
        if self.is_chunked_prefill and not self.is_block_kv_layout:
            raise ValueError("chunked prefill requires block KV layout")
        if self.is_prefix_caching and not self.is_block_kv_layout:
            raise ValueError("prefix caching requires block KV layout")
        if self.is_block_kv_layout and self.pa_num_blocks is None:
            self.pa_num_blocks = (
                self.kv_cache_batch_size * ((self.seq_len + self.pa_block_size - 1)
                                            // self.pa_block_size))
        spec = self.speculation_config
        if spec and spec.enable_eagle_speculation and not spec.enable_fused_speculation:
            raise ValueError("EAGLE speculation requires fused speculation")
        cc = self.collective_config
        if cc is not None and cc.dtype is not None:
            # typed refusal shared with parallel/collectives.py (lazy import:
            # resilience is self-contained, but config loads first at startup)
            from .resilience.errors import ConfigurationError
            if cc.dtype not in ("int8", "fp8"):
                raise ConfigurationError(
                    f"collective_config.dtype {cc.dtype!r} unsupported: "
                    "expected 'int8', 'fp8', or None")
            if cc.block < 1:
                raise ConfigurationError(
                    "collective_config.block must be >= 1")
        if self.mlp_low_rank is not None:
            from .resilience.errors import ConfigurationError
            if self.mlp_low_rank < 1:
                raise ConfigurationError(
                    f"mlp_low_rank must be >= 1, got {self.mlp_low_rank} "
                    "(None disables the low-rank MLP)")
        sc = self.on_device_sampling_config
        if sc is not None and sc.stream_seed is not None \
                and not sc.do_sample:
            from .resilience.errors import ConfigurationError
            raise ConfigurationError(
                "on_device_sampling_config.stream_seed requires "
                "do_sample=True: coupled streams only exist for sampled "
                "decode (greedy is already deterministic)")

    # -- dtype helpers --
    @property
    def jax_dtype(self):
        return to_jax_dtype(self.dtype)

    @property
    def jax_kv_dtype(self):
        return to_jax_dtype(self.kv_cache_dtype)

    @property
    def jax_logits_dtype(self):
        return to_jax_dtype(self.logits_dtype)

    @property
    def speculation_length(self) -> int:
        return self.speculation_config.speculation_length if self.speculation_config else 0

    # -- serialization (reference: models/config.py:927-1038 JSON round-trip) --
    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if dataclasses.is_dataclass(v):
                v = v.to_dict() if hasattr(v, "to_dict") else dataclasses.asdict(v)
            out[f.name] = v
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TpuConfig":
        d = dict(d)
        known = {f.name for f in dataclasses.fields(cls)}
        for key, sub_cls in _SUBCONFIG_TYPES.items():
            if isinstance(d.get(key), dict):
                d[key] = sub_cls(**d[key])
        unknown = [k for k in d if k not in known]
        for k in unknown:
            # warn-on-unknown (reference: models/config.py:639-640)
            logger.warning("TpuConfig: ignoring unknown key %r", k)
            d.pop(k)
        return cls(**d)


# Back-compat alias: reference users know this as NeuronConfig.
NeuronConfig = TpuConfig


@dataclass
class MoETpuConfig(TpuConfig):
    """Convenience subclass that always carries an MoEConfig
    (reference: models/config.py:798 ``MoENeuronConfig``)."""

    def __post_init__(self):
        if self.moe_config is None:
            self.moe_config = MoEConfig()
        super().__post_init__()


class InferenceConfig:
    """Wrapper pairing a HF-style model config with a :class:`TpuConfig`
    (reference: models/config.py:849-1042 ``InferenceConfig``).

    Arbitrary HF config attributes live directly on the object; ``tpu_config``
    (alias ``neuron_config``) holds runtime knobs. JSON round-trip via
    :meth:`save` / :meth:`load`.
    """

    _NON_HF_KEYS = ("tpu_config",)

    def __init__(self, tpu_config: TpuConfig, load_config=None, metadata=None, **kwargs):
        self.tpu_config = tpu_config
        self.metadata = metadata or {}
        if load_config is not None:
            if callable(load_config):
                load_config(self)
            else:
                for k, v in dict(load_config).items():
                    setattr(self, k, v)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.add_derived_config()
        self.validate_config()

    # alias to match reference naming
    @property
    def neuron_config(self) -> TpuConfig:
        return self.tpu_config

    def add_derived_config(self):
        """Model families override to compute derived attributes
        (reference: per-model ``setup_attr_for_model``)."""

    def get_required_attributes(self) -> List[str]:
        return []

    def validate_config(self):
        missing = [a for a in self.get_required_attributes() if not hasattr(self, a)]
        if missing:
            raise ValueError(f"InferenceConfig missing required attributes: {missing}")

    def get_text_config(self) -> "InferenceConfig":
        """Multimodal configs override to return the text sub-config
        (reference: models/config.py:946)."""
        return self

    # -- serialization --
    def to_dict(self) -> Dict[str, Any]:
        hf = {k: v for k, v in self.__dict__.items()
              if k not in self._NON_HF_KEYS and not k.startswith("_")
              and _json_safe(v)}
        return {"tpu_config": self.tpu_config.to_dict(), "hf_config": hf,
                "config_cls": f"{type(self).__module__}:{type(self).__qualname__}"}

    def to_json_string(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=str, sort_keys=True)

    def save(self, path: str):
        """Serialize next to compiled artifacts
        (reference: models/config.py:927-944)."""
        if os.path.isdir(path) or path.endswith(os.sep):
            os.makedirs(path, exist_ok=True)
            path = os.path.join(path, "tpu_inference_config.json")
        else:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_json_string())

    @classmethod
    def from_json_string(cls, s: str) -> "InferenceConfig":
        d = json.loads(s)
        config_cls = cls
        if "config_cls" in d and ":" in d.get("config_cls", ""):
            import importlib
            mod_name, qual = d["config_cls"].split(":")
            try:
                mod = importlib.import_module(mod_name)
                config_cls = getattr(mod, qual.split(".")[-1], cls)
            except ImportError:
                logger.warning("could not re-import config class %s", d["config_cls"])
        obj = config_cls.__new__(config_cls)
        obj.tpu_config = TpuConfig.from_dict(d["tpu_config"])
        obj.metadata = {}
        for k, v in d.get("hf_config", {}).items():
            setattr(obj, k, v)
        obj.add_derived_config()
        return obj

    @classmethod
    def load(cls, path: str) -> "InferenceConfig":
        if os.path.isdir(path):
            path = os.path.join(path, "tpu_inference_config.json")
        with open(path) as f:
            return cls.from_json_string(f.read())


def _json_safe(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def load_pretrained_config(model_path: str):
    """Build a load_config callable from a HF checkpoint dir's config.json
    (reference: utils/hf_adapter.py:36 ``load_pretrained_config``)."""

    def _load(cfg: InferenceConfig):
        cfg_path = os.path.join(model_path, "config.json")
        with open(cfg_path) as f:
            hf = json.load(f)
        for k, v in hf.items():
            setattr(cfg, k, v)
        cfg.model_path = model_path

    return _load
