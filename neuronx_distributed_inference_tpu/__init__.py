"""TPU-native distributed inference framework.

A ground-up JAX/XLA/Pallas re-design with the capability surface of
aws-neuron/neuronx-distributed-inference (the reference implementation for
Trainium). See SURVEY.md at the repo root for the component-by-component map.
"""

__version__ = "0.1.0"

from .compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

from .config import (ChunkedPrefillConfig, InferenceConfig, MoEConfig,
                     OnDeviceSamplingConfig, SpeculationConfig, TpuConfig,
                     load_pretrained_config)

__all__ = [
    "TpuConfig", "InferenceConfig", "OnDeviceSamplingConfig", "MoEConfig",
    "SpeculationConfig", "ChunkedPrefillConfig", "load_pretrained_config",
]
