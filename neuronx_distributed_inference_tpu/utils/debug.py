"""Debug utilities — input capture + auto-capture on logit divergence
(reference: utils/debug_utils.py:11-90 input-capture hook with auto-capture
when logits diverge, wiring inference_demo.py:616-649; _log_input
models/model_base.py:3506)."""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional

import numpy as np

logger = logging.getLogger("nxdi_tpu")

CAPTURE_DIR_ENV = "NXDI_TPU_DEBUG_CAPTURE_DIR"


def capture_inputs(path: str, tag: str, **arrays) -> str:
    """Save a set of named arrays as one .npz (reference: input-capture hook
    saving CTE/TKG inputs at chosen token indices)."""
    os.makedirs(path, exist_ok=True)
    f = os.path.join(path, f"{tag}.npz")
    np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()
                   if v is not None})
    logger.info("debug: captured %s", f)
    return f


def check_divergence(actual: np.ndarray, golden: np.ndarray,
                     divergence_tol: float = 1e-3,
                     capture_dir: Optional[str] = None,
                     tag: str = "divergence",
                     inputs: Optional[Dict[str, Any]] = None) -> Optional[int]:
    """Return the first index (flattened over leading dims) where
    |actual-golden| exceeds the tolerance, else None. On divergence, when a
    capture dir is set (arg or $NXDI_TPU_DEBUG_CAPTURE_DIR), dump both
    tensors (+ inputs) for offline triage — the reference's auto-capture on
    logit divergence."""
    actual = np.asarray(actual, np.float32)
    golden = np.asarray(golden, np.float32)
    err = np.abs(actual - golden)
    bad = err > (divergence_tol + divergence_tol * np.abs(golden))
    if not bad.any():
        return None
    idx = int(np.argwhere(bad.reshape(bad.shape[0], -1).any(axis=1))[0, 0])
    capture_dir = capture_dir or os.environ.get(CAPTURE_DIR_ENV)
    if capture_dir:
        payload = {"actual": actual, "golden": golden}
        if inputs:
            payload.update(inputs)
        capture_inputs(capture_dir, f"{tag}_idx{idx}", **payload)
    logger.warning("divergence at index %d: max err %.5f", idx,
                   float(err.max()))
    return idx


def log_inputs(tag: str, **arrays) -> None:
    """Compact input logging (reference: _log_input)."""
    parts = []
    for k, v in arrays.items():
        if v is None:
            continue
        v = np.asarray(v)
        parts.append(f"{k}: shape={v.shape} dtype={v.dtype}")
    logger.debug("%s inputs | %s", tag, " | ".join(parts))
