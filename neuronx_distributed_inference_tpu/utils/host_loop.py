"""Shared host-side decode loop for applications with model-specific step
state (whisper / mllama cross-attention decoders) — the same serving
conventions as ``CausalLMApplication.generate``: tokens stay ON DEVICE
through the loop (each device->host fetch costs a tunnel round trip on
remoted TPUs), JAX's async dispatch pipelines the steps, and EOS is
checked at chunk boundaries on tokens that already finished their async
copy (reference: the ``_sample`` host hot loop of utils/hf_adapter.py
:139-258 + async_execution.py double-buffering)."""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


def greedy_host_loop(step: Callable, first_tokens, max_new_tokens: int,
                     eos_ids: Optional[np.ndarray] = None,
                     eos_chunk: int = 8) -> np.ndarray:
    """Run up to ``max_new_tokens - 1`` decode steps after ``first_tokens``.

    step(last_dev) -> next_dev: takes/returns DEVICE (B,) int32 token
    arrays; the caller's closure advances positions and any model state.
    Returns the generated tokens (B, n) as numpy (n <= max_new_tokens;
    rows that hit EOS early may decode to the chunk boundary — harmless
    extra tokens past EOS, the same convention as the main app).
    """
    collected = [first_tokens]
    done = None
    checked = 0
    for i in range(1, max_new_tokens):
        nxt = step(collected[-1])
        try:
            nxt.copy_to_host_async()
        except AttributeError:
            pass
        collected.append(nxt)
        if eos_ids is not None and (i % eos_chunk == 0
                                    or i == max_new_tokens - 1):
            # check only the NEW chunk (already host-copied above) and OR
            # into a running done mask - O(n) total, like the main app
            chunk = np.stack([np.asarray(t)
                              for t in collected[checked:]], axis=1)
            checked = len(collected)
            hit = np.isin(chunk, eos_ids).any(axis=1)
            done = hit if done is None else (done | hit)
            if bool(done.all()):
                break
    return np.stack([np.asarray(t) for t in collected], axis=1)
