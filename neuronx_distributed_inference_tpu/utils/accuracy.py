"""Accuracy gates vs HF CPU golden (reference: utils/accuracy.py —
``check_accuracy`` token matching :244, ``check_accuracy_logits`` :478/:707
with per-index tol_map and divergence tolerance).

The golden is always the HF transformers model on CPU — same convention as
the reference (utils/accuracy.py:585-600 generates expected logits with the
CPU model)."""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("nxdi_tpu")


@dataclass
class AccuracyReport:
    passed: bool
    mode: str
    num_tokens_checked: int = 0
    num_divergences: int = 0
    first_divergence_index: Optional[int] = None
    max_error: float = 0.0
    details: Dict[str, Any] = field(default_factory=dict)

    def __str__(self):
        s = "PASS" if self.passed else "FAIL"
        return (f"[{self.mode}] {s}: {self.num_tokens_checked} tokens, "
                f"{self.num_divergences} divergences, max_err={self.max_error:.2e}")


def get_generate_outputs_hf(hf_model, input_ids: np.ndarray,
                            attention_mask: Optional[np.ndarray],
                            max_new_tokens: int,
                            eos_token_id: Optional[int] = None):
    """Per-row greedy generation + per-step logits from the HF CPU golden.

    Runs HF generate() one row at a time with the padding stripped — HF
    decoder-only generation requires left padding, and per-row unpadded runs
    sidestep padding-side pitfalls entirely while keeping the scores aligned
    to generation steps. Returns (gen_tokens, scores): gen_tokens[i] is the
    1-D array of tokens generated for row i (stops at EOS), scores[i] is the
    list of (V,) logit vectors per step."""
    import torch
    hf_model.eval()
    ids = np.asarray(input_ids, dtype=np.int64)
    b, s = ids.shape
    lens = (np.asarray(attention_mask).astype(int).sum(1)
            if attention_mask is not None else np.full((b,), s))
    gen_tokens, scores = [], []
    kwargs = {}
    if eos_token_id is not None:
        kwargs["eos_token_id"] = eos_token_id
    for i in range(b):
        row = torch.tensor(ids[i:i + 1, :lens[i]])
        with torch.no_grad():
            out = hf_model.generate(
                row, max_new_tokens=max_new_tokens, do_sample=False,
                output_scores=True, return_dict_in_generate=True, **kwargs)
        gen_tokens.append(out.sequences.numpy()[0, lens[i]:])
        scores.append([sc.numpy()[0] for sc in out.scores])
    return gen_tokens, scores


def check_accuracy(app, hf_model, input_ids: np.ndarray,
                   max_new_tokens: int = 32,
                   attention_mask: Optional[np.ndarray] = None,
                   eos_token_id: Optional[int] = None) -> AccuracyReport:
    """Token-matching gate (reference: utils/accuracy.py:244): greedy tokens
    from the TPU app must equal the HF CPU golden exactly, compared per row
    up to the golden's generated length (post-EOS padding excluded)."""
    golden_gen, _ = get_generate_outputs_hf(hf_model, input_ids,
                                            attention_mask, max_new_tokens,
                                            eos_token_id)
    res = app.generate(np.asarray(input_ids, np.int32),
                       attention_mask=attention_mask,
                       max_new_tokens=max_new_tokens,
                       eos_token_id=eos_token_id)
    ours_gen = res["generated"]
    num_div, first, checked = 0, None, 0
    for i, golden in enumerate(golden_gen):
        n = min(len(golden), ours_gen.shape[1])
        mism = ours_gen[i, :n] != golden[:n]
        checked += n
        if mism.any():
            num_div += int(mism.sum())
            idx = int(np.argwhere(mism)[0, 0])
            first = idx if first is None else min(first, idx)
    return AccuracyReport(passed=num_div == 0, mode="token-matching",
                          num_tokens_checked=checked,
                          num_divergences=num_div, first_divergence_index=first,
                          details={"ours": ours_gen.tolist(),
                                   "golden": [g.tolist() for g in golden_gen]})


def check_accuracy_logits(app, hf_model, input_ids: np.ndarray,
                          max_new_tokens: int = 16,
                          divergence_difference_tol: float = 0.001,
                          tol_map: Optional[Dict[int, Tuple[float, float]]] = None,
                          attention_mask: Optional[np.ndarray] = None
                          ) -> AccuracyReport:
    """Logit-matching gate (reference: utils/accuracy.py:478 v1 / :707 v2).

    Teacher-forces the golden's greedy tokens through the TPU model and
    compares per-step next-token logits within ``divergence_difference_tol``;
    ``tol_map`` = {step_index: (atol, rtol)} per-index overrides
    (reference: inference_demo.py --tol-map)."""
    if not app.tpu_config.output_logits:
        raise ValueError("app must be built with output_logits=True for "
                         "logit-matching")
    golden_gen, golden_scores = get_generate_outputs_hf(
        hf_model, input_ids, attention_mask, max_new_tokens)
    b, s = np.asarray(input_ids).shape
    # teacher tokens: per-row golden generations, right-padded with the last
    # token (padded steps are never compared)
    max_t = max(len(g) for g in golden_gen)
    teacher = np.stack([np.pad(g, (0, max_t - len(g)), mode="edge")
                        for g in golden_gen]).astype(np.int32)
    res = app.generate(np.asarray(input_ids, np.int32),
                       attention_mask=attention_mask,
                       max_new_tokens=max_t, return_logits=True,
                       teacher_tokens=teacher)
    step_logits = res["logits"]
    seq_lens = (np.asarray(attention_mask).sum(1).astype(int)
                if attention_mask is not None else np.full((b,), s))

    max_err, num_div, first = 0.0, 0, None
    checked = 0
    for step in range(min(max_t, len(step_logits))):
        atol, rtol = (tol_map or {}).get(step, (divergence_difference_tol, 0.0))
        for i in range(b):
            if step >= len(golden_scores[i]):
                continue  # row i's golden stopped at EOS before this step
            golden = golden_scores[i][step]                # (V,)
            if step == 0:
                ours = step_logits[0][i, seq_lens[i] - 1]  # prefill last pos
            else:
                ours = step_logits[step][i, -1, :]
            v = min(ours.shape[-1], golden.shape[-1])
            err = np.abs(ours[:v] - golden[:v])
            max_err = max(max_err, float(err.max()))
            div = err > (atol + rtol * np.abs(golden[:v]))
            checked += int(div.size)
            if div.any():
                num_div += int(div.sum())
                if first is None:
                    first = step
    return AccuracyReport(passed=num_div == 0, mode="logit-matching",
                          num_tokens_checked=checked, num_divergences=num_div,
                          first_divergence_index=first, max_error=max_err)
