"""KV-cache reconstruction debug utilities (reference:
utils/kv_cache_reconstruct_utils.py, 251 LoC — the paged-layout debugging
story): rebuild a sequence's CONTIGUOUS per-layer K/V view from any of the
cache layouts so layouts can be diffed against each other or dumped for
inspection.

All functions are host-side (numpy in, numpy out) and read-only."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


def reconstruct_contiguous(cache: Dict, row: int, length: int
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """Contiguous stacked cache {"k" (L,B,H,D,S) transposed-K, "v"
    (L,B,H,S,D)} -> (k (L, length, H, D), v (L, length, H, D))."""
    k = np.asarray(cache["k"][:, row])                   # (L, H, D, S)
    v = np.asarray(cache["v"][:, row])                   # (L, H, S, D)
    k_lin = np.transpose(k[:, :, :, :length], (0, 3, 1, 2))
    v_lin = np.transpose(v[:, :, :length], (0, 2, 1, 3))
    return k_lin, v_lin


def reconstruct_rolling(cache: Dict, row: int, length: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Rolling window cache (W slots, slot = pos %% W) -> the LAST
    min(length, W) positions in order (older positions are gone).
    Returns (k (L, n, H, D), v (L, n, H, D), ) with n = min(length, W)."""
    W = cache["v"].shape[3]
    n = min(length, W)
    positions = np.arange(length - n, length)
    slots = positions % W
    k = np.asarray(cache["k"][:, row])                   # (L, H, D, W)
    v = np.asarray(cache["v"][:, row])
    k_lin = np.transpose(k[:, :, :, slots], (0, 3, 1, 2))
    v_lin = np.transpose(v[:, :, slots], (0, 2, 1, 3))
    return k_lin, v_lin


def reconstruct_mixed(cache: Dict, layer_pattern, row: int, length: int
                      ) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    """Mixed per-layer cache ({"k","v"} global + {"k_l","v_l"} rolling) ->
    {absolute_layer: (k (n, H, D), v (n, H, D))} — global layers return
    ``length`` positions, local layers their last min(length, W)."""
    from ..modules.kv_cache import mixed_layer_map
    lmap = mixed_layer_map(layer_pattern)
    gk, gv = reconstruct_contiguous(
        {"k": cache["k"], "v": cache["v"]}, row, length)
    lk, lv = reconstruct_rolling(
        {"k": cache["k_l"], "v": cache["v_l"]}, row, length)
    out = {}
    for i, is_local in enumerate(layer_pattern):
        if is_local:
            out[i] = (lk[lmap[i]], lv[lmap[i]])
        else:
            out[i] = (gk[lmap[i]], gv[lmap[i]])
    return out


def reconstruct_paged(cache: Dict, block_table, length: int,
                      row: Optional[int] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Paged cache {"k","v" (L, N, Bs, H, D)} + a sequence's block list (or
    a (B, max_blocks) table with ``row``) -> (k (L, length, H, D),
    v (L, length, H, D)) (reference: kv_cache_reconstruct_utils.py)."""
    bt = np.asarray(block_table)
    if bt.ndim == 2:
        if row is None:
            raise ValueError("row required with a 2-D block table")
        bt = bt[row]
    k = np.asarray(cache["k"])                           # (L, N, Bs, H, D)
    v = np.asarray(cache["v"])
    bs = k.shape[2]
    n_blocks = -(-length // bs)
    if n_blocks > bt.shape[0]:
        raise ValueError(f"length {length} needs {n_blocks} blocks, table "
                         f"has {bt.shape[0]}")
    k_seq = k[:, bt[:n_blocks]].reshape(k.shape[0], -1, k.shape[3],
                                        k.shape[4])[:, :length]
    v_seq = v[:, bt[:n_blocks]].reshape(v.shape[0], -1, v.shape[3],
                                        v.shape[4])[:, :length]
    return k_seq, v_seq


def diff_layouts(a: Tuple[np.ndarray, np.ndarray],
                 b: Tuple[np.ndarray, np.ndarray],
                 atol: float = 1e-5) -> Dict[str, float]:
    """Compare two reconstructions; returns max-abs diffs and the first
    mismatching (layer, position) — the cross-layout debugging primitive
    (reference: the reconstruct-and-compare flow of
    kv_cache_reconstruct_utils.py)."""
    out = {}
    for name, x, y in (("k", a[0], b[0]), ("v", a[1], b[1])):
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32)
        d = np.abs(x - y)
        out[f"{name}_max_abs_diff"] = float(d.max()) if d.size else 0.0
        if d.size and d.max() > atol:
            idx = np.unravel_index(np.argmax(d), d.shape)
            out[f"{name}_first_mismatch"] = (int(idx[0]), int(idx[1]))
    return out
