"""utils subpackage."""
