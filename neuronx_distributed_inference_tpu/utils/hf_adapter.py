"""HuggingFace ``generate()``-compatible adapter
(reference: utils/hf_adapter.py ``HuggingFaceGenerationAdapter`` :133-890).

Wraps a :class:`CausalLMApplication` so code written against the HF
transformers generation API works unchanged:

  * torch tensors in / torch tensors out (``GenerateOutput``-shaped dict or
    plain sequences tensor, matching ``return_dict_in_generate``)
  * LEFT padding accepted (HF decoder-only convention) and converted to the
    framework's right-padded layout (reference handles right padding in
    ``prepare_inputs_for_generation`` :259-335; we normalize at the boundary)
  * ``GenerationConfig`` / kwargs: max_new_tokens, max_length, do_sample,
    top_k, top_p, temperature, eos_token_id, pad_token_id
  * assisted decoding via ``assistant_model`` (reference: :439-632) routed to
    the fused SpeculativeDecoder

The host loop itself lives in the application layer
(models/application.py ``generate``); this file is pure adaptation.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import numpy as np

from ..ops.sampling import prepare_sampling_params

logger = logging.getLogger("nxdi_tpu")


def _to_numpy(x):
    if x is None:
        return None
    if hasattr(x, "detach"):           # torch tensor
        return x.detach().cpu().numpy()
    return np.asarray(x)


class HuggingFaceGenerationAdapter:
    """Duck-typed stand-in for a HF ``PreTrainedModel`` in generation code.

    Parameters
    ----------
    app : CausalLMApplication (already weight-loaded)
    generation_config : optional object/dict with HF generation defaults
    """

    def __init__(self, app, generation_config=None):
        self.app = app
        self.config = app.config
        self.generation_config = generation_config
        self.device = "tpu"

    # HF code probes these
    @property
    def main_input_name(self):
        return "input_ids"

    def can_generate(self):
        return True

    def eval(self):
        return self

    # ------------------------------------------------------------------
    def _resolve(self, name, kwargs, default=None):
        if name in kwargs and kwargs[name] is not None:
            return kwargs[name]
        gc = kwargs.get("generation_config") or self.generation_config
        if gc is not None:
            v = gc.get(name) if isinstance(gc, dict) else getattr(gc, name, None)
            if v is not None:
                return v
        return default

    @staticmethod
    def _normalize_padding(ids: np.ndarray, mask: np.ndarray):
        """LEFT-padded rows -> right-padded (framework layout). Rows already
        right-padded or unpadded pass through untouched."""
        b, s = ids.shape
        out_ids = np.zeros_like(ids)
        out_mask = np.zeros_like(mask)
        lens = mask.astype(np.int64).sum(axis=1)
        left_padded = False
        for i in range(b):
            pos = np.nonzero(mask[i])[0]
            n = int(lens[i])
            if n and not np.array_equal(pos, np.arange(n)):
                left_padded = True
            out_ids[i, :n] = ids[i, pos]
            out_mask[i, :n] = 1
        return out_ids, out_mask, lens, left_padded

    # ------------------------------------------------------------------
    def generate(self, input_ids=None, attention_mask=None,
                 assistant_model=None, return_dict_in_generate: bool = False,
                 **kwargs):
        """HF-compatible generation entry point.

        Returns a torch LongTensor ``sequences`` (prompt + generated, in the
        caller's original padding layout) or a dict when
        ``return_dict_in_generate=True``.
        """
        ids = _to_numpy(input_ids).astype(np.int64)
        if ids.ndim == 1:
            ids = ids[None, :]
        b, s = ids.shape
        mask = _to_numpy(attention_mask)
        if mask is None:
            mask = np.ones_like(ids)
        mask = mask.astype(np.int64)

        max_new = self._resolve("max_new_tokens", kwargs)
        if max_new is None:
            max_length = self._resolve("max_length", kwargs,
                                       self.app.tpu_config.seq_len)
            max_new = max(int(max_length) - s, 1)
        eos = self._resolve("eos_token_id", kwargs)
        if isinstance(eos, (list, tuple)) and not eos:
            eos = None
        pad_id = self._resolve("pad_token_id", kwargs)
        if pad_id is None:
            pad_id = (eos[0] if isinstance(eos, (list, tuple)) else eos) \
                if eos is not None else 0

        do_sample = bool(self._resolve("do_sample", kwargs, False))
        sampling_params = None
        if do_sample:
            sampling_params = prepare_sampling_params(
                b,
                self._resolve("top_k", kwargs, 0) or 0,
                self._resolve("top_p", kwargs, 1.0),
                self._resolve("temperature", kwargs, 1.0))

        r_ids, r_mask, lens, _ = self._normalize_padding(ids, mask)

        if assistant_model is not None:
            if do_sample:
                logger.warning("assisted decoding is greedy-only; ignoring "
                               "do_sample/top_k/top_p/temperature")
            out = self._assisted_generate(assistant_model, r_ids, r_mask,
                                          int(max_new), eos)
        else:
            out = self.app.generate(
                r_ids, attention_mask=r_mask, max_new_tokens=int(max_new),
                eos_token_id=eos, sampling_params=sampling_params)

        gen = out["generated"]                           # (B, T)
        n_gen = gen.shape[1]
        # HF layout contract: sequences[:, :s] is the caller's input block
        # UNCHANGED (whatever its padding side); generated tokens start at
        # column s, truncated at the first eos then padded with pad_id —
        # so the universal idiom ``out[:, input_ids.shape[1]:]`` yields
        # exactly the new tokens.
        eos_arr = (np.atleast_1d(np.asarray(eos, dtype=np.int64))
                   if eos is not None else None)
        seqs = np.full((b, s + n_gen), pad_id, dtype=np.int64)
        seqs[:, :s] = ids
        for i in range(b):
            row = gen[i]
            if eos_arr is not None:
                hits = np.nonzero(np.isin(row, eos_arr))[0]
                if hits.size:
                    row = row[:hits[0] + 1]
            seqs[i, s:s + len(row)] = row
        result = _maybe_torch(seqs)
        if return_dict_in_generate:
            d: Dict[str, Any] = {"sequences": result}
            if "mean_tokens_per_step" in out:
                d["mean_tokens_per_step"] = out["mean_tokens_per_step"]
            return d
        return result

    # ------------------------------------------------------------------
    def _assisted_generate(self, assistant_model, r_ids, r_mask, max_new, eos):
        """Assisted decoding (reference: hf_adapter.py:439-632). The
        assistant may be another adapter, a CausalLMApplication, or a
        prebuilt SpeculativeDecoder."""
        from ..models.speculation import SpeculativeDecoder
        if isinstance(assistant_model, SpeculativeDecoder):
            dec = assistant_model
        else:
            draft_app = getattr(assistant_model, "app", assistant_model)
            dec = SpeculativeDecoder(self.app, draft_app)
        return dec.generate(r_ids, max_new_tokens=max_new, eos_token_id=eos,
                            attention_mask=r_mask)

    __call__ = generate


def _maybe_torch(a: np.ndarray):
    try:
        import torch
        return torch.from_numpy(np.ascontiguousarray(a))
    except ImportError:        # torch always present in practice; keep soft
        return a
