"""Checkpoint I/O (reference: modules/checkpoint.py).

Loads HF checkpoints (safetensors — single or sharded via index json — or
torch .bin) into host numpy dicts; model families provide
``convert_hf_state_dict`` to reshape into the stacked/padded TPU layout; this
module then device_puts each leaf with its NamedSharding (shard-on-load —
the analog of the reference's ``builder.shard_checkpoint``,
application_base.py:375-421)."""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

logger = logging.getLogger("nxdi_tpu")

SAFETENSORS_INDEX = "model.safetensors.index.json"


def load_state_dict(model_path: str) -> Dict[str, np.ndarray]:
    """Load a HF checkpoint directory into {name: np.ndarray}
    (reference: modules/checkpoint.py:24-170 ``load_state_dict`` — regular /
    sharded safetensors and .bin paths)."""
    if os.path.isfile(model_path):
        return _load_one(model_path)
    idx = os.path.join(model_path, SAFETENSORS_INDEX)
    if os.path.exists(idx):
        with open(idx) as f:
            index = json.load(f)
        shards = sorted(set(index["weight_map"].values()))
        out: Dict[str, np.ndarray] = {}
        for shard in shards:
            out.update(_load_one(os.path.join(model_path, shard)))
        return out
    st = os.path.join(model_path, "model.safetensors")
    if os.path.exists(st):
        return _load_one(st)
    bins = [f for f in os.listdir(model_path)
            if f.endswith(".bin") and "training" not in f]
    if bins:
        out = {}
        for b in sorted(bins):
            out.update(_load_one(os.path.join(model_path, b)))
        return out
    raise FileNotFoundError(f"no checkpoint files found under {model_path}")


def _load_one(path: str) -> Dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        from safetensors import safe_open
        out = {}
        with safe_open(path, framework="np") as f:
            for k in f.keys():
                try:
                    out[k] = f.get_tensor(k)
                except (TypeError, ValueError, AttributeError):
                    # bf16/fp8 tensors: numpy lacks these dtypes (safetensors
                    # raises AttributeError for fp8) — round-trip via torch
                    out[k] = _torch_tensor(path, k)
        return out
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=True)
    return {k: _to_numpy(v) for k, v in sd.items()}


def _torch_tensor(path: str, key: str) -> np.ndarray:
    from safetensors import safe_open
    with safe_open(path, framework="pt") as f:
        return _to_numpy(f.get_tensor(key))


def _to_numpy(t) -> np.ndarray:
    import torch
    import ml_dtypes
    if t.dtype == torch.bfloat16:
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    if t.dtype == torch.float8_e4m3fn:
        return t.view(torch.uint8).numpy().view(ml_dtypes.float8_e4m3fn)
    if t.dtype == torch.float8_e5m2:
        return t.view(torch.uint8).numpy().view(ml_dtypes.float8_e5m2)
    return t.numpy()


def save_state_dict_safetensors(state_dict: Dict[str, np.ndarray], path: str,
                                max_shard_bytes: int = 5 * 2**30):
    """Save as (possibly sharded) safetensors
    (reference: modules/checkpoint.py ``save_state_dict_safetensors``)."""
    from safetensors.numpy import save_file
    os.makedirs(path, exist_ok=True)
    items = list(state_dict.items())
    shards, cur, cur_bytes = [], {}, 0
    for k, v in items:
        if cur and cur_bytes + v.nbytes > max_shard_bytes:
            shards.append(cur)
            cur, cur_bytes = {}, 0
        cur[k] = v
        cur_bytes += v.nbytes
    shards.append(cur)
    if len(shards) == 1:
        save_file(shards[0], os.path.join(path, "model.safetensors"))
        return
    weight_map = {}
    for i, shard in enumerate(shards):
        name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
        save_file(shard, os.path.join(path, name))
        for k in shard:
            weight_map[k] = name
    with open(os.path.join(path, SAFETENSORS_INDEX), "w") as f:
        json.dump({"metadata": {}, "weight_map": weight_map}, f)


def device_put_params(host_params: Dict[str, Any], shardings: Dict[str, Any],
                      dtype=None) -> Dict[str, Any]:
    """Transfer a host param tree to devices with per-leaf shardings."""

    def _put(x, s):
        if dtype is not None and np.issubdtype(np.asarray(x).dtype, np.floating):
            x = np.asarray(x).astype(dtype)
        return jax.device_put(x, s)

    return jax.tree.map(_put, host_params, shardings)
