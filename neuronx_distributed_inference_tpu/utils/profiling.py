"""Profiling — jax.profiler integration (reference: utils/profiling.py,
which shells out to ``neuron-profile capture`` on compiled NEFFs; the TPU
equivalent is the XLA/TPU profiler whose traces open in TensorBoard /
Perfetto, SURVEY §5)."""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("nxdi_tpu")


@contextlib.contextmanager
def profile(log_dir: str = "profiles", host_tracer_level: int = 2):
    """Trace everything in the with-block; view with
    ``tensorboard --logdir <log_dir>`` (profile plugin) or xprof."""
    import jax
    os.makedirs(log_dir, exist_ok=True)
    logger.info("profiler: tracing to %s", log_dir)
    with jax.profiler.trace(log_dir):
        yield log_dir


def profile_generate(app, input_ids, log_dir: str = "profiles",
                     **generate_kwargs) -> Dict[str, Any]:
    """Profile one generate() call end-to-end (reference:
    utils/profiling.py capture flow: warm first, then trace)."""
    import jax
    # warm compile outside the trace so the profile shows steady-state
    app.generate(input_ids, **{**generate_kwargs,
                               "max_new_tokens": min(
                                   2, generate_kwargs.get("max_new_tokens", 2))})
    app.reset()
    t0 = time.perf_counter()
    with profile(log_dir):
        out = app.generate(input_ids, **generate_kwargs)
        jax.block_until_ready(out.get("generated"))
    out["profile_dir"] = log_dir
    out["profiled_wall_s"] = time.perf_counter() - t0
    return out


def annotate(name: str):
    """Named trace region (shows up in the profiler timeline)."""
    import jax
    return jax.profiler.TraceAnnotation(name)
