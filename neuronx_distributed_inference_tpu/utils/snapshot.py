"""Snapshot system — env-var-driven capture of model inputs (+ weights) at
chosen requests/tokens (reference: utils/snapshot.py:234-448, registration
application_base.py:423-554; env vars NXD_INFERENCE_CAPTURE_SNAPSHOT*).

TPU version: no TorchScript hooks needed — the application calls
``SnapshotManager.save`` at the two host->device boundaries (prefill /
decode) with the exact arrays being fed to the jitted graph.

Env vars (reference names accepted with the NXDI_TPU prefix too):
  NXDI_TPU_CAPTURE_SNAPSHOT=1         enable
  NXDI_TPU_SNAPSHOT_OUTPUT_PATH=dir   output root (default ./snapshots)
  NXDI_TPU_SNAPSHOT_FORMAT=npy|pickle
  NXDI_TPU_SNAPSHOT_AT_REQUESTS=0,2   request indices to capture
  NXDI_TPU_SNAPSHOT_FOR_TOKENS=0,1    token indices (0 = prefill)
  NXDI_TPU_SNAPSHOT_WEIGHTS=1         also dump the weights once
"""

from __future__ import annotations

import logging
import os
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger("nxdi_tpu")

_PREFIXES = ("NXDI_TPU", "NXD_INFERENCE")


def _env(suffix: str) -> Optional[str]:
    for p in _PREFIXES:
        v = os.environ.get(f"{p}_{suffix}")
        if v is not None:
            return v
    return None


def _int_list(s: Optional[str]) -> Optional[List[int]]:
    if not s:
        return None
    return [int(x) for x in s.split(",") if x.strip() != ""]


@dataclass
class SnapshotConfig:
    enabled: bool = False
    output_path: str = "snapshots"
    fmt: str = "npy"                       # "npy" | "pickle"
    at_requests: Optional[List[int]] = None   # None = every request
    for_tokens: Optional[List[int]] = None    # None = every token; 0=prefill
    capture_weights: bool = False

    @classmethod
    def from_env(cls) -> "SnapshotConfig":
        return cls(
            enabled=_env("CAPTURE_SNAPSHOT") in ("1", "true", "True"),
            output_path=_env("SNAPSHOT_OUTPUT_PATH") or "snapshots",
            fmt=(_env("SNAPSHOT_FORMAT") or "npy"),
            at_requests=_int_list(_env("SNAPSHOT_AT_REQUESTS")),
            for_tokens=_int_list(_env("SNAPSHOT_FOR_TOKENS")),
            capture_weights=_env("SNAPSHOT_WEIGHTS") in ("1", "true", "True"),
        )


class SnapshotManager:
    """Tracks (request, token) indices and writes matching snapshots."""

    def __init__(self, cfg: Optional[SnapshotConfig] = None):
        self.cfg = cfg or SnapshotConfig.from_env()
        self.request_idx = -1
        self.token_idx = 0          # 0 = prefill, then one per decode step
        self._weights_saved = False

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled

    def on_request(self):
        self.request_idx += 1
        self.token_idx = 0

    def save_step(self, tensors: Dict[str, Any],
                  weights: Optional[Dict[str, Any]] = None):
        """Save at the current token index, then advance it."""
        self.save(self.token_idx, tensors, weights)
        self.token_idx += 1

    def should(self, token_idx: int) -> bool:
        c = self.cfg
        if not c.enabled:
            return False
        if c.at_requests is not None and self.request_idx not in c.at_requests:
            return False
        if c.for_tokens is not None and token_idx not in c.for_tokens:
            return False
        return True

    def save(self, token_idx: int, tensors: Dict[str, Any],
             weights: Optional[Dict[str, Any]] = None):
        """Write one snapshot if (request, token) matches the config."""
        if not self.should(token_idx):
            return
        d = os.path.join(self.cfg.output_path,
                         f"request_{self.request_idx}", f"token_{token_idx}")
        os.makedirs(d, exist_ok=True)
        arrays = {k: np.asarray(v) for k, v in tensors.items()
                  if v is not None}
        if self.cfg.fmt == "pickle":
            with open(os.path.join(d, "inputs.pkl"), "wb") as f:
                pickle.dump(arrays, f)
        else:
            for k, v in arrays.items():
                np.save(os.path.join(d, f"{k}.npy"), v)
        logger.info("snapshot: captured %d tensors at request %d token %d",
                    len(arrays), self.request_idx, token_idx)
        if (self.cfg.capture_weights and weights is not None
                and not self._weights_saved):
            wd = os.path.join(self.cfg.output_path, "weights")
            os.makedirs(wd, exist_ok=True)
            flat = _flatten(weights)
            if self.cfg.fmt == "pickle":
                with open(os.path.join(wd, "weights.pkl"), "wb") as f:
                    pickle.dump({k: np.asarray(v) for k, v in flat.items()}, f)
            else:
                for k, v in flat.items():
                    np.save(os.path.join(wd, f"{k.replace('/', '_')}.npy"),
                            np.asarray(v))
            self._weights_saved = True


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out
