"""Benchmark harness (reference: utils/benchmark.py — ``LatencyCollector``
forward hooks :397-431, ``Benchmark`` loop :449-482, report schema :496-516,
``benchmark_sampling`` :21-208).

Same report schema: latency_ms_{p0,p50,p90,p95,p99,p100,avg} per submodel and
e2e, plus throughput = total generated tokens / total time."""

from __future__ import annotations

import json
import time
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

import numpy as np

BENCHMARK_REPORT_FILENAME = "benchmark_report.json"


class LatencyCollector:
    """Accumulates per-call wall times for one submodel tag
    (reference: utils/benchmark.py:397-431)."""

    def __init__(self):
        self.latency_list: List[float] = []

    def record(self, seconds: float):
        self.latency_list.append(seconds)

    def percentile(self, pct: float) -> float:
        if not self.latency_list:
            return 0.0
        return float(np.percentile(self.latency_list, pct))

    def report(self) -> Dict[str, float]:
        out = {}
        for pct in (0, 50, 90, 95, 99, 100):
            out[f"latency_ms_p{pct}"] = self.percentile(pct) * 1e3
        out["latency_ms_avg"] = (float(np.mean(self.latency_list)) * 1e3
                                 if self.latency_list else 0.0)
        return out


class Benchmark:
    """E2E benchmark loop (reference: utils/benchmark.py:449-482)."""

    def __init__(self, benchmark_func: Callable[[], Any], n_runs: int = 20,
                 preprocess_func: Optional[Callable[[], Any]] = None):
        self.benchmark_func = benchmark_func
        self.n_runs = n_runs
        self.preprocess_func = preprocess_func
        self.collector = LatencyCollector()

    def run(self):
        for _ in range(self.n_runs):
            if self.preprocess_func:
                self.preprocess_func()
            t0 = time.perf_counter()
            self.benchmark_func()
            self.collector.record(time.perf_counter() - t0)
        return self.collector.report()


def generate_report(e2e: LatencyCollector,
                    submodel_collectors: Dict[str, LatencyCollector],
                    total_generated_tokens: int,
                    report_path: Optional[str] = None) -> Dict[str, Any]:
    """Assemble the reference-schema report
    (reference: utils/benchmark.py:496-516 + JSON write :203-208)."""
    total_time = sum(e2e.latency_list)
    report: Dict[str, Any] = {"e2e_model": e2e.report()}
    report["e2e_model"]["throughput"] = (
        total_generated_tokens / total_time if total_time else 0.0)
    for tag, col in submodel_collectors.items():
        report[tag] = col.report()
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2)
    return report


def benchmark_sampling(app, input_ids: np.ndarray, max_new_tokens: int = 64,
                       n_runs: int = 20,
                       report_path: Optional[str] = None) -> Dict[str, Any]:
    """Benchmark an application's generate() (reference:
    utils/benchmark.py:21-208 ``benchmark_sampling``). One warmup run, then
    n_runs timed runs; throughput counts generated tokens only."""
    app.generate(input_ids, max_new_tokens=max_new_tokens)  # warmup/compile
    e2e = LatencyCollector()
    ttft = LatencyCollector()
    total_tokens = 0
    for _ in range(n_runs):
        app.reset()
        t0 = time.perf_counter()
        res = app.generate(input_ids, max_new_tokens=max_new_tokens)
        e2e.record(time.perf_counter() - t0)
        ttft.record(res["ttft_s"])
        total_tokens += int(res["generated"].size)
    return generate_report(e2e, {"context_encoding_model": ttft},
                           total_tokens, report_path)
