"""Testing harness (reference: utils/testing.py — ``build_module`` /
``build_function`` :123-268 micro-compile helpers, ``validate_accuracy``
:67-121, ``init_cpu_env``/``destroy_cpu_env`` :40-64 fake-distributed CPU
backend; SURVEY §4).

TPU equivalents: the fake-distributed backend is just JAX's virtual CPU
devices; build_function is an AOT jit lower+compile wrapper; accuracy
validation compares a device callable against a CPU/golden callable with
the reference's assert_close semantics."""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np


def init_cpu_env(num_devices: int = 8) -> int:
    """Force the virtual-CPU backend with ``num_devices`` devices
    (reference: init_cpu_env's gloo world + NXD_CPU_MODE). Must run before
    the JAX backend initializes; returns the device count actually live."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={num_devices}"
        ).strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", num_devices)
    except RuntimeError:
        pass
    return len(jax.devices())


def destroy_cpu_env() -> None:
    """Kept for API parity (JAX needs no teardown; the reference destroys
    its gloo process group here)."""


def build_function(fn: Callable, example_args: Sequence[Any],
                   static_argnums: Tuple[int, ...] = (),
                   donate_argnums: Tuple[int, ...] = (),
                   mesh=None) -> Callable:
    """AOT-compile a bare function at the example input shapes
    (reference: build_function — one-off ModelBuilder trace+compile).
    Returns the compiled executable (callable with matching shapes)."""
    import jax
    jitted = jax.jit(fn, static_argnums=static_argnums,
                     donate_argnums=donate_argnums)
    if mesh is not None:
        with jax.sharding.set_mesh(mesh):
            return jitted.lower(*example_args).compile()
    return jitted.lower(*example_args).compile()


def build_module(module_fn: Callable, params: Any,
                 example_args: Sequence[Any], mesh=None) -> Callable:
    """Compile ``module_fn(params, *args)`` with params closed over —
    the functional analog of the reference's nn.Module build_module."""
    compiled = build_function(module_fn, (params, *example_args), mesh=mesh)
    return lambda *args: compiled(params, *args)


def assert_close(actual, expected, rtol: float = 1.6e-2,
                 atol: float = 1e-5, msg: str = ""):
    """Dtype-aware closeness (reference: torch_neuronx assert_close usage —
    loose default rtol for bf16-class comparisons)."""
    a = np.asarray(actual, np.float32)
    e = np.asarray(expected, np.float32)
    np.testing.assert_allclose(a, e, rtol=rtol, atol=atol, err_msg=msg)


@dataclasses.dataclass
class AccuracyReport:
    passed: bool
    max_abs_err: float
    max_rel_err: float
    num_mismatched: int
    message: str = ""

    def __str__(self) -> str:
        s = "PASS" if self.passed else "FAIL"
        return (f"validate_accuracy: {s} max_abs={self.max_abs_err:.3e} "
                f"max_rel={self.max_rel_err:.3e} "
                f"mismatched={self.num_mismatched} {self.message}")


def validate_accuracy(device_fn: Callable, inputs: Sequence[Any],
                      cpu_callable: Optional[Callable] = None,
                      golden: Any = None, rtol: float = 1.6e-2,
                      atol: float = 1e-5) -> AccuracyReport:
    """Run ``device_fn(*inputs)`` and compare against a CPU callable and/or
    a precomputed golden (reference: validate_accuracy :67-121 compares
    device vs cpu vs golden)."""
    import jax
    actual = jax.device_get(device_fn(*inputs))
    if golden is None:
        if cpu_callable is None:
            raise ValueError("need cpu_callable or golden")
        golden = cpu_callable(*inputs)
    flat_a = np.concatenate([np.ravel(np.asarray(x, np.float32))
                             for x in jax.tree.leaves(actual)])
    flat_g = np.concatenate([np.ravel(np.asarray(x, np.float32))
                             for x in jax.tree.leaves(golden)])
    abs_err = np.abs(flat_a - flat_g)
    denom = np.maximum(np.abs(flat_g), 1e-9)
    rel_err = abs_err / denom
    bad = abs_err > (atol + rtol * np.abs(flat_g))
    return AccuracyReport(
        passed=not bad.any(),
        max_abs_err=float(abs_err.max(initial=0.0)),
        max_rel_err=float(rel_err.max(initial=0.0)),
        num_mismatched=int(bad.sum()),
    )


def check_generation_golden(app, ids: np.ndarray, hf_model,
                            max_new_tokens: int = 8, atol: float = 5e-3,
                            rtol: float = 1e-3,
                            margin: Optional[float] = None) -> None:
    """Teacher-forced golden comparison against a HF model (reference:
    utils/accuracy.py:478 logit-matching with divergence tolerance).

    Greedy token equality is brittle on tiny random-weight models: near-tie
    logits flip argmax under fp rounding and the comparison fails on a token
    that is numerically irrelevant. Instead:
      1. feed the HF greedy continuation back (teacher forcing) and require
         every step's logits to match the golden logits within atol/rtol;
      2. require token equality only at steps where the golden top-2 logit
         margin exceeds ``margin`` (default 20*atol) — i.e. where argmax is
         numerically decisive.
    """
    import torch
    b, s = ids.shape
    with torch.no_grad():
        hf_seq = hf_model.generate(torch.tensor(ids),
                                   max_new_tokens=max_new_tokens,
                                   do_sample=False).numpy()
        full = hf_model(torch.tensor(hf_seq)).logits.numpy()
    gen = hf_seq[:, s:]
    app.reset()
    res = app.generate(ids.astype(np.int32), max_new_tokens=max_new_tokens,
                       teacher_tokens=gen.astype(np.int32),
                       return_logits=True)
    logits = res["logits"]
    # prefill logits over the prompt positions
    np.testing.assert_allclose(np.asarray(logits[0])[:, :s], full[:, :s],
                               atol=atol, rtol=rtol,
                               err_msg="prefill logits diverge from golden")
    # decode step i fed gen[:, i-1] at position s+i-1 → golden full[:, s+i-1]
    for i in range(1, len(logits)):
        got = np.asarray(logits[i]).reshape(b, -1)
        np.testing.assert_allclose(
            got, full[:, s + i - 1], atol=atol, rtol=rtol,
            err_msg=f"decode logits diverge from golden at step {i}")
    if margin is None:
        margin = 20 * atol
    top2 = np.sort(full, axis=-1)[..., -2:]
    decisive = (top2[..., 1] - top2[..., 0]) > margin
    t = gen.shape[1]
    toks = res["generated"][:, :t]
    mism = (toks != gen) & decisive[:, s - 1:s - 1 + t]
    assert not mism.any(), (
        f"decisive-token mismatch at {np.argwhere(mism)}: "
        f"got {toks[mism]}, want {gen[mism]}")


def make_tiny_checkpoint(tmp_dir: str, model_type: str = "llama",
                         num_layers: int = 4, **config_over) -> str:
    """Save a tiny random-weight HF checkpoint (reference: the N-layer
    random checkpoint creation, modules/checkpoint.py:202-287, and the
    tiny integration configs of SURVEY §4)."""
    import torch
    import transformers
    cls_map = {
        "llama": (transformers.LlamaConfig, transformers.LlamaForCausalLM),
        "mistral": (transformers.MistralConfig,
                    transformers.MistralForCausalLM),
        "qwen2": (transformers.Qwen2Config, transformers.Qwen2ForCausalLM),
        "qwen3": (transformers.Qwen3Config, transformers.Qwen3ForCausalLM),
    }
    cfg_cls, model_cls = cls_map[model_type]
    kw = dict(hidden_size=64, intermediate_size=128,
              num_hidden_layers=num_layers, num_attention_heads=4,
              num_key_value_heads=2, vocab_size=512, rms_norm_eps=1e-5,
              max_position_embeddings=256, tie_word_embeddings=False,
              torch_dtype="float32")
    kw.update(config_over)
    torch.manual_seed(0)
    model = model_cls(cfg_cls(**kw))
    model.eval()
    model.save_pretrained(tmp_dir, safe_serialization=True)
    return tmp_dir
