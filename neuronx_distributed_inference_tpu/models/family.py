"""Model-family protocol (reference analog: the per-model contract described
in SURVEY §2.7 — each family provides ``get_required_attributes``,
``setup_attr_for_model``, ``init_model``, ``convert_hf_to_neuron_state_dict``,
``load_hf_model``).

A family here is a class with:
  * ``config_cls``            — InferenceConfig subclass
  * ``build_spec(config)``    — InferenceConfig -> DecoderSpec
  * ``convert_hf_state_dict`` — HF numpy state dict -> stacked TPU param tree
  * ``load_hf_model(path)``   — CPU torch model for golden accuracy checks
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Type

import numpy as np

from ..config import InferenceConfig
from ..parallel.layers import place_q_weight, replicate_kv_weight
from .model_base import DecoderSpec, spec_from_config

_REGISTRY: Dict[str, Type["DecoderFamily"]] = {}


def register_family(*names: str):
    def deco(cls):
        for n in names:
            _REGISTRY[n] = cls
        cls.family_names = names
        return cls
    return deco


def get_family(name: str) -> Type["DecoderFamily"]:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model family {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def family_for_config(config) -> Type["DecoderFamily"]:
    mt = getattr(config, "model_type", None)
    return get_family(mt)


class DecoderFamily:
    """Base implementation that covers the standard Llama-shaped decoder.
    Families override hooks for their deltas (bias, qk-norm, soft caps, MoE)."""

    family_names = ()
    config_cls: Type[InferenceConfig] = InferenceConfig
    hf_prefix = "model"
    spec_overrides: Dict[str, Any] = {}
    # HF weight name feeding the pre-MLP norm ("post_norm" in the spec);
    # sandwich-norm families (gemma3) point it at pre_feedforward_layernorm
    post_norm_src = "post_attention_layernorm"
    # HF weight name feeding the pre-attention norm (apertus uses
    # "attention_layernorm")
    input_norm_src = "input_layernorm"
    # HF attention output-projection module name (phi uses "dense")
    attn_o_src = "self_attn.o_proj"

    # -- spec --
    @classmethod
    def build_spec(cls, config: InferenceConfig, tp_degree: Optional[int] = None
                   ) -> DecoderSpec:
        return spec_from_config(config, tp_degree, **cls.spec_overrides)

    # -- weights --
    @classmethod
    def convert_hf_state_dict(cls, sd: Dict[str, np.ndarray], spec: DecoderSpec
                              ) -> Dict[str, Any]:
        """HF names/layouts -> stacked TPU tree
        (reference analog: convert_hf_to_neuron_state_dict per model).

        torch Linear stores (out, in); we store (in, out) so matmuls read
        x @ w. Q/K/V head padding + KV replication happen here at load time
        (reference: gqa.py preshard_hook :679+)."""
        p = cls.hf_prefix
        g = spec.gqa
        D = spec.head_dim

        def get(name):
            if name in sd:
                return np.asarray(sd[name])
            raise KeyError(f"missing checkpoint tensor {name}; have "
                           f"{sorted(k for k in sd)[:8]}...")

        def layer_stack(fmt, transform):
            return np.stack(
                [transform(get(fmt.format(i=i))) for i in range(spec.num_layers)])

        def q_t(w):  # (nq*D, H) -> (H, padded_q*D)
            return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

        def kv_t(w):
            return replicate_kv_weight(np.ascontiguousarray(w.T), g, D, axis=-1)

        def o_t(w):  # (H, nq*D) -> (padded_q*D, H): place on input axis
            return place_q_weight(np.ascontiguousarray(w.T), g, D, axis=0)

        def t(w):
            return np.ascontiguousarray(w.T)

        def ident(w):
            return np.asarray(w)

        layers = {
            "input_norm": layer_stack(
                p + ".layers.{i}." + cls.input_norm_src + ".weight", ident),
            "q_proj": layer_stack(p + ".layers.{i}.self_attn.q_proj.weight", q_t),
            "k_proj": layer_stack(p + ".layers.{i}.self_attn.k_proj.weight", kv_t),
            "v_proj": layer_stack(p + ".layers.{i}.self_attn.v_proj.weight", kv_t),
            "o_proj": layer_stack(p + ".layers.{i}." + cls.attn_o_src + ".weight", o_t),
            "post_norm": layer_stack(
                p + ".layers.{i}." + cls.post_norm_src + ".weight", ident),
        }
        layers.update(cls.convert_mlp_weights(get, layer_stack, spec))
        layers.update(cls.convert_extra_layer_weights(get, layer_stack, spec))
        if spec.qkv_bias:
            def q_b(b):
                return place_q_weight(b, g, D)

            def kv_b(b):
                return replicate_kv_weight(b, g, D)

            layers["q_bias"] = layer_stack(p + ".layers.{i}.self_attn.q_proj.bias", q_b)
            layers["k_bias"] = layer_stack(p + ".layers.{i}.self_attn.k_proj.bias", kv_b)
            layers["v_bias"] = layer_stack(p + ".layers.{i}.self_attn.v_proj.bias", kv_b)
        if spec.o_bias:
            layers["o_bias"] = layer_stack(
                p + ".layers.{i}." + cls.attn_o_src + ".bias", ident)
        if spec.qk_norm:
            layers["q_norm"] = layer_stack(p + ".layers.{i}.self_attn.q_norm.weight", ident)
            layers["k_norm"] = layer_stack(p + ".layers.{i}.self_attn.k_norm.weight", ident)

        def vpad(w):  # pad vocab rows to padded_vocab
            if w.shape[0] < spec.padded_vocab:
                w = np.pad(w, [(0, spec.padded_vocab - w.shape[0])] +
                           [(0, 0)] * (w.ndim - 1))
            return w

        out = {
            "embed": vpad(get(p + ".embed_tokens.weight")),
            "layers": layers,
            "final_norm": get(p + ".norm.weight"),
        }
        if not spec.tie_word_embeddings:
            out["lm_head"] = np.ascontiguousarray(vpad(get("lm_head.weight")).T)
        return out

    # -- extra per-layer weights hook (sandwich norms, sinks, …) --
    @classmethod
    def convert_extra_layer_weights(cls, get, layer_stack, spec: DecoderSpec
                                    ) -> Dict[str, np.ndarray]:
        return {}

    # -- MLP / MoE weight conversion hook --
    @classmethod
    def convert_mlp_weights(cls, get, layer_stack, spec: DecoderSpec
                            ) -> Dict[str, np.ndarray]:
        """Dense gate/up/down by default; MoE families override
        (reference analog: per-model convert_hf_to_neuron_state_dict MoE
        branches, e.g. mixtral/dbrx)."""
        p = cls.hf_prefix

        def t(w):
            return np.ascontiguousarray(w.T)

        return {
            "gate_proj": layer_stack(p + ".layers.{i}.mlp.gate_proj.weight", t),
            "up_proj": layer_stack(p + ".layers.{i}.mlp.up_proj.weight", t),
            "down_proj": layer_stack(p + ".layers.{i}.mlp.down_proj.weight", t),
        }

    @classmethod
    def convert_moe_weights(cls, get, spec: DecoderSpec, router_name: str,
                            expert_fmt: str, gate: str, up: str, down: str
                            ) -> Dict[str, np.ndarray]:
        """Shared MoE conversion: stack per-layer routers (fp32, transposed to
        (H,E)) and per-layer-per-expert projections to (L,E,in,out). Name
        templates use {i} (layer), {e} (expert), {name} (projection)."""
        L, E = spec.num_layers, spec.moe.num_experts

        def experts(name):
            return np.stack([
                np.stack([np.ascontiguousarray(np.asarray(get(
                    expert_fmt.format(i=i, e=e, name=name))).T)
                    for e in range(E)]) for i in range(L)])

        return {
            "router": np.stack([np.ascontiguousarray(np.asarray(get(
                router_name.format(i=i))).T.astype(np.float32))
                for i in range(L)]),
            "expert_gate": experts(gate),
            "expert_up": experts(up),
            "expert_down": experts(down),
        }

    # -- golden --
    @classmethod
    def load_hf_model(cls, model_path: str):
        """CPU torch model for golden logit generation
        (reference: each model's load_hf_model; utils/accuracy.py golden flow)."""
        import transformers
        return transformers.AutoModelForCausalLM.from_pretrained(model_path)
