"""Janus (DeepSeek multimodal) — image UNDERSTANDING path: SigLIP-style
vision encoder + MLP aligner + llama text stack (reference:
contrib/models/Janus-1.3B). The VQ image-GENERATION head (vqmodel +
generation_* weights) is out of scope — understanding is what the serving
surface needs; the app raises loudly if asked to generate pixels."""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import InferenceConfig
from ..utils import checkpoint as ckpt
from . import vision
from .application import CausalLMApplication
from .family import get_family


class JanusInferenceConfig(InferenceConfig):
    def get_required_attributes(self) -> List[str]:
        return ["text_config", "vision_config", "image_token_id"]

    def get_text_config(self) -> InferenceConfig:
        tc = dict(self.text_config)
        family = get_family(tc.get("model_type", "llama"))
        return family.config_cls(self.tpu_config, **tc)


class JanusApplication:
    """Vision encoder + aligner + llama LM (understanding path)."""

    def __init__(self, model_path: Optional[str],
                 config: JanusInferenceConfig, mesh=None):
        self.config = config
        self.tpu_config = config.tpu_config
        self.model_path = model_path
        self.text = CausalLMApplication(model_path, config.get_text_config(),
                                        mesh=mesh)
        vc = dict(config.vision_config)
        self.vit_spec = vision.VitSpec(
            hidden_size=int(vc["hidden_size"]),
            num_layers=int(vc["num_hidden_layers"]),
            num_heads=int(vc["num_attention_heads"]),
            intermediate_size=int(vc.get(
                "intermediate_size",
                vc["hidden_size"] * float(vc.get("mlp_ratio", 4.0)))),
            patch_size=int(vc["patch_size"]),
            image_size=int(vc["image_size"]),
            num_channels=int(vc.get("num_channels", 3)),
            use_cls_token=False, pre_layernorm=False, patch_bias=True,
            post_layernorm=True,
            act=vc.get("hidden_act", "gelu"),
            eps=float(vc.get("layer_norm_eps", 1e-6)),
            feature_layer=-1)
        self.image_token_id = int(config.image_token_id)
        self.vision_params = None
        self.aligner = None
        self._vit = jax.jit(partial(vision.vit_forward, self.vit_spec))
        self._align = jax.jit(self._align_fn)

    def load_weights(self):
        sd = ckpt.load_state_dict(self.model_path)
        text_sd = {}
        for k, v in sd.items():
            if k.endswith("lm_head.weight"):
                text_sd["lm_head.weight"] = v
            elif k.startswith("model.language_model."):
                text_sd["model." + k[len("model.language_model."):]] = v
        self.text.params = None
        host = self.text.family.convert_hf_state_dict(text_sd, self.text.spec)
        self.text._put_params(host)
        self.vision_params = jax.tree.map(
            jnp.asarray, vision.convert_clip_vision_tower(
                sd, self.vit_spec, "model.vision_model",
                o_proj_name="projection_layer", bare_prefix=True))

        def t(w):
            return jnp.asarray(np.ascontiguousarray(
                np.asarray(w, np.float32).T))

        hidden = []
        i = 0
        while f"model.aligner.hidden_layers.{i}.weight" in sd:
            hidden.append(
                (t(sd[f"model.aligner.hidden_layers.{i}.weight"]),
                 jnp.asarray(np.asarray(
                     sd[f"model.aligner.hidden_layers.{i}.bias"],
                     np.float32))))
            i += 1
        self.aligner = {
            "fc1_w": t(sd["model.aligner.fc1.weight"]),
            "fc1_b": jnp.asarray(np.asarray(sd["model.aligner.fc1.bias"],
                                            np.float32)),
            "hidden": hidden,
        }
        return self

    def init_cache(self):
        self.text.init_cache()
        return self

    def _align_fn(self, aligner, feats):
        """HF JanusVisionAlignerMLP: fc1 then GELU->linear per hidden layer."""
        h = feats @ aligner["fc1_w"] + aligner["fc1_b"]
        for w, b in aligner["hidden"]:
            h = jax.nn.gelu(h, approximate=False) @ w + b
        return h

    def encode_images(self, pixel_values: np.ndarray) -> jnp.ndarray:
        feats = self._vit(self.vision_params, jnp.asarray(pixel_values))
        return self._align(self.aligner, feats)

    def generate(self, input_ids: np.ndarray, pixel_values: np.ndarray,
                 attention_mask: Optional[np.ndarray] = None,
                 max_new_tokens: int = 32, **kw) -> Dict[str, Any]:
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        image_mask = (input_ids == self.image_token_id)
        feats = np.asarray(self.encode_images(pixel_values))
        per_row = image_mask.sum(axis=1)
        if not (per_row == per_row[0]).all():
            raise ValueError("rows must hold equal image-token counts")
        n_patch = feats.shape[0] * feats.shape[1] // b
        if per_row[0] != n_patch:
            raise ValueError(
                f"prompt holds {per_row[0]} image tokens per row but the "
                f"encoder emitted {n_patch} patch features per row")
        image_embeds = feats.reshape(b, per_row[0], -1)
        if self.text.cache is None:
            self.text.init_cache()
        return self.text.generate(
            input_ids, attention_mask=attention_mask,
            max_new_tokens=max_new_tokens,
            image_embeds=image_embeds, image_mask=image_mask, **kw)

    def generate_images(self, *a, **k):
        raise NotImplementedError(
            "Janus VQ image generation (vqmodel + generation_head) is not "
            "implemented; only the understanding path is supported")

    def reset(self):
        self.text.reset()
        return self
